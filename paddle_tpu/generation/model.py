"""Program builders for the generation subsystem.

The GenerationEngine drives TWO Program-IR executables against the
predictor's scope (same parameter names as models/gpt.py, so the
weights a saved LM was trained/exported with serve both lanes):

* ``build_prefill_program(cfg, seq_len, geom)`` — full causal forward
  over a [B, S] prompt window (flash attention when the config asks
  for it), PLUS per-layer ``kv_cache_write`` of the prompt's K/V into
  the page pool, PLUS in-graph last-token selection and greedy argmax.
  One executable per (batch-bucket, seq-bucket) pair.
* ``build_decode_program(cfg, geom)`` — ONE token per sequence: embed,
  per layer (ln -> fused qkv -> kv_cache_write of the new row ->
  ``paged_attention`` over the updated pool -> proj/ffn), head matmul,
  in-graph argmax. The batch dim is the engine's fixed decode-lane
  count, so the whole continuous-batching life of the engine is ONE
  compiled executable driven through the PR-2 BoundStep cache.

``build_lm_program(cfg, seq_len)`` is the loss-free LM used to export
an inference model for the Predictor (build_gpt_lm always wires a CE
loss, which would drag a labels feed into serving).

``build_ragged_step_program(cfg, geom, chunk, kv_dtype)`` is the
tentpole successor to the pair above: ONE [lanes, chunk] executable
whose rows are whatever each sequence needs this step — a prefill
chunk, a decode token, a decode token + speculative drafts, or an
idle lane — through ``kernels/ragged_paged_attention``. The engine's
"ragged" mode (the default) runs its whole life through it; the
prefill/decode pair remains for mode="two_lane" (the identity
oracle).

Feed-name contract (the engine assembles these every step):
  gen_tokens       [B, S] / [B, 1] / [B, chunk] int64
  gen_pos_ids      [B, chunk] int64  ragged only: absolute position
                               ids of each chunk token (row start + j)
  gen_positions    [B] int64   absolute position of each new row
                               (prefill: 0; decode: current length;
                               ragged: the row's chunk start)
  gen_num_valid    [B] int32   real rows in this window (prefill: the
                               true prompt length; decode: 1 active /
                               0 idle lane; ragged: chunk tokens)
  gen_attend_lens  [B] int32   decode only: tokens to attend over
                               (= position + 1)
  gen_last_index   [B] int64   prefill only: index of the true last
                               prompt token (length - 1)
  gen_block_tables [B, max_pages_per_seq] int32
  gen_k_pages_{l} / gen_v_pages_{l}   the per-layer page pools
  gen_k_scales_{l} / gen_v_scales_{l} int8 pools only: fp32 scale
                               planes [kv_heads, pages, page_size]
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .. import layers, nets
from ..core.framework import Program, program_guard, unique_name
from ..models.gpt import GPTConfig, _attr
from ..param_attr import ParamAttr

__all__ = ["CacheGeometry", "build_lm_program", "build_prefill_program",
           "build_decode_program", "build_ragged_step_program", "GPTConfig"]


@dataclasses.dataclass(frozen=True)
class CacheGeometry:
    """The page-pool shape both programs compile against."""
    num_pages: int
    page_size: int
    max_pages_per_seq: int

    @property
    def max_tokens_per_seq(self) -> int:
        return self.max_pages_per_seq * self.page_size


def _page_feeds(cfg: GPTConfig, geom: CacheGeometry, dtype: str = "float32"):
    kvh = cfg.num_heads
    d = cfg.hidden_size // cfg.num_heads
    shape = [kvh, geom.num_pages, geom.page_size, d]
    kps = [layers.data(f"gen_k_pages_{i}", shape, append_batch_size=False,
                       dtype=dtype)
           for i in range(cfg.num_layers)]
    vps = [layers.data(f"gen_v_pages_{i}", shape, append_batch_size=False,
                       dtype=dtype)
           for i in range(cfg.num_layers)]
    return kps, vps


def _scale_feeds(cfg: GPTConfig, geom: CacheGeometry):
    kvh = cfg.num_heads
    shape = [kvh, geom.num_pages, geom.page_size]
    kss = [layers.data(f"gen_k_scales_{i}", shape, append_batch_size=False)
           for i in range(cfg.num_layers)]
    vss = [layers.data(f"gen_v_scales_{i}", shape, append_batch_size=False)
           for i in range(cfg.num_layers)]
    return kss, vss


def _ln(x, name):
    return layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=ParamAttr(name=f"{name}.scale"),
        bias_attr=ParamAttr(name=f"{name}.bias"))


def _qkv_split(x, cfg: GPTConfig, pre: str):
    qkv = layers.fc(
        x, 3 * cfg.hidden_size, num_flatten_dims=2,
        param_attr=_attr(f"{pre}_qkv.w", cfg.initializer_range),
        bias_attr=ParamAttr(name=f"{pre}_qkv.b"))
    return layers.split(qkv, 3, dim=2)


def _proj_ffn(x, ctx, cfg: GPTConfig, pre: str):
    """Post-attention half of the decoder layer (shared verbatim by
    both lanes so prefill and decode numerics can only diverge in the
    attention read itself)."""
    h, std = cfg.hidden_size, cfg.initializer_range
    proj = layers.fc(
        ctx, h, num_flatten_dims=2,
        param_attr=_attr(f"{pre}_proj.w", std),
        bias_attr=ParamAttr(name=f"{pre}_proj.b"))
    x = layers.elementwise_add(x, proj)
    ln2 = _ln(x, f"{pre}_ln2")
    ffn1 = layers.fc(
        ln2, cfg.ffn_size, num_flatten_dims=2, act="gelu",
        param_attr=_attr(f"{pre}_ffn1.w", std),
        bias_attr=ParamAttr(name=f"{pre}_ffn1.b"))
    ffn2 = layers.fc(
        ffn1, h, num_flatten_dims=2,
        param_attr=_attr(f"{pre}_ffn2.w", std),
        bias_attr=ParamAttr(name=f"{pre}_ffn2.b"))
    return layers.elementwise_add(x, ffn2)


def _head(x, cfg: GPTConfig):
    x = _ln(x, "gpt_lnf")
    return layers.fc(
        x, cfg.vocab_size, num_flatten_dims=2,
        param_attr=_attr("gpt_head.w", cfg.initializer_range),
        bias_attr=ParamAttr(name="gpt_head.b"))


def _embed(tokens, cfg: GPTConfig):
    return layers.embedding(
        tokens, size=[cfg.vocab_size, cfg.hidden_size],
        param_attr=_attr("gpt_tok_emb", cfg.initializer_range))


def _pos_embed(ids, cfg: GPTConfig):
    return layers.embedding(
        ids, size=[cfg.max_position, cfg.hidden_size],
        param_attr=_attr("gpt_pos_emb", cfg.initializer_range))


def build_lm_program(cfg: GPTConfig, seq_len: int):
    """Loss-free causal LM: tokens [B, S] -> logits [B, S, V]. The
    exportable inference twin of models/gpt.build_gpt_lm (which always
    appends a CE loss and therefore a labels feed)."""
    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        tokens = layers.data("tokens", [seq_len], dtype="int64")
        x = layers.elementwise_add(
            _embed(tokens, cfg),
            _pos_embed(layers.assign(
                np.arange(seq_len, dtype="int64")[None, :]), cfg))
        for i in range(cfg.num_layers):
            pre = f"dec{i}"
            ln1 = _ln(x, f"{pre}_ln1")
            q, k, v = _qkv_split(ln1, cfg, pre)
            if cfg.use_flash_attention:
                from ..kernels import flash_attention_layer

                ctx = flash_attention_layer(q, k, v, cfg.num_heads,
                                            causal=True)
            else:
                ctx = nets.scaled_dot_product_attention(
                    q, k, v, num_heads=cfg.num_heads, causal=True)
            x = _proj_ffn(x, ctx, cfg, pre)
        logits = _head(x, cfg)
    return main, startup, {"tokens": tokens}, {"logits": logits}


def build_prefill_program(cfg: GPTConfig, seq_len: int, geom: CacheGeometry):
    """Prefill lane: forward the prompt window, write its K/V into the
    page pool, emit the first greedy token per row — all one
    executable. Returns (program, fetch_vars) where fetch order is
    [next_token, k_pages_0.., v_pages_0..]."""
    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        tokens = layers.data("gen_tokens", [seq_len], dtype="int64")
        positions = layers.data("gen_positions", [], dtype="int64")
        num_valid = layers.data("gen_num_valid", [], dtype="int32")
        last_index = layers.data("gen_last_index", [], dtype="int64")
        tables = layers.data("gen_block_tables", [geom.max_pages_per_seq],
                             dtype="int32")
        kps, vps = _page_feeds(cfg, geom)
        from ..kernels import kv_cache_write_layer

        x = layers.elementwise_add(
            _embed(tokens, cfg),
            _pos_embed(layers.assign(
                np.arange(seq_len, dtype="int64")[None, :]), cfg))
        out_pages = []
        for i in range(cfg.num_layers):
            pre = f"dec{i}"
            ln1 = _ln(x, f"{pre}_ln1")
            q, k, v = _qkv_split(ln1, cfg, pre)
            ko, vo = kv_cache_write_layer(
                kps[i], vps[i], k, v, tables, positions, num_valid,
                cfg.num_heads)
            out_pages.append((ko, vo))
            if cfg.use_flash_attention:
                from ..kernels import flash_attention_layer

                ctx = flash_attention_layer(q, k, v, cfg.num_heads,
                                            causal=True)
            else:
                ctx = nets.scaled_dot_product_attention(
                    q, k, v, num_heads=cfg.num_heads, causal=True)
            x = _proj_ffn(x, ctx, cfg, pre)
        logits = _head(x, cfg)                      # [B, S, V]
        # in-graph last-token selection: one_hot(last_index) row-dots
        # the logits so the [B, S, V] tensor never leaves the device
        sel = layers.one_hot(layers.unsqueeze(last_index, [1]), seq_len)
        last_logits = layers.reduce_sum(
            layers.elementwise_mul(logits, layers.unsqueeze(sel, [2])),
            dim=[1])                                # [B, V]
        next_tok = layers.argmax(last_logits, axis=-1)   # [B]
    fetches = [next_tok] + [p[0] for p in out_pages] + \
        [p[1] for p in out_pages]
    return main, fetches


def build_ragged_step_program(cfg: GPTConfig, geom: CacheGeometry,
                              chunk: int, kv_dtype: str = "float32"):
    """THE ragged executable: one [lanes, chunk] mixed batch serves
    prefill chunks, decode rows and speculative-verify rows side by
    side — the whole GenerationEngine life is this ONE program bound
    to ONE BoundStep.

    Per row r the engine feeds up to ``chunk`` NEW tokens starting at
    absolute position gen_positions[r] (gen_num_valid[r] of them are
    real; 0 = idle lane). Each layer scatters the chunk's K/V into the
    page pool (int8-quantized when ``kv_dtype == "int8"``), then
    ragged_paged_attention attends every chunk token over its
    sequence's full prefix through the block tables. The head runs
    over ALL chunk positions and argmax is fetched for every position
    — the engine reads the last valid column for plain rows and every
    column for speculative verification (greedy target tokens at each
    draft offset).

    Returns (program, fetches) with fetch order
    [next_tokens(R*C), k_pages.., v_pages.. (, k_scales.., v_scales..)].
    """
    quantized = kv_dtype == "int8"
    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        tokens = layers.data("gen_tokens", [chunk], dtype="int64")
        pos_ids = layers.data("gen_pos_ids", [chunk], dtype="int64")
        positions = layers.data("gen_positions", [], dtype="int64")
        num_valid = layers.data("gen_num_valid", [], dtype="int32")
        tables = layers.data("gen_block_tables", [geom.max_pages_per_seq],
                             dtype="int32")
        kps, vps = _page_feeds(cfg, geom,
                               "int8" if quantized else "float32")
        kss = vss = [None] * cfg.num_layers
        if quantized:
            kss, vss = _scale_feeds(cfg, geom)
        from ..kernels import (kv_cache_write_layer,
                               quantized_kv_cache_write_layer,
                               ragged_paged_attention_layer)

        x = layers.elementwise_add(_embed(tokens, cfg),
                                   _pos_embed(pos_ids, cfg))   # [R, C, H]
        out_pages = []
        for i in range(cfg.num_layers):
            pre = f"dec{i}"
            ln1 = _ln(x, f"{pre}_ln1")
            q, k, v = _qkv_split(ln1, cfg, pre)
            if quantized:
                ko, vo, kso, vso = quantized_kv_cache_write_layer(
                    kps[i], vps[i], kss[i], vss[i], k, v, tables,
                    positions, num_valid, cfg.num_heads)
            else:
                ko, vo = kv_cache_write_layer(
                    kps[i], vps[i], k, v, tables, positions, num_valid,
                    cfg.num_heads)
                kso = vso = None
            out_pages.append((ko, vo, kso, vso))
            ctx = ragged_paged_attention_layer(
                q, ko, vo, tables, positions, num_valid, cfg.num_heads,
                k_scales_var=kso, v_scales_var=vso)
            x = _proj_ffn(x, ctx, cfg, pre)
        logits = _head(x, cfg)                      # [R, C, V]
        next_tok = layers.argmax(
            layers.reshape(logits, [-1, cfg.vocab_size]), axis=-1)  # [R*C]
    fetches = ([next_tok] + [p[0] for p in out_pages]
               + [p[1] for p in out_pages])
    if quantized:
        fetches += [p[2] for p in out_pages] + [p[3] for p in out_pages]
    return main, fetches


def build_decode_program(cfg: GPTConfig, geom: CacheGeometry):
    """Decode lane: one new token per sequence through the paged
    cache. Fetch order matches prefill: [next_token, k_pages..,
    v_pages..]."""
    main, startup = Program(), Program()
    with program_guard(main, startup), unique_name.guard():
        tokens = layers.data("gen_tokens", [1], dtype="int64")
        positions = layers.data("gen_positions", [], dtype="int64")
        num_valid = layers.data("gen_num_valid", [], dtype="int32")
        attend = layers.data("gen_attend_lens", [], dtype="int32")
        tables = layers.data("gen_block_tables", [geom.max_pages_per_seq],
                             dtype="int32")
        kps, vps = _page_feeds(cfg, geom)
        from ..kernels import kv_cache_write_layer, paged_attention_layer

        x = layers.elementwise_add(
            layers.unsqueeze(_embed(tokens, cfg), [1]),
            layers.unsqueeze(_pos_embed(positions, cfg), [1]))  # [B, 1, H]
        out_pages = []
        for i in range(cfg.num_layers):
            pre = f"dec{i}"
            ln1 = _ln(x, f"{pre}_ln1")
            q, k, v = _qkv_split(ln1, cfg, pre)
            ko, vo = kv_cache_write_layer(
                kps[i], vps[i], k, v, tables, positions, num_valid,
                cfg.num_heads)
            out_pages.append((ko, vo))
            ctx = paged_attention_layer(q, ko, vo, tables, attend,
                                        cfg.num_heads)
            x = _proj_ffn(x, ctx, cfg, pre)
        logits = _head(x, cfg)                      # [B, 1, V]
        next_tok = layers.argmax(
            layers.reshape(logits, [-1, cfg.vocab_size]), axis=-1)  # [B]
    fetches = [next_tok] + [p[0] for p in out_pages] + \
        [p[1] for p in out_pages]
    return main, fetches

"""PagedKVCache: the page pool + block tables behind continuous
batching.

Design (Ragged Paged Attention, arXiv:2604.15464): K/V live in
fixed-size pages inside ONE preallocated device buffer per layer;
each sequence owns a block table (ordered list of page ids) and a true
length. Growing a sequence by one token never reallocates — at worst
it pops one page off the free list. Completion returns the pages in
O(pages). The pool is sized once (``num_pages * page_size`` token
slots) so device memory is a configuration decision, not a runtime
surprise — exactly the property serving under heavy traffic needs.

This class is the HOST-side manager: block tables, lengths, the free
list, slot assignment, admission accounting. The device-side page
buffers (jax arrays, [num_kv_heads, num_pages, page_size, head_dim]
per layer) are held here too, but they are only ever *mutated* inside
the compiled prefill/decode steps (kernels/paged_attention.py
``kv_cache_write``) — the engine fetches the functionally-updated
pools and swaps them back via ``set_buffers``. All bookkeeping methods
are called from the engine's single step loop; the lock protects the
metric/probe reader paths (``stats()`` / ``match_len`` from scrape and
traffic threads).

Page 0 is permanently reserved as the JUNK page: idle decode lanes and
batch-padding rows point their tables at it, so their (discarded)
writes can never corrupt a live sequence.

``dtype="int8"`` selects the QUANTIZED pool (ragged engine only): K/V
pages store blockwise-int8 values plus one fp32 scale per
(head, token slot) — the kernels/quant.py block unit with
block = head_dim. A page then costs ~1/3.6 the fp32 bytes
(``page_bytes``), so the same HBM budget holds ~3.6x the pages and
~2x+ the resident sequences — the capacity multiplier
tools/generation_bench.py --int8 gates.

**Radix prefix cache** (``prefix_cache=True``, ragged engine only):
every page carries a REFCOUNT, and full (page-aligned) token runs are
published into a prefix TRIE keyed by the exact page_size-token tuple
each page holds. ``acquire(prompt_tokens)`` walks the trie and
attaches the matched prefix pages to the new sequence's block table BY
REFERENCE — the shared prompt prefills once, ever — while the
unmatched suffix gets private pages (copy-on-write is structural: the
engine only ever writes positions >= the sequence length, and growth
always pops FRESH pages, so a full shared page is immutable by
construction). ``release`` decrements and returns a page to the free
list only at refcount zero; pool pressure evicts trie-only leaves
first, LRU, before admission ever backpressures or a live sequence is
preempted. Since int8 scale planes ride the same page indirection,
a shared page is also a shared quantized page — the two capacity
multipliers compose.

Exhaustion is backpressure, not corruption: ``acquire`` /
``allocate_slot`` / ``ensure_capacity`` raise ``PagePoolExhausted``;
the engine responds by delaying admission (queued requests wait for
pages) or by evicting a victim sequence (whose request is re-queued
for re-prefill — greedy decode makes the recomputed continuation
identical).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PagedKVCache", "PagePoolExhausted"]


class PagePoolExhausted(RuntimeError):
    """No free pages for the requested growth — admission backpressure
    (or eviction) must resolve it; never an allocation."""


_SCATTER_JIT = []


def _scatter_pages(bufs, sel, blks):
    """Write page blocks into pool buffers as ONE jitted call: the
    ingest path (disagg page splice) touches 2-4 buffers per layer,
    and un-jitted per-buffer ``at[].set`` dispatch costs multiples of
    a decode step. jax.jit caches per pytree shape, so the
    power-of-two padding upstream bounds the executable set."""
    if not _SCATTER_JIT:
        import jax

        def _run(bufs, sel, blks):
            return [b.at[:, sel].set(x.astype(b.dtype))
                    for b, x in zip(bufs, blks)]

        _SCATTER_JIT.append(jax.jit(_run))
    return _SCATTER_JIT[0](bufs, sel, blks)


class _TrieNode:
    """One published page: ``key`` is the exact page_size-token tuple
    the page holds, ``page`` the pool page id. Children extend the
    token run by one more full page. ``last_used`` is a monotonic tick
    (NOT wall time — deterministic LRU under test). ``tenant`` is the
    traffic-tier identity that published the page — the per-tenant
    trie-quota accounting unit."""

    __slots__ = ("key", "page", "parent", "children", "last_used",
                 "tenant")

    def __init__(self, key, page, parent, tenant="default"):
        self.key = key
        self.page = page
        self.parent = parent
        self.children: Dict[tuple, "_TrieNode"] = {}
        self.last_used = 0
        self.tenant = tenant


class PagedKVCache:
    def __init__(self, num_layers: int, num_kv_heads: int, head_dim: int, *,
                 num_pages: int, page_size: int, max_seqs: int,
                 max_pages_per_seq: int, dtype: str = "float32",
                 prefix_cache: bool = False, prefix_min_pages: int = 1,
                 trie_max_pages: int = 0, tenant_quota_pages: int = 0):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        if page_size < 1 or max_seqs < 1 or max_pages_per_seq < 1:
            raise ValueError("page_size/max_seqs/max_pages_per_seq >= 1")
        self.num_layers = int(num_layers)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_seqs = int(max_seqs)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.dtype = dtype
        self.quantized = dtype == "int8"
        self.prefix_cache = bool(prefix_cache)
        self.prefix_min_pages = max(1, int(prefix_min_pages))
        self.trie_max_pages = max(0, int(trie_max_pages))
        self.tenant_quota_pages = max(0, int(tenant_quota_pages))
        self._lock = threading.Lock()
        # device pools, one K + one V per layer (lazy: first access
        # allocates, so constructing a cache in a test costs nothing);
        # int8 pools carry fp32 scale planes [KVH, P, ps] alongside
        self._k_pages: Optional[List[Any]] = None
        self._v_pages: Optional[List[Any]] = None
        self._k_scales: Optional[List[Any]] = None
        self._v_scales: Optional[List[Any]] = None
        # host bookkeeping
        self.block_tables = np.zeros((max_seqs, max_pages_per_seq), np.int32)
        self.lengths = np.zeros(max_seqs, np.int32)
        self._pages_of: List[List[int]] = [[] for _ in range(max_seqs)]
        self._active = [False] * max_seqs
        # page 0 = junk page, never on the free list
        self._free = list(range(num_pages - 1, 0, -1))
        # refcounts: one per sequence chain holding the page, plus one
        # if the page is trie-resident; a page returns to the free
        # list only at zero
        self._ref = np.zeros(num_pages, np.int64)
        # the prefix trie (radix cache): root holds no page; each
        # child edge is one full page keyed by its exact token tuple
        self._root = _TrieNode(None, None, None)
        self._node_of_page: Dict[int, _TrieNode] = {}
        self._tick = 0
        # per-slot publish cursor: how many leading chain pages are
        # trie-resident, and the node at that depth (walks resume
        # there instead of re-keying from the root every step)
        self._published_of = [0] * max_seqs
        self._pub_node: List[Optional[_TrieNode]] = [None] * max_seqs
        # a sibling published the same token run onto a DIFFERENT page
        # first — this chain stays private from that depth on
        self._pub_dead = [False] * max_seqs
        self.evictions_total = 0
        self.allocations_total = 0
        # radix counters (radix_stats -> paddle_generation_radix_*)
        self.prefix_lookups_total = 0
        self.prefix_hits_total = 0
        self.prefix_hit_tokens_total = 0
        self.prefix_requested_tokens_total = 0
        self.cow_forks_total = 0
        self.leaf_evictions_total = 0
        self.published_pages_total = 0
        # disagg splice counters (ingest = pulled from a page store,
        # exported = read back out for spill/streaming)
        self.ingested_pages_total = 0
        self.exported_pages_total = 0
        # per-tenant trie accounting: pages currently resident, leaf
        # evictions forced by the tenant's own quota, and publishes
        # refused because the quota held and nothing was evictable
        self._tenant_pages: Dict[str, int] = {}
        self._tenant_evictions: Dict[str, int] = {}
        self.tenant_quota_rejections_total = 0

    # -- device buffers ------------------------------------------------------
    def _ensure_buffers(self):
        if self._k_pages is None:
            import jax.numpy as jnp

            shape = (self.num_kv_heads, self.num_pages, self.page_size,
                     self.head_dim)
            self._k_pages = [jnp.zeros(shape, self.dtype)
                             for _ in range(self.num_layers)]
            self._v_pages = [jnp.zeros(shape, self.dtype)
                             for _ in range(self.num_layers)]
            if self.quantized:
                # scale 1.0 everywhere: a junk/unwritten slot
                # dequantizes to 0.0, never to NaN/garbage
                sshape = shape[:3]
                self._k_scales = [jnp.ones(sshape, "float32")
                                  for _ in range(self.num_layers)]
                self._v_scales = [jnp.ones(sshape, "float32")
                                  for _ in range(self.num_layers)]

    @property
    def k_pages(self) -> List[Any]:
        self._ensure_buffers()
        return self._k_pages

    @property
    def v_pages(self) -> List[Any]:
        self._ensure_buffers()
        return self._v_pages

    @property
    def k_scales(self) -> List[Any]:
        self._ensure_buffers()
        return self._k_scales

    @property
    def v_scales(self) -> List[Any]:
        self._ensure_buffers()
        return self._v_scales

    def set_buffers(self, k_pages: List[Any], v_pages: List[Any],
                    k_scales: Optional[List[Any]] = None,
                    v_scales: Optional[List[Any]] = None) -> None:
        """Swap in the functionally-updated pools fetched from a
        prefill/decode/ragged step (scale planes too for the int8
        pool)."""
        if len(k_pages) != self.num_layers or len(v_pages) != self.num_layers:
            raise ValueError("set_buffers: wrong layer count")
        self._k_pages = list(k_pages)
        self._v_pages = list(v_pages)
        if self.quantized:
            if k_scales is None or v_scales is None:
                raise ValueError("set_buffers: int8 pool needs scale planes")
            self._k_scales = list(k_scales)
            self._v_scales = list(v_scales)

    @staticmethod
    def page_bytes(num_kv_heads: int, head_dim: int, page_size: int,
                   dtype: str) -> int:
        """HBM bytes ONE page costs per layer (K + V, scale planes
        included for int8) — the capacity arithmetic the int8 bench
        gates its ~2x-resident-sequences claim on."""
        slots = num_kv_heads * page_size
        if dtype == "int8":
            return 2 * (slots * head_dim + 4 * slots)   # int8 body + scales
        import numpy as np

        item = 2 if dtype == "bfloat16" else np.dtype(dtype).itemsize
        return 2 * slots * head_dim * item

    def pool_bytes(self) -> int:
        """Total device bytes of the page pools across layers."""
        return (self.num_layers * self.num_pages
                * self.page_bytes(self.num_kv_heads, self.head_dim,
                                  self.page_size, self.dtype))

    # -- capacity accounting -------------------------------------------------
    def pages_needed(self, n_tokens: int) -> int:
        return -(-max(int(n_tokens), 0) // self.page_size)

    @property
    def usable_pages(self) -> int:
        """Pool capacity available to sequences (junk page excluded)."""
        return self.num_pages - 1

    def free_pages(self) -> int:
        return len(self._free)

    def can_fit_ever(self, n_tokens: int) -> bool:
        """Could a sequence of n_tokens EVER be served by this pool —
        the admission-time sanity check (Overloaded before prefill)."""
        need = self.pages_needed(n_tokens)
        return (need <= self.usable_pages
                and need <= self.max_pages_per_seq
                and n_tokens <= self.max_pages_per_seq * self.page_size)

    def can_allocate(self, n_tokens: int) -> bool:
        return self.pages_needed(n_tokens) <= len(self._free)

    def can_acquire(self, n_tokens: int, prompt=None) -> bool:
        """can_allocate, but counting trie-only pages the allocator
        may legally reclaim (LRU leaf eviction) on top of the free
        list — the admission check under a warm radix cache.

        With ``prompt`` given, trie-only pages on the prompt's OWN
        match path are excluded from the budget: ``acquire`` ATTACHES
        them (refcount 2, no longer evictable) while still popping
        ``n_tokens`` worth of suffix pages, so counting them as
        reclaimable-for-the-suffix double-books exactly the pages a
        store-ingested run just inserted and admits requests the pool
        cannot serve."""
        with self._lock:
            excl = set()
            if prompt is not None:
                excl = {nd.page for nd in self._match_nodes(prompt)
                        if int(self._ref[nd.page]) == 1}
            budget = len(self._free) + sum(
                1 for p in self._node_of_page
                if int(self._ref[p]) == 1 and p not in excl)
        return self.pages_needed(n_tokens) <= budget

    def free_slots(self) -> int:
        return sum(1 for a in self._active if not a)

    # -- the prefix trie (radix cache) ---------------------------------------
    def _touch(self, node: _TrieNode) -> None:
        self._tick += 1
        node.last_used = self._tick

    def _page_key(self, tokens, i: int) -> tuple:
        ps = self.page_size
        return tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])

    def _match_nodes(self, tokens) -> List[_TrieNode]:
        """Trie path for the longest page-aligned prefix of
        ``tokens``, capped so at least one prompt token is left to
        prefill (the step that samples the first output token), and
        floored at prefix_min_pages (shorter matches are not worth the
        shared-page bookkeeping)."""
        if not self.prefix_cache:
            return []
        cap = (len(tokens) - 1) // self.page_size
        nodes: List[_TrieNode] = []
        node = self._root
        for i in range(cap):
            child = node.children.get(self._page_key(tokens, i))
            if child is None:
                break
            nodes.append(child)
            node = child
        if len(nodes) < self.prefix_min_pages:
            return []
        return nodes

    def match_len(self, tokens) -> int:
        """Matched-prefix length IN TOKENS a prompt would get right
        now. Pure peek — no refcount, no LRU touch, no counters — safe
        from the traffic thread (suffix-only TTFT pricing)."""
        with self._lock:
            return len(self._match_nodes(np.asarray(tokens).reshape(-1))) \
                * self.page_size

    @staticmethod
    def _tenant_key(tenant) -> str:
        return str(tenant) if tenant else "default"

    def _evict_leaf_locked(self, tenant: Optional[str] = None) -> bool:
        """Reclaim ONE trie-only page: the least-recently-used leaf
        whose page no live sequence holds (refcount 1 = the trie's own
        reference). Interior nodes and shared pages are never touched
        — evicting them would free nothing and orphan the path. With
        ``tenant`` set only that tenant's leaves are candidates (the
        per-tenant quota recycles the tenant's own pages, never a
        neighbour's)."""
        best: Optional[_TrieNode] = None
        stack = [self._root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                if child.children:
                    stack.append(child)
                elif (int(self._ref[child.page]) == 1
                      and (tenant is None or child.tenant == tenant)):
                    if best is None or child.last_used < best.last_used:
                        best = child
        if best is None:
            return False
        del best.parent.children[best.key]
        del self._node_of_page[best.page]
        self._ref[best.page] = 0
        self._free.append(best.page)
        self.leaf_evictions_total += 1
        left = self._tenant_pages.get(best.tenant, 0) - 1
        if left > 0:
            self._tenant_pages[best.tenant] = left
        else:
            self._tenant_pages.pop(best.tenant, None)
        if tenant is not None:
            self._tenant_evictions[tenant] = \
                self._tenant_evictions.get(tenant, 0) + 1
        return True

    def _pop_page_locked(self) -> int:
        """One page off the free list; a dry list reclaims trie-only
        leaves (LRU) BEFORE surfacing backpressure — cached prefixes
        yield to live sequences, never the other way around."""
        if not self._free and not self._evict_leaf_locked():
            raise PagePoolExhausted("page pool dry (no evictable "
                                    "trie leaves)")
        return self._free.pop()

    def _quota_room_locked(self, tenant: str) -> bool:
        """True once ``tenant`` may insert one more trie page: either
        under its quota, or an LRU leaf of its OWN was evicted to make
        room. A refusal is counted — the per-tenant rejection gauge."""
        if not self.tenant_quota_pages:
            return True
        if self._tenant_pages.get(tenant, 0) < self.tenant_quota_pages:
            return True
        if self._evict_leaf_locked(tenant=tenant):
            return True
        self.tenant_quota_rejections_total += 1
        return False

    def publish(self, slot: int, context_tokens, tenant=None) -> int:
        """Insert ``slot``'s full pages into the trie so later prompts
        can attach them. ``context_tokens`` must cover the sequence's
        cached context (prompt + emitted); only pages fully covered by
        ``lengths[slot]`` publish — positions past the length may
        still hold rejected-draft garbage, full pages below it are
        immutable (writes only ever target positions >= length).
        ``tenant`` attributes the new pages for the per-tenant quota.
        Returns the newly published page count. No-op unless
        prefix_cache."""
        if not self.prefix_cache:
            return 0
        tn = self._tenant_key(tenant)
        with self._lock:
            if not self._active[slot] or self._pub_dead[slot]:
                return 0
            tokens = np.asarray(context_tokens).reshape(-1)
            full = min(int(self.lengths[slot]),
                       int(tokens.size)) // self.page_size
            idx = self._published_of[slot]
            if full <= idx:
                return 0
            node = self._pub_node[slot] or self._root
            chain = self._pages_of[slot]
            new = 0
            while idx < full:
                key = self._page_key(tokens, idx)
                child = node.children.get(key)
                if child is not None:
                    if child.page != chain[idx]:
                        # a sibling that cold-prefilled the same run
                        # concurrently published first; keep ours
                        # private rather than re-point live tables
                        self._pub_dead[slot] = True
                        break
                    self._touch(child)
                else:
                    if (self.trie_max_pages
                            and len(self._node_of_page) >= self.trie_max_pages
                            and not self._evict_leaf_locked()):
                        break   # cap reached, nothing evictable: retry later
                    if not self._quota_room_locked(tn):
                        break   # tenant at quota, nothing of theirs to evict
                    child = _TrieNode(key, chain[idx], node, tn)
                    node.children[key] = child
                    self._node_of_page[chain[idx]] = child
                    self._ref[chain[idx]] += 1
                    self._touch(child)
                    self._tenant_pages[tn] = self._tenant_pages.get(tn, 0) + 1
                    new += 1
                node = child
                idx += 1
            self._published_of[slot] = idx
            self._pub_node[slot] = node
            self.published_pages_total += new
            return new

    def drop_trie(self) -> int:
        """Flush the whole prefix trie: every trie-resident page loses
        the trie's reference (freed at zero — shared pages survive
        until their sequences release). Live sequences republish from
        scratch on their next publish. Returns pages freed. The
        teardown/drain hook: after drop_trie + releasing every slot,
        ``pages_in_use`` must be exactly zero."""
        with self._lock:
            freed = 0
            for p in list(self._node_of_page):
                self._ref[p] -= 1
                if self._ref[p] == 0:
                    self._free.append(p)
                    freed += 1
            self._node_of_page.clear()
            self._root.children.clear()
            self._tenant_pages.clear()
            for s in range(self.max_seqs):
                self._published_of[s] = 0
                self._pub_node[s] = self._root if self._active[s] else None
                self._pub_dead[s] = False
            return freed

    def trie_pages(self) -> int:
        with self._lock:
            return len(self._node_of_page)

    def reclaimable_pages(self, slot: int) -> int:
        """Pages that evicting ``slot`` would actually give back: the
        ones only THIS sequence holds (net of the trie's reference —
        a trie-resident page drops to trie-only on release and LRU
        leaf eviction reclaims it on the retry). The engine's pool-dry
        victim ranking uses this instead of raw page count, so a
        mostly-shared sequence is never evicted for ~zero gain."""
        with self._lock:
            return sum(
                1 for p in self._pages_of[slot]
                if int(self._ref[p])
                - (1 if p in self._node_of_page else 0) == 1)

    # -- disagg splice path (page store <-> pool) ----------------------------
    def export_run(self, tokens, max_pages: Optional[int] = None):
        """Read the trie-resident pages along ``tokens``' page-aligned
        prefix out of the device pools, uncapped (a spill wants EVERY
        full page, including the one ``_match_nodes`` reserves for the
        first-output-token prefill). Returns ``(n_pages, k_run, v_run,
        k_scales, v_scales)`` with k/v ``[n, L, KVH, ps, hd]`` in the
        pool dtype and scales ``[n, L, KVH, ps]`` (None for fp32
        pools). Safe against a concurrently running step: full
        trie-resident pages are immutable by construction (writes only
        ever target positions >= length; growth pops fresh pages), and
        the buffer refs are snapshotted under the lock."""
        empty = (0, None, None, None, None)
        if not self.prefix_cache:
            return empty
        tokens = np.asarray(tokens).reshape(-1)
        with self._lock:
            if self._k_pages is None:
                return empty
            pids: List[int] = []
            node = self._root
            for i in range(int(tokens.size) // self.page_size):
                child = node.children.get(self._page_key(tokens, i))
                if child is None:
                    break
                self._touch(child)
                pids.append(child.page)
                node = child
                if max_pages and len(pids) >= max_pages:
                    break
            kbufs = list(self._k_pages)
            vbufs = list(self._v_pages)
            ksb = list(self._k_scales) if self.quantized else None
            vsb = list(self._v_scales) if self.quantized else None
            self.exported_pages_total += len(pids)
        if not pids:
            return empty
        sel = np.asarray(pids, np.int32)
        k_run = np.stack([np.asarray(b[:, sel]).transpose(1, 0, 2, 3)
                          for b in kbufs], axis=1)
        v_run = np.stack([np.asarray(b[:, sel]).transpose(1, 0, 2, 3)
                          for b in vbufs], axis=1)
        k_sc = v_sc = None
        if ksb is not None:
            k_sc = np.stack([np.asarray(b[:, sel]).transpose(1, 0, 2)
                             for b in ksb], axis=1)
            v_sc = np.stack([np.asarray(b[:, sel]).transpose(1, 0, 2)
                             for b in vsb], axis=1)
        return len(pids), k_run, v_run, k_sc, v_sc

    def ingest_run(self, tokens, k_run, v_run, k_scales=None,
                   v_scales=None, *, tenant=None) -> int:
        """Splice externally-produced full pages (a page-store fetch)
        into the pool + trie so the next ``acquire`` attaches them by
        reference and resumes at ``lengths=matched``. Array layouts
        mirror ``export_run``; data must already be in the POOL dtype
        (int8 pools take int8 bodies + fp32 scale planes verbatim).
        Pages already trie-resident are skipped without a device
        write; caps (``trie_max_pages``, the per-tenant quota, pool
        pressure) truncate the run — a partial ingest just matches
        less, never wrong tokens. MUST be called from the engine's
        step-loop thread: the device writes race ``set_buffers``
        otherwise. Returns pages ingested."""
        if not self.prefix_cache:
            return 0
        tokens = np.asarray(tokens).reshape(-1)
        k_run = np.asarray(k_run)
        v_run = np.asarray(v_run)
        n_avail = min(int(tokens.size) // self.page_size,
                      int(k_run.shape[0]), int(v_run.shape[0]))
        if n_avail <= 0:
            return 0
        want = (self.num_layers, self.num_kv_heads, self.page_size,
                self.head_dim)
        if k_run.shape[1:] != want or v_run.shape[1:] != want:
            raise ValueError(
                f"ingest_run: page shape {k_run.shape[1:]} != "
                f"[L,KVH,ps,hd] {want}")
        if self.quantized and (k_scales is None or v_scales is None):
            raise ValueError("ingest_run: int8 pool needs scale planes")
        self._ensure_buffers()
        tn = self._tenant_key(tenant)
        fresh: List[Tuple[int, int]] = []   # (run index, page id)
        with self._lock:
            node = self._root
            for i in range(n_avail):
                key = self._page_key(tokens, i)
                child = node.children.get(key)
                if child is not None:
                    self._touch(child)
                    node = child
                    continue
                if (self.trie_max_pages
                        and len(self._node_of_page) >= self.trie_max_pages
                        and not self._evict_leaf_locked()):
                    break
                if not self._quota_room_locked(tn):
                    break
                try:
                    p = self._pop_page_locked()
                except PagePoolExhausted:
                    break   # partial ingest: shorter match, never wrong
                child = _TrieNode(key, p, node, tn)
                node.children[key] = child
                self._node_of_page[p] = child
                self._ref[p] = 1
                self._touch(child)
                self._tenant_pages[tn] = self._tenant_pages.get(tn, 0) + 1
                fresh.append((i, p))
                node = child
            self.ingested_pages_total += len(fresh)
        if not fresh:
            return 0
        # one fused jitted scatter for every buffer, padded to the next
        # power of two with junk page 0 (block 0 data, harmless): an
        # unbucketed length would compile a fresh executable per
        # distinct run size, and per-buffer at[].set dispatch alone
        # costs multiples of a decode step — both are splice-time
        # stalls on exactly the latency-critical warm-start path
        n = len(fresh)
        width = 1
        while width < n:
            width *= 2
        sel = np.zeros(width, np.int32)
        sel[:n] = [p for _, p in fresh]
        idx = [i for i, _ in fresh] + [fresh[0][0]] * (width - n)
        bufs, blks = [], []
        for li in range(self.num_layers):
            bufs.append(self._k_pages[li])
            blks.append(np.stack([k_run[i, li] for i in idx], axis=1))
            bufs.append(self._v_pages[li])
            blks.append(np.stack([v_run[i, li] for i in idx], axis=1))
            if self.quantized:
                bufs.append(self._k_scales[li])
                blks.append(np.stack(
                    [np.asarray(k_scales)[i, li] for i in idx], axis=1))
                bufs.append(self._v_scales[li])
                blks.append(np.stack(
                    [np.asarray(v_scales)[i, li] for i in idx], axis=1))
        out = _scatter_pages(bufs, sel, blks)
        per = 4 if self.quantized else 2
        for li in range(self.num_layers):
            self._k_pages[li] = out[per * li]
            self._v_pages[li] = out[per * li + 1]
            if self.quantized:
                self._k_scales[li] = out[per * li + 2]
                self._v_scales[li] = out[per * li + 3]
        return len(fresh)

    def trie_leaf_runs(self) -> List[np.ndarray]:
        """Token runs (root-to-leaf concatenated page keys) covering
        every trie leaf — the drain-spill walk: exporting each run
        spills the whole trie with shared interior pages read once per
        leaf path."""
        with self._lock:
            runs: List[np.ndarray] = []
            stack: List[Tuple[_TrieNode, List[int]]] = [(self._root, [])]
            while stack:
                node, path = stack.pop()
                if node is not self._root:
                    path = path + list(node.key)
                if node.children:
                    for child in node.children.values():
                        stack.append((child, path))
                elif path:
                    runs.append(np.asarray(path, np.int64))
            return runs

    # -- sequence lifecycle --------------------------------------------------
    def acquire(self, prompt_tokens) -> Tuple[int, int]:
        """Claim a batch slot + pages for a prompt, attaching any
        trie-matched prefix pages BY REFERENCE (their K/V is already
        resident — prefill starts at the fork point). Returns
        ``(slot, matched_tokens)`` with matched_tokens page-aligned
        and < len(prompt). Raises PagePoolExhausted when slots or
        pages are unavailable *right now* (backpressure, not
        rejection). With prefix_cache off this is exactly
        ``allocate_slot`` (matched_tokens == 0)."""
        tokens = np.asarray(prompt_tokens).reshape(-1)
        n = int(tokens.size)
        need_total = self.pages_needed(n)
        if need_total > self.max_pages_per_seq:
            raise ValueError(
                f"{n} tokens need {need_total} pages > max_pages_per_seq="
                f"{self.max_pages_per_seq}")
        with self._lock:
            slot = next((i for i, a in enumerate(self._active) if not a),
                        None)
            if slot is None:
                raise PagePoolExhausted("no free decode slots")
            nodes = self._match_nodes(tokens)
            if self.prefix_cache:
                self.prefix_lookups_total += 1
                self.prefix_requested_tokens_total += n
            # bump the matched path FIRST: refcount >= 2 shields those
            # pages from the leaf eviction the suffix allocation below
            # may trigger
            for nd in nodes:
                self._ref[nd.page] += 1
                self._touch(nd)
            priv: List[int] = []
            try:
                for _ in range(need_total - len(nodes)):
                    p = self._pop_page_locked()
                    self._ref[p] = 1
                    priv.append(p)
            except PagePoolExhausted:
                for p in priv:
                    self._ref[p] = 0
                    self._free.append(p)
                for nd in nodes:
                    self._ref[nd.page] -= 1
                raise
            pages = [nd.page for nd in nodes] + priv
            self._pages_of[slot] = pages
            row = self.block_tables[slot]
            row[:] = 0
            row[:len(pages)] = pages
            # the matched prefix's K/V is genuinely resident: the new
            # sequence starts at length = matched (the fork point)
            self.lengths[slot] = len(nodes) * self.page_size
            self._active[slot] = True
            self.allocations_total += len(priv)
            self._published_of[slot] = len(nodes)
            self._pub_node[slot] = nodes[-1] if nodes else self._root
            self._pub_dead[slot] = False
            if nodes:
                self.prefix_hits_total += 1
                self.prefix_hit_tokens_total += len(nodes) * self.page_size
                # the first private page past the shared prefix IS the
                # copy-on-write fork
                self.cow_forks_total += 1
            return slot, len(nodes) * self.page_size

    def allocate_slot(self, n_tokens: int) -> int:
        """Claim a batch slot + pages for an n_tokens prompt with NO
        trie consultation (the pre-radix API; warmup and token-count
        callers). Returns the slot id; raises PagePoolExhausted when
        pages or slots are unavailable *right now*."""
        need = self.pages_needed(n_tokens)
        if need > self.max_pages_per_seq:
            raise ValueError(
                f"{n_tokens} tokens need {need} pages > max_pages_per_seq="
                f"{self.max_pages_per_seq}")
        with self._lock:
            slot = next((i for i, a in enumerate(self._active) if not a),
                        None)
            if slot is None:
                raise PagePoolExhausted("no free decode slots")
            pages: List[int] = []
            try:
                for _ in range(need):
                    p = self._pop_page_locked()
                    self._ref[p] = 1
                    pages.append(p)
            except PagePoolExhausted:
                for p in pages:
                    self._ref[p] = 0
                    self._free.append(p)
                raise
            self._pages_of[slot] = pages
            row = self.block_tables[slot]
            row[:] = 0
            row[:len(pages)] = pages
            self.lengths[slot] = 0
            self._active[slot] = True
            self.allocations_total += need
            self._published_of[slot] = 0
            self._pub_node[slot] = self._root
            self._pub_dead[slot] = False
            return slot

    def ensure_capacity(self, slot: int, new_len: int) -> None:
        """Grow slot's page chain to cover new_len tokens; growth pops
        FRESH private pages (never a shared one — that is what makes
        copy-on-write structural); raises PagePoolExhausted when the
        pool is dry even after trie-leaf reclaim (engine evicts
        then)."""
        need = self.pages_needed(new_len)
        if new_len > self.max_pages_per_seq * self.page_size:
            raise ValueError(
                f"sequence of {new_len} tokens exceeds max_pages_per_seq="
                f"{self.max_pages_per_seq} x page_size={self.page_size}")
        with self._lock:
            pages = self._pages_of[slot]
            while len(pages) < need:
                p = self._pop_page_locked()
                self._ref[p] = 1
                self.block_tables[slot, len(pages)] = p
                pages.append(p)
                self.allocations_total += 1

    def advance(self, slot: int, n: int = 1) -> int:
        self.lengths[slot] += n
        return int(self.lengths[slot])

    def release(self, slot: int) -> None:
        """Sequence done: every chain page drops one reference; pages
        reach the free list only at refcount ZERO — a page the trie
        (or a sibling sequence) still holds survives. Table row back
        to the junk page, slot reusable."""
        with self._lock:
            for p in self._pages_of[slot]:
                self._ref[p] -= 1
                if self._ref[p] == 0:
                    self._free.append(p)
            self._pages_of[slot] = []
            self.block_tables[slot, :] = 0
            self.lengths[slot] = 0
            self._active[slot] = False
            self._published_of[slot] = 0
            self._pub_node[slot] = None
            self._pub_dead[slot] = False

    def evict(self, slot: int) -> None:
        """Preemption: identical to release, but counted — the engine
        re-queues the victim's request for re-prefill."""
        self.release(slot)
        with self._lock:
            self.evictions_total += 1

    def is_active(self, slot: int) -> bool:
        return self._active[slot]

    def active_slots(self) -> List[int]:
        return [i for i, a in enumerate(self._active) if a]

    # -- introspection -------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            in_use = self.usable_pages - len(self._free)
            return {
                "pages_total": self.usable_pages,
                "pages_free": len(self._free),
                "pages_in_use": in_use,
                "page_utilization": (round(in_use / self.usable_pages, 4)
                                     if self.usable_pages else 0.0),
                "active_seqs": sum(1 for a in self._active if a),
                "max_seqs": self.max_seqs,
                "evictions_total": self.evictions_total,
                "page_allocations_total": self.allocations_total,
                "pool_bytes": self.pool_bytes(),
            }

    def radix_stats(self) -> Dict[str, Any]:
        """The ``paddle_generation_radix_*`` gauge family (nested into
        engine.stats() as the "radix" group): prefix hit volume/rate,
        the shared/private/trie-resident page split, CoW forks and
        trie-leaf evictions."""
        with self._lock:
            chained: Dict[int, int] = {}
            for slot in range(self.max_seqs):
                for p in self._pages_of[slot]:
                    chained[p] = chained.get(p, 0) + 1
            shared = sum(1 for p in chained if int(self._ref[p]) >= 2)
            private = sum(1 for p in chained if int(self._ref[p]) == 1)
            req = self.prefix_requested_tokens_total
            return {
                "enabled": int(self.prefix_cache),
                "prefix_lookups_total": self.prefix_lookups_total,
                "prefix_hits_total": self.prefix_hits_total,
                "prefix_hit_tokens_total": self.prefix_hit_tokens_total,
                "prefix_requested_tokens_total": req,
                "prefix_hit_rate": (
                    round(self.prefix_hit_tokens_total / req, 4)
                    if req else 0.0),
                "shared_pages": shared,
                "private_pages": private,
                "trie_pages": len(self._node_of_page),
                "cow_forks_total": self.cow_forks_total,
                "leaf_evictions_total": self.leaf_evictions_total,
                "published_pages_total": self.published_pages_total,
                "ingested_pages_total": self.ingested_pages_total,
                "exported_pages_total": self.exported_pages_total,
                "tenant_quota_pages": self.tenant_quota_pages,
                "tenant_quota_rejections_total":
                    self.tenant_quota_rejections_total,
                "tenant_pages": dict(self._tenant_pages),
                "tenant_leaf_evictions": dict(self._tenant_evictions),
            }

    def check_integrity(self) -> None:
        """Invariant audit (tests call this after concurrent
        join/leave churn and in every radix-test teardown): chains and
        tables mirror each other, the trie is structurally sound,
        every page's refcount equals (chains holding it) +
        (1 if trie-resident), a page shared by chains is always
        trie-resident, free + in-use covers the pool exactly."""
        with self._lock:
            holders: Dict[int, List[int]] = {}
            for slot in range(self.max_seqs):
                pages = self._pages_of[slot]
                if not self._active[slot] and pages:
                    raise AssertionError(f"inactive slot {slot} holds pages")
                if len(set(pages)) != len(pages):
                    raise AssertionError(
                        f"slot {slot} chain repeats a page: {pages}")
                for j, p in enumerate(pages):
                    if p == 0:
                        raise AssertionError("junk page 0 inside a chain")
                    holders.setdefault(p, []).append(slot)
                    if int(self.block_tables[slot, j]) != p:
                        raise AssertionError(
                            f"table/chain mismatch at slot {slot} idx {j}")
                covered = len(pages) * self.page_size
                if self._active[slot] and int(self.lengths[slot]) > covered:
                    raise AssertionError(
                        f"slot {slot} length {self.lengths[slot]} > "
                        f"allocated {covered}")
            # trie structure: parent/child links coherent, every page
            # appears at most once, node_of_page is exactly the trie
            trie: Dict[int, _TrieNode] = {}
            stack = [self._root]
            while stack:
                node = stack.pop()
                for key, child in node.children.items():
                    if child.parent is not node or child.key != key:
                        raise AssertionError(
                            f"trie link broken at page {child.page}")
                    p = child.page
                    if not isinstance(p, int) or p <= 0:
                        raise AssertionError(f"trie node with bad page {p!r}")
                    if p in trie:
                        raise AssertionError(f"page {p} twice in the trie")
                    if (child.key is not None
                            and len(child.key) != self.page_size):
                        raise AssertionError(
                            f"trie key of {len(child.key)} tokens != "
                            f"page_size {self.page_size}")
                    trie[p] = child
                    stack.append(child)
            if set(trie) != set(self._node_of_page):
                raise AssertionError(
                    "node_of_page desynced from the trie: "
                    f"{set(trie) ^ set(self._node_of_page)}")
            # per-tenant page counts mirror the trie exactly
            tcount: Dict[str, int] = {}
            for nd in trie.values():
                tcount[nd.tenant] = tcount.get(nd.tenant, 0) + 1
            if tcount != self._tenant_pages:
                raise AssertionError(
                    f"tenant page accounting desynced: {tcount} != "
                    f"{self._tenant_pages}")
            for p, nd in trie.items():
                if self._node_of_page[p] is not nd:
                    raise AssertionError(f"node_of_page[{p}] is a stale node")
            # refcounts: chains + trie residency, nothing else
            for p in range(1, self.num_pages):
                expected = len(holders.get(p, ())) + (1 if p in trie else 0)
                if int(self._ref[p]) != expected:
                    raise AssertionError(
                        f"refcount leak: page {p} ref {int(self._ref[p])} "
                        f"!= {expected} (chains {holders.get(p, [])}, "
                        f"trie={p in trie})")
            # a page in two chains got there only via the trie
            for p, slots in holders.items():
                if len(slots) > 1 and p not in trie:
                    raise AssertionError(
                        f"page {p} shared by slots {slots} without trie "
                        "residency")
            # publish cursors stay inside the trie
            for slot in range(self.max_seqs):
                if not self._active[slot]:
                    continue
                pub = self._published_of[slot]
                pages = self._pages_of[slot]
                if pub > len(pages):
                    raise AssertionError(
                        f"slot {slot} published {pub} > chain {len(pages)}")
                for j in range(pub):
                    if pages[j] not in trie:
                        raise AssertionError(
                            f"slot {slot} counts page {pages[j]} as "
                            "published but it is not trie-resident")
            # free list: unique, disjoint from use, refcount zero
            fs = set(self._free)
            if len(fs) != len(self._free):
                raise AssertionError("free list holds duplicates")
            in_use = set(holders) | set(trie)
            dup = fs & in_use
            if dup:
                raise AssertionError(f"pages both free and in use: {dup}")
            bad = [p for p in fs if int(self._ref[p]) != 0]
            if bad:
                raise AssertionError(f"free pages with refs: {bad}")
            if len(fs) + len(in_use) != self.usable_pages:
                raise AssertionError(
                    f"page leak: {len(fs)} free + {len(in_use)} in use "
                    f"!= {self.usable_pages}")

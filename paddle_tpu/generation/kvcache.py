"""PagedKVCache: the page pool + block tables behind continuous
batching.

Design (Ragged Paged Attention, arXiv:2604.15464): K/V live in
fixed-size pages inside ONE preallocated device buffer per layer;
each sequence owns a block table (ordered list of page ids) and a true
length. Growing a sequence by one token never reallocates — at worst
it pops one page off the free list. Completion returns the pages in
O(pages). The pool is sized once (``num_pages * page_size`` token
slots) so device memory is a configuration decision, not a runtime
surprise — exactly the property serving under heavy traffic needs.

This class is the HOST-side manager: block tables, lengths, the free
list, slot assignment, admission accounting. The device-side page
buffers (jax arrays, [num_kv_heads, num_pages, page_size, head_dim]
per layer) are held here too, but they are only ever *mutated* inside
the compiled prefill/decode steps (kernels/paged_attention.py
``kv_cache_write``) — the engine fetches the functionally-updated
pools and swaps them back via ``set_buffers``. All bookkeeping methods
are called from the engine's single step loop; the lock only protects
the metric-reader path (``stats()`` from a scrape thread).

Page 0 is permanently reserved as the JUNK page: idle decode lanes and
batch-padding rows point their tables at it, so their (discarded)
writes can never corrupt a live sequence.

``dtype="int8"`` selects the QUANTIZED pool (ragged engine only): K/V
pages store blockwise-int8 values plus one fp32 scale per
(head, token slot) — the kernels/quant.py block unit with
block = head_dim. A page then costs ~1/3.6 the fp32 bytes
(``page_bytes``), so the same HBM budget holds ~3.6x the pages and
~2x+ the resident sequences — the capacity multiplier
tools/generation_bench.py --int8 gates.

Exhaustion is backpressure, not corruption: ``allocate_slot`` /
``ensure_capacity`` raise ``PagePoolExhausted``; the engine responds
by delaying admission (queued requests wait for pages) or by evicting
a victim sequence (whose request is re-queued for re-prefill — greedy
decode makes the recomputed continuation identical).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["PagedKVCache", "PagePoolExhausted"]


class PagePoolExhausted(RuntimeError):
    """No free pages for the requested growth — admission backpressure
    (or eviction) must resolve it; never an allocation."""


class PagedKVCache:
    def __init__(self, num_layers: int, num_kv_heads: int, head_dim: int, *,
                 num_pages: int, page_size: int, max_seqs: int,
                 max_pages_per_seq: int, dtype: str = "float32"):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        if page_size < 1 or max_seqs < 1 or max_pages_per_seq < 1:
            raise ValueError("page_size/max_seqs/max_pages_per_seq >= 1")
        self.num_layers = int(num_layers)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_seqs = int(max_seqs)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.dtype = dtype
        self.quantized = dtype == "int8"
        self._lock = threading.Lock()
        # device pools, one K + one V per layer (lazy: first access
        # allocates, so constructing a cache in a test costs nothing);
        # int8 pools carry fp32 scale planes [KVH, P, ps] alongside
        self._k_pages: Optional[List[Any]] = None
        self._v_pages: Optional[List[Any]] = None
        self._k_scales: Optional[List[Any]] = None
        self._v_scales: Optional[List[Any]] = None
        # host bookkeeping
        self.block_tables = np.zeros((max_seqs, max_pages_per_seq), np.int32)
        self.lengths = np.zeros(max_seqs, np.int32)
        self._pages_of: List[List[int]] = [[] for _ in range(max_seqs)]
        self._active = [False] * max_seqs
        # page 0 = junk page, never on the free list
        self._free = list(range(num_pages - 1, 0, -1))
        self.evictions_total = 0
        self.allocations_total = 0

    # -- device buffers ------------------------------------------------------
    def _ensure_buffers(self):
        if self._k_pages is None:
            import jax.numpy as jnp

            shape = (self.num_kv_heads, self.num_pages, self.page_size,
                     self.head_dim)
            self._k_pages = [jnp.zeros(shape, self.dtype)
                             for _ in range(self.num_layers)]
            self._v_pages = [jnp.zeros(shape, self.dtype)
                             for _ in range(self.num_layers)]
            if self.quantized:
                # scale 1.0 everywhere: a junk/unwritten slot
                # dequantizes to 0.0, never to NaN/garbage
                sshape = shape[:3]
                self._k_scales = [jnp.ones(sshape, "float32")
                                  for _ in range(self.num_layers)]
                self._v_scales = [jnp.ones(sshape, "float32")
                                  for _ in range(self.num_layers)]

    @property
    def k_pages(self) -> List[Any]:
        self._ensure_buffers()
        return self._k_pages

    @property
    def v_pages(self) -> List[Any]:
        self._ensure_buffers()
        return self._v_pages

    @property
    def k_scales(self) -> List[Any]:
        self._ensure_buffers()
        return self._k_scales

    @property
    def v_scales(self) -> List[Any]:
        self._ensure_buffers()
        return self._v_scales

    def set_buffers(self, k_pages: List[Any], v_pages: List[Any],
                    k_scales: Optional[List[Any]] = None,
                    v_scales: Optional[List[Any]] = None) -> None:
        """Swap in the functionally-updated pools fetched from a
        prefill/decode/ragged step (scale planes too for the int8
        pool)."""
        if len(k_pages) != self.num_layers or len(v_pages) != self.num_layers:
            raise ValueError("set_buffers: wrong layer count")
        self._k_pages = list(k_pages)
        self._v_pages = list(v_pages)
        if self.quantized:
            if k_scales is None or v_scales is None:
                raise ValueError("set_buffers: int8 pool needs scale planes")
            self._k_scales = list(k_scales)
            self._v_scales = list(v_scales)

    @staticmethod
    def page_bytes(num_kv_heads: int, head_dim: int, page_size: int,
                   dtype: str) -> int:
        """HBM bytes ONE page costs per layer (K + V, scale planes
        included for int8) — the capacity arithmetic the int8 bench
        gates its ~2x-resident-sequences claim on."""
        slots = num_kv_heads * page_size
        if dtype == "int8":
            return 2 * (slots * head_dim + 4 * slots)   # int8 body + scales
        import numpy as np

        item = 2 if dtype == "bfloat16" else np.dtype(dtype).itemsize
        return 2 * slots * head_dim * item

    def pool_bytes(self) -> int:
        """Total device bytes of the page pools across layers."""
        return (self.num_layers * self.num_pages
                * self.page_bytes(self.num_kv_heads, self.head_dim,
                                  self.page_size, self.dtype))

    # -- capacity accounting -------------------------------------------------
    def pages_needed(self, n_tokens: int) -> int:
        return -(-max(int(n_tokens), 0) // self.page_size)

    @property
    def usable_pages(self) -> int:
        """Pool capacity available to sequences (junk page excluded)."""
        return self.num_pages - 1

    def free_pages(self) -> int:
        return len(self._free)

    def can_fit_ever(self, n_tokens: int) -> bool:
        """Could a sequence of n_tokens EVER be served by this pool —
        the admission-time sanity check (Overloaded before prefill)."""
        need = self.pages_needed(n_tokens)
        return (need <= self.usable_pages
                and need <= self.max_pages_per_seq
                and n_tokens <= self.max_pages_per_seq * self.page_size)

    def can_allocate(self, n_tokens: int) -> bool:
        return self.pages_needed(n_tokens) <= len(self._free)

    def free_slots(self) -> int:
        return sum(1 for a in self._active if not a)

    # -- sequence lifecycle --------------------------------------------------
    def allocate_slot(self, n_tokens: int) -> int:
        """Claim a batch slot + pages for an n_tokens prompt. Returns
        the slot id; raises PagePoolExhausted when pages or slots are
        unavailable *right now* (backpressure, not rejection)."""
        need = self.pages_needed(n_tokens)
        if need > self.max_pages_per_seq:
            raise ValueError(
                f"{n_tokens} tokens need {need} pages > max_pages_per_seq="
                f"{self.max_pages_per_seq}")
        with self._lock:
            slot = next((i for i, a in enumerate(self._active) if not a),
                        None)
            if slot is None:
                raise PagePoolExhausted("no free decode slots")
            if need > len(self._free):
                raise PagePoolExhausted(
                    f"need {need} pages, {len(self._free)} free")
            pages = [self._free.pop() for _ in range(need)]
            self._pages_of[slot] = pages
            row = self.block_tables[slot]
            row[:] = 0
            row[:len(pages)] = pages
            self.lengths[slot] = 0
            self._active[slot] = True
            self.allocations_total += need
            return slot

    def ensure_capacity(self, slot: int, new_len: int) -> None:
        """Grow slot's page chain to cover new_len tokens; raises
        PagePoolExhausted when the pool is dry (engine evicts then)."""
        need = self.pages_needed(new_len)
        if new_len > self.max_pages_per_seq * self.page_size:
            raise ValueError(
                f"sequence of {new_len} tokens exceeds max_pages_per_seq="
                f"{self.max_pages_per_seq} x page_size={self.page_size}")
        with self._lock:
            pages = self._pages_of[slot]
            while len(pages) < need:
                if not self._free:
                    raise PagePoolExhausted(
                        f"slot {slot} needs page {len(pages)}, pool dry")
                p = self._free.pop()
                self.block_tables[slot, len(pages)] = p
                pages.append(p)
                self.allocations_total += 1

    def advance(self, slot: int, n: int = 1) -> int:
        self.lengths[slot] += n
        return int(self.lengths[slot])

    def release(self, slot: int) -> None:
        """Sequence done: pages back on the free list, table row back
        to the junk page, slot reusable."""
        with self._lock:
            self._free.extend(self._pages_of[slot])
            self._pages_of[slot] = []
            self.block_tables[slot, :] = 0
            self.lengths[slot] = 0
            self._active[slot] = False

    def evict(self, slot: int) -> None:
        """Preemption: identical to release, but counted — the engine
        re-queues the victim's request for re-prefill."""
        self.release(slot)
        with self._lock:
            self.evictions_total += 1

    def is_active(self, slot: int) -> bool:
        return self._active[slot]

    def active_slots(self) -> List[int]:
        return [i for i, a in enumerate(self._active) if a]

    # -- introspection -------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            in_use = self.usable_pages - len(self._free)
            return {
                "pages_total": self.usable_pages,
                "pages_free": len(self._free),
                "pages_in_use": in_use,
                "page_utilization": (round(in_use / self.usable_pages, 4)
                                     if self.usable_pages else 0.0),
                "active_seqs": sum(1 for a in self._active if a),
                "max_seqs": self.max_seqs,
                "evictions_total": self.evictions_total,
                "page_allocations_total": self.allocations_total,
                "pool_bytes": self.pool_bytes(),
            }

    def check_integrity(self) -> None:
        """Invariant audit (tests call this after concurrent
        join/leave churn): every allocated page appears in exactly one
        chain, free + allocated covers the pool, tables mirror chains."""
        seen: Dict[int, int] = {}
        with self._lock:
            for slot in range(self.max_seqs):
                pages = self._pages_of[slot]
                if not self._active[slot] and pages:
                    raise AssertionError(f"inactive slot {slot} holds pages")
                for j, p in enumerate(pages):
                    if p in seen:
                        raise AssertionError(
                            f"page {p} in slots {seen[p]} and {slot}")
                    if p == 0:
                        raise AssertionError("junk page 0 inside a chain")
                    seen[p] = slot
                    if int(self.block_tables[slot, j]) != p:
                        raise AssertionError(
                            f"table/chain mismatch at slot {slot} idx {j}")
                covered = len(pages) * self.page_size
                if self._active[slot] and int(self.lengths[slot]) > covered:
                    raise AssertionError(
                        f"slot {slot} length {self.lengths[slot]} > "
                        f"allocated {covered}")
            dup = set(self._free) & set(seen)
            if dup:
                raise AssertionError(f"pages both free and allocated: {dup}")
            if len(self._free) + len(seen) != self.usable_pages:
                raise AssertionError(
                    f"page leak: {len(self._free)} free + {len(seen)} "
                    f"allocated != {self.usable_pages}")

"""Draft models for speculative decoding.

Speculative decoding (the ragged engine's ``spec_tokens`` path) needs
a DRAFT: something cheap that proposes the next k tokens of every
active sequence, which the target model then verifies in ONE ragged
call. Correctness never depends on the draft — the target's greedy
tokens are emitted whatever the draft proposed (a bad draft only
lowers the accepted-token rate and with it the speedup) — so the
protocol is deliberately tiny:

    propose(contexts, k) -> list of up-to-k int arrays, one per context

``HostDraft`` is the built-in implementation: a forward pass of a
(usually smaller) GPT whose weights were pulled out of a predictor's
scope, run as one jitted greedy loop over the whole batch of contexts
— k proposal tokens for EVERY active sequence cost k tiny batched
forwards, not k x rows. ``from_predictor(pred, cfg, num_layers=n)``
truncates to the first n decoder layers for a genuinely smaller draft;
with the full layer stack the draft replicates the target and the
acceptance rate approaches 1.0 (the bench's upper-bound
configuration — tools/generation_bench.py --spec).

The draft runs OUTSIDE the ragged executable on purpose: its batch
shape is [rows, max_position] with its own (cheap) compile, and the
target executable stays byte-identical whether speculation is on or
off — flipping ``spec_tokens`` mid-fleet never recompiles the serving
step.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = ["DraftModel", "HostDraft"]


class DraftModel:
    """Protocol: batched greedy proposal of up to k continuation
    tokens per context. Subclass and override ``propose``."""

    def propose(self, contexts: Sequence[np.ndarray],
                k: int) -> List[np.ndarray]:
        raise NotImplementedError


class HostDraft(DraftModel):
    """GPT forward over extracted weights as the draft.

    Weights live as numpy on the host; ``propose`` pads the contexts
    to one [rows, max_len] batch and runs a single jitted
    k-step greedy extension (re-prefill per proposed token — at draft
    scale the whole forward is tiny, and one fused executable beats k
    incremental host round-trips).
    """

    def __init__(self, params: dict, num_layers: int, num_heads: int,
                 max_position: int, *, name: str = "host_draft"):
        self.params = {k: np.asarray(v) for k, v in params.items()}
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.max_position = int(max_position)
        self.name = name
        # every propose() pads its row count up to at least min_rows
        # (the engine sets this to its lane count): ONE rows bucket for
        # the whole engine life instead of a compile per distinct
        # spec-row count — the draft is tiny, predictability wins
        self.min_rows = 1
        self._jitted = {}
        self._device_params = None

    # -- construction --------------------------------------------------------
    @classmethod
    def from_predictor(cls, predictor, cfg,
                       num_layers: Optional[int] = None) -> "HostDraft":
        """Extract draft weights from a loaded predictor's scope.
        ``num_layers`` truncates the decoder stack (a smaller draft);
        default keeps every layer (a replica draft — acceptance ~1)."""
        scope = predictor._scope
        n = int(num_layers if num_layers is not None else cfg.num_layers)
        names = ["gpt_tok_emb", "gpt_pos_emb",
                 "gpt_lnf.scale", "gpt_lnf.bias",
                 "gpt_head.w", "gpt_head.b"]
        for i in range(n):
            pre = f"dec{i}"
            names += [f"{pre}_ln1.scale", f"{pre}_ln1.bias",
                      f"{pre}_qkv.w", f"{pre}_qkv.b",
                      f"{pre}_proj.w", f"{pre}_proj.b",
                      f"{pre}_ln2.scale", f"{pre}_ln2.bias",
                      f"{pre}_ffn1.w", f"{pre}_ffn1.b",
                      f"{pre}_ffn2.w", f"{pre}_ffn2.b"]
        params = {}
        for name in names:
            var = scope.find_var(name)
            if var is None:
                raise ValueError(
                    f"draft weight {name!r} not in the predictor scope — "
                    "is this an LM exported by generation.build_lm_program?")
            params[name] = np.asarray(var)
        return cls(params, n, cfg.num_heads, cfg.max_position)

    # -- forward -------------------------------------------------------------
    def _fn(self, rows: int, max_len: int, k: int):
        """One jitted greedy k-extension over [rows, max_len]: a full
        prefill builds per-layer K/V caches and yields proposal 1;
        each further proposal is an INCREMENTAL single-position step
        over the caches — the draft costs ~one forward plus k-1 tiny
        extensions, not k re-prefills."""
        key = (rows, max_len, k)
        fn = self._jitted.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        # ONE device copy of the weights, shared by every shape
        # bucket's closure (a copy per bucket would multiply the
        # draft's footprint by the bucket count)
        if self._device_params is None:
            self._device_params = {n: jnp.asarray(v)
                                   for n, v in self.params.items()}
        p = self._device_params
        H = self.num_heads
        L = max_len

        def ln(x, pre):
            mu = x.mean(-1, keepdims=True)
            var = ((x - mu) ** 2).mean(-1, keepdims=True)
            return ((x - mu) / jnp.sqrt(var + 1e-5)
                    ) * p[f"{pre}.scale"] + p[f"{pre}.bias"]

        def head_logits(x):
            return ln(x, "gpt_lnf") @ p["gpt_head.w"] + p["gpt_head.b"]

        def prefill(toks, lens):
            # toks [R, L] int32 -> (argmax at each row's last token,
            # per-layer K/V caches [R, L, H*D])
            R = toks.shape[0]
            x = p["gpt_tok_emb"][toks] + p["gpt_pos_emb"][None, :L]
            kpmask = (jnp.arange(L)[None, :] < lens[:, None])
            causal = jnp.tril(jnp.ones((L, L), bool))
            caches = []
            for i in range(self.num_layers):
                pre = f"dec{i}"
                h = ln(x, f"{pre}_ln1")
                qkv = h @ p[f"{pre}_qkv.w"] + p[f"{pre}_qkv.b"]
                q, kk, v = jnp.split(qkv, 3, axis=-1)
                caches.append((kk, v))
                D = q.shape[-1] // H

                def heads(t):
                    return t.reshape(R, L, H, D).transpose(0, 2, 1, 3)

                s = jnp.einsum("rhqd,rhkd->rhqk", heads(q),
                               heads(kk)) / jnp.sqrt(D).astype(x.dtype)
                s = jnp.where(causal[None, None], s, -1e9)
                s = jnp.where(kpmask[:, None, None, :], s, -1e9)
                ctx = jnp.einsum("rhqk,rhkd->rhqd", jax.nn.softmax(s, -1),
                                 heads(v))
                ctx = ctx.transpose(0, 2, 1, 3).reshape(R, L, -1)
                x = x + ctx @ p[f"{pre}_proj.w"] + p[f"{pre}_proj.b"]
                h2 = ln(x, f"{pre}_ln2")
                f1 = jax.nn.gelu(
                    h2 @ p[f"{pre}_ffn1.w"] + p[f"{pre}_ffn1.b"],
                    approximate=False)
                x = x + f1 @ p[f"{pre}_ffn2.w"] + p[f"{pre}_ffn2.b"]
            logits = head_logits(x)
            last = jnp.take_along_axis(
                logits, (lens - 1)[:, None, None].astype(jnp.int32), axis=1)
            return jnp.argmax(last[:, 0], -1).astype(jnp.int32), caches

        def step(tok, pos, lens, caches):
            # one new token per row at position pos [R] over the caches
            R = tok.shape[0]
            x = (p["gpt_tok_emb"][tok][:, None]
                 + p["gpt_pos_emb"][jnp.minimum(pos, L - 1)][:, None])
            new_caches = []
            attend = (jnp.arange(L)[None, :] <= pos[:, None])   # [R, L]
            for i, (ck, cv) in enumerate(caches):
                pre = f"dec{i}"
                h = ln(x, f"{pre}_ln1")
                qkv = h @ p[f"{pre}_qkv.w"] + p[f"{pre}_qkv.b"]
                q, kk, v = jnp.split(qkv, 3, axis=-1)
                idx = jnp.minimum(pos, L - 1)
                ck = ck.at[jnp.arange(R), idx].set(kk[:, 0])
                cv = cv.at[jnp.arange(R), idx].set(v[:, 0])
                new_caches.append((ck, cv))
                D = q.shape[-1] // H
                qh = q.reshape(R, H, D)
                kh = ck.reshape(R, L, H, D)
                vh = cv.reshape(R, L, H, D)
                s = jnp.einsum("rhd,rlhd->rhl", qh,
                               kh) / jnp.sqrt(D).astype(x.dtype)
                s = jnp.where(attend[:, None, :], s, -1e9)
                ctx = jnp.einsum("rhl,rlhd->rhd", jax.nn.softmax(s, -1),
                                 vh).reshape(R, 1, -1)
                x = x + ctx @ p[f"{pre}_proj.w"] + p[f"{pre}_proj.b"]
                h2 = ln(x, f"{pre}_ln2")
                f1 = jax.nn.gelu(
                    h2 @ p[f"{pre}_ffn1.w"] + p[f"{pre}_ffn1.b"],
                    approximate=False)
                x = x + f1 @ p[f"{pre}_ffn2.w"] + p[f"{pre}_ffn2.b"]
            logits = head_logits(x)
            return jnp.argmax(logits[:, 0], -1).astype(jnp.int32), new_caches

        def extend(toks, lens):
            nxt, caches = prefill(toks, lens)
            out = [nxt]
            pos = lens
            for _ in range(k - 1):
                nxt, caches = step(nxt, pos, lens, caches)
                pos = pos + 1
                out.append(nxt)
            return jnp.stack(out, axis=1)        # [R, k]

        fn = jax.jit(extend)
        self._jitted[key] = fn
        return fn

    def warmup(self, k: int) -> None:
        """Compile every (rows, length) bucket ``propose`` can hit —
        the engine's warmup calls this so no serving step ever pays a
        draft XLA compile mid-generation (the same contract the
        target executable's warmup keeps)."""
        if k < 1:
            return
        b = 16
        seen = set()
        while True:
            cap = min(self.max_position, b)
            if cap not in seen:
                seen.add(cap)
                self.propose([np.zeros(max(1, cap - k), np.int64)], k)
            if cap >= self.max_position:
                return
            b *= 2

    def propose(self, contexts: Sequence[np.ndarray],
                k: int) -> List[np.ndarray]:
        if not contexts or k < 1:
            return [np.zeros(0, np.int64) for _ in contexts]
        rows = len(contexts)
        lens = np.array([len(c) for c in contexts], np.int32)
        # bucket BOTH dims (rows to a pow-2 floor of min_rows, lengths
        # to a pow-2 ladder) so a handful of executables serves every
        # batch shape the engine's churn produces — a compile per
        # distinct row count would burn the very steps speculation
        # saves (and warmup() can pre-pay the whole ladder)
        rows_b = 1 << (max(rows, self.min_rows) - 1).bit_length()
        need = int(lens.max()) + k
        max_len = min(self.max_position,
                      max(16, 1 << (need - 1).bit_length()))
        toks = np.zeros((rows_b, max_len), np.int32)
        for i, c in enumerate(contexts):
            toks[i, :len(c)] = np.asarray(c, np.int64)[:max_len]
        pad_lens = np.ones(rows_b, np.int32)
        pad_lens[:rows] = lens
        ks = np.asarray(self._fn(rows_b, max_len, k)(toks, pad_lens))
        out = []
        for i in range(rows):
            # never propose past the position window (the engine caps
            # against its own page/budget limits on top)
            room = max(0, self.max_position - int(lens[i]) - 1)
            out.append(ks[i, :min(k, room)].astype(np.int64))
        return out

"""paddle_tpu.generation — paged KV-cache + continuous-batching
autoregressive decode (the stateful LLM serving lane).

The serving subsystem (PR 3) coalesces stateless predict calls; this
package serves the workload that made TPU serving hard: autoregressive
decode under heavy concurrent traffic. K/V lives in fixed-size pages
behind per-sequence block tables (Ragged Paged Attention,
arXiv:2604.15464); ONE ragged [lanes, chunk] executable serves mixed
prefill chunks, decode rows and speculative-verify rows side by side
(mode="ragged", the default — "two_lane" retains the PR-6
prefill/decode lane pair as the token-identity oracle); sequences
join/leave the running batch every step; every token streams to its
caller the moment it is sampled. Long prompts prefill in chunks
across steps (decode ITL never stalls on a fat prompt); a draft model
(draft.HostDraft or any DraftModel) + spec_tokens turns on
speculative decoding (greedy-identical by construction); kv_dtype=
"int8" quantizes the page pools for ~2x+ resident sequences per byte
budget; and generation_prefix_cache turns on the radix KV cache —
per-page refcounts + a token-keyed prefix trie, so prompts sharing a
prefix attach its pages copy-on-write and prefill only their suffix
(ragged engine only; see PagedKVCache.acquire/publish/release).

    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu import generation

    main, startup, feeds, fetches = generation.build_lm_program(cfg, 64)
    ...train / load...; fluid.io.save_inference_model(d, ["tokens"],
                                                      [fetches["logits"]], exe, main)
    pred = create_predictor(Config(d))
    eng = generation.GenerationEngine(pred, cfg)     # cfg: GPTConfig
    for tok in eng.submit([1, 5, 9], max_new_tokens=32, eos_id=2):
        ...                                          # streamed tokens
    eng.close(drain=True)

`serving.ServingServer(serve_engine, generation_engine=eng)` exposes
the streamed `POST /v1/generate` HTTP endpoint. Flags: the
``generation_*`` family (flags.py). The decode attention kernel is
``paddle_tpu.kernels.paged_attention`` (Mosaic on TPU, pure-JAX
reference on CPU CI).
"""

from .draft import DraftModel, HostDraft
from .engine import GenerationEngine, GenerationMetrics, GenerationStream
from .kvcache import PagedKVCache, PagePoolExhausted
from .model import (CacheGeometry, GPTConfig, build_decode_program,
                    build_lm_program, build_prefill_program,
                    build_ragged_step_program)

__all__ = [
    "GenerationEngine",
    "GenerationStream",
    "GenerationMetrics",
    "PagedKVCache",
    "PagePoolExhausted",
    "CacheGeometry",
    "GPTConfig",
    "DraftModel",
    "HostDraft",
    "build_lm_program",
    "build_prefill_program",
    "build_decode_program",
    "build_ragged_step_program",
]

"""Checkpoint save/load + inference-model export.

Reference: python/paddle/fluid/io.py — save/load_persistables (:556,
:834) iterate persistable vars and run save/load ops;
save/load_inference_model (:1022, :1229) prune the program to
feed/fetch targets; single-file save/load (:1507, :1565).

TPU-native format: one .npz per save directory (or single file) holding
each persistable var by name + a JSON program description. Same
"persistables by name" semantics; no bit-compat with the reference's
binary LoD tensor format (documented divergence).
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional

import numpy as np

from .core import framework
from .core.executor import Executor, Scope, global_scope
from .core.framework import Program, Variable

__all__ = [
    "get_program_parameter", "get_program_persistable_vars",
    "load_program_state", "set_program_state", "batch",
    "save_vars",
    "save_params",
    "save_persistables",
    "load_vars",
    "load_params",
    "load_persistables",
    "save",
    "load",
    "save_inference_model",
    "load_inference_model",
]

_PARAMS_FILE = "__params__.npz"
_MODEL_FILE = "__model__"


def _persistable_vars(program: Program) -> List[Variable]:
    return [
        v
        for v in program.global_block().vars.values()
        if v.persistable and not v.is_data
    ]


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None, filename=None):
    main_program = main_program or framework.default_main_program()
    if vars is None:
        vars = [v for v in main_program.global_block().vars.values() if predicate is None or predicate(v)]
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    for v in vars:
        val = scope.find_var(v.name)
        if val is None:
            continue
        arrays[v.name] = np.asarray(val)
    np.savez(os.path.join(dirname, filename or _PARAMS_FILE), **arrays)


def save_params(executor, dirname, main_program=None, filename=None):
    main_program = main_program or framework.default_main_program()
    save_vars(
        executor,
        dirname,
        main_program,
        vars=[p for p in main_program.all_parameters()],
        filename=filename,
    )


def save_persistables(executor, dirname, main_program=None, filename=None):
    main_program = main_program or framework.default_main_program()
    save_vars(
        executor, dirname, main_program, vars=_persistable_vars(main_program),
        filename=filename,
    )


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None, filename=None):
    import jax.numpy as jnp

    main_program = main_program or framework.default_main_program()
    if vars is None:
        vars = [v for v in main_program.global_block().vars.values() if predicate is None or predicate(v)]
    path = os.path.join(dirname, filename or _PARAMS_FILE)
    data = np.load(path)
    scope = global_scope()
    for v in vars:
        if v.name in data:
            scope.set_var(v.name, jnp.asarray(data[v.name]))


def load_params(executor, dirname, main_program=None, filename=None):
    main_program = main_program or framework.default_main_program()
    load_vars(
        executor, dirname, main_program, vars=list(main_program.all_parameters()),
        filename=filename,
    )


def load_persistables(executor, dirname, main_program=None, filename=None):
    main_program = main_program or framework.default_main_program()
    load_vars(
        executor, dirname, main_program, vars=_persistable_vars(main_program),
        filename=filename,
    )


def save(program: Program, model_path: str):
    """Single-call whole-state save (reference io.py:1507): program IR +
    all persistables."""
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    scope = global_scope()
    arrays = {}
    for v in _persistable_vars(program):
        val = scope.find_var(v.name)
        if val is not None:
            arrays[v.name] = np.asarray(val)
    np.savez(model_path + ".pdparams.npz", **arrays)
    with open(model_path + ".pdmodel.json", "w") as f:
        f.write(program.to_json())


def load(program: Program, model_path: str, executor=None):
    import jax.numpy as jnp

    data = np.load(model_path + ".pdparams.npz")
    scope = global_scope()
    for name in data.files:
        scope.set_var(name, jnp.asarray(data[name]))


def _prune_program(program: Program, feed_names, target_vars) -> Program:
    """Keep only ops needed to compute targets from feeds (reference
    Program._prune)."""
    pruned = Program.from_dict(program.to_dict())
    block = pruned.global_block()
    needed = {v.name if isinstance(v, Variable) else str(v) for v in target_vars}
    keep = []
    for op in reversed(block.ops):
        if set(op.output_arg_names) & needed:
            keep.append(op)
            needed |= {n for n in op.input_arg_names}
    block.ops = list(reversed(keep))
    pruned._bump()
    return pruned


def save_inference_model(
    dirname,
    feeded_var_names,
    target_vars,
    executor,
    main_program=None,
    model_filename=None,
    params_filename=None,
    export_for_deployment=True,
    program_only=False,
):
    main_program = main_program or framework.default_main_program()
    os.makedirs(dirname, exist_ok=True)
    inference_program = _prune_program(main_program, feeded_var_names, target_vars)
    meta = {
        "program": inference_program.to_dict(),
        "feed_names": list(feeded_var_names),
        "fetch_names": [
            v.name if isinstance(v, Variable) else str(v) for v in target_vars
        ],
    }
    with open(os.path.join(dirname, model_filename or _MODEL_FILE), "w") as f:
        json.dump(meta, f)
    if not program_only:
        save_persistables(executor, dirname, inference_program, params_filename)
    return meta["fetch_names"]


def load_inference_model(
    dirname, executor, model_filename=None, params_filename=None
):
    with open(os.path.join(dirname, model_filename or _MODEL_FILE)) as f:
        meta = json.load(f)
    program = Program.from_dict(meta["program"])
    load_persistables(executor, dirname, program, params_filename)
    block = program.global_block()
    fetch_vars = [block.var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars


# -- sharded / async checkpointing (orbax + multi-host) ----------------------

# Commit protocol (resilience/): a checkpoint directory is COMMITTED
# only once it contains this marker, written AFTER every array file has
# landed. The marker carries a manifest (relative path -> size) of the
# directory at commit time, so a later truncation (crash during GC,
# fault injection, partial copy) is detected, plus caller `extra`
# metadata — the supervisor stores step counter, RNG state and reader
# position here, alongside the persistables.
#
# Multi-host (jax.process_count() > 1) extends this to a TWO-PHASE
# commit over a shared filesystem: every rank writes its own shard file
# plus a shard-done file (phase 1), and process 0 stamps the one commit
# marker only after every rank's done-file — with a matching save nonce
# — is present (phase 2). A host that dies mid-save leaves its
# done-file missing, so the marker is never written and resume falls
# back to the previous committed checkpoint; a torn multi-host
# checkpoint is unobservable by construction.
_COMMIT_MARKER = "_PT_COMMIT.json"
_SHARD_DONE_PREFIX = "_PT_SHARD_DONE."
_STAGE_READY = "_PT_STAGE_READY"
_SHARD_FILE = "__shards__.rank{rank}.npz"
_SHARD_META = "__shards__.meta.json"

# test hook: (rank, world) override so the two-phase protocol is unit-
# testable without spawning a jax.distributed world
_FORCE_DIST = None

# per-process save sequence number, part of the save nonce. Every rank
# executes the same sequence of saves (SPMD), so the counter stays
# aligned across ranks while making each save ATTEMPT's nonce unique —
# a crashed attempt's leftover done-files can never satisfy a later
# attempt's phase-2 wait.
_SAVE_SEQ = [0]


class CheckpointCommitTimeout(RuntimeError):
    """Phase 2 of a multi-host checkpoint commit timed out — some
    rank's shard-done file (or process 0's commit marker) never
    arrived. The save FAILED; no marker was (or will be) written for
    it. In a supervised run the step-level retry / the elastic
    launcher's world restart owns recovery."""


def _dist_info():
    """(process_rank, world_size) — the multi-host checkpoint layout
    switch. ``_FORCE_DIST`` lets tests exercise the protocol without a
    real jax.distributed world."""
    if _FORCE_DIST is not None:
        return _FORCE_DIST
    try:
        import jax

        if jax.process_count() > 1:
            return jax.process_index(), jax.process_count()
    except Exception:  # noqa: BLE001 — jax absent/uninitialized: lone writer
        pass
    return 0, 1


def _checkpoint_manifest(path):
    out = {}
    for root, _, files in os.walk(path):
        for fn in files:
            if fn == _COMMIT_MARKER:
                continue
            full = os.path.join(root, fn)
            out[os.path.relpath(full, path)] = os.path.getsize(full)
    return out


def _is_commit_process():
    """Mesh-aware commit protocol: every process saves its OWN
    addressable shards (orbax coordinates the array writes), but
    exactly one process — process 0 — stamps the commit marker, after
    the collective save completed. A marker written by a straggler
    while another process's shards were still in flight would publish
    a checkpoint the resume path believes complete. Single-process
    (including the 8-emulated-host-device CI mesh) is trivially
    process 0."""
    try:
        import jax

        return jax.process_index() == 0
    except Exception:  # noqa: BLE001 — jax not initialized: lone writer
        return True


def write_commit_marker(path, extra=None):
    """Mark a checkpoint directory committed. Written atomically (temp
    + rename) so a crash mid-write leaves no marker — i.e. the dir
    stays uncommitted — never a truncated JSON that half-parses."""
    marker = {
        "manifest": _checkpoint_manifest(path),
        "commit_time": time.time(),
        "extra": dict(extra or {}),
    }
    tmp = os.path.join(path, _COMMIT_MARKER + ".tmp")
    with open(tmp, "w") as f:
        json.dump(marker, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(path, _COMMIT_MARKER))
    return marker


def read_commit_marker(path):
    """The commit marker dict, or None when the dir is uncommitted (no
    marker / unparseable marker)."""
    try:
        with open(os.path.join(path, _COMMIT_MARKER)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def is_committed_checkpoint(path):
    """True when `path` holds a complete, committed checkpoint.

    Marker present -> verify every manifest file still exists with its
    committed size (catches truncation after commit). No marker ->
    legacy fallback: accept only directories orbax itself finalized
    (its _CHECKPOINT_METADATA lands last), so checkpoints written
    before this protocol existed still resume, while a crash
    mid-`save_checkpoint` is never picked up.
    """
    if not os.path.isdir(path):
        return False
    marker = read_commit_marker(path)
    if marker is not None:
        for rel, size in marker.get("manifest", {}).items():
            full = os.path.join(path, rel)
            try:
                if os.path.getsize(full) != size:
                    return False
            except OSError:
                return False
        return True
    return os.path.isfile(os.path.join(path, "_CHECKPOINT_METADATA"))


# -- two-phase cross-host commit ---------------------------------------------


def _atomic_json(path, payload):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def write_shard_done(path, rank, nonce):
    """Phase 1, per rank: mark this rank's shards durable for the save
    attempt identified by ``nonce``. Atomic (temp + rename) — a crash
    mid-write leaves no done-file, i.e. the rank counts as NOT done."""
    _atomic_json(os.path.join(path, f"{_SHARD_DONE_PREFIX}{rank}"),
                 {"rank": int(rank), "nonce": str(nonce)})


def done_shard_ranks(path, world, nonce):
    """Ranks whose phase-1 done-file for THIS save attempt is present.
    Done-files from a crashed earlier attempt carry a different nonce
    and never count — process 0 can't be tricked into committing a
    directory whose shard data is part-old, part-new."""
    done = []
    for rank in range(int(world)):
        try:
            with open(os.path.join(
                    path, f"{_SHARD_DONE_PREFIX}{rank}")) as f:
                if str(json.load(f).get("nonce")) == str(nonce):
                    done.append(rank)
        except (OSError, ValueError):
            continue
    return done


def finalize_two_phase_commit(path, world, extra=None, nonce=None,
                              timeout_s=None, poll_s=0.05):
    """Phase 2, process 0 only: wait until EVERY rank's shard-done file
    for this save attempt is present, then stamp the one commit marker
    (its manifest covers every rank's files). A rank that died mid-save
    keeps its done-file missing, the wait times out, and the directory
    stays uncommitted forever — ``latest_checkpoint`` will never select
    it. Raises ``CheckpointCommitTimeout`` naming the missing ranks."""
    from .flags import flag

    world = int(world)
    timeout_s = (float(flag("dist_commit_timeout_s"))
                 if timeout_s is None else float(timeout_s))
    deadline = time.time() + timeout_s
    while True:
        done = done_shard_ranks(path, world, nonce)
        if len(done) >= world:
            break
        if time.time() >= deadline:
            missing = sorted(set(range(world)) - set(done))
            raise CheckpointCommitTimeout(
                f"two-phase commit of {path!r}: rank(s) {missing} never "
                f"wrote their shard-done file within {timeout_s:.0f}s "
                f"(save nonce {nonce!r}) — a host likely died mid-save; "
                "the checkpoint stays UNCOMMITTED and resume will use "
                "the previous committed one")
        time.sleep(poll_s)
    marker_extra = dict(extra or {})
    marker_extra.setdefault("world", world)
    marker_extra["commit_nonce"] = str(nonce)
    return write_commit_marker(path, marker_extra)


def _wait_for_marker(paths, nonce, timeout_s, poll_s=0.05):
    """Non-zero ranks' phase-2 wait: block until process 0's commit
    marker for THIS attempt appears at any of ``paths`` (staging or its
    published location — the rename can land between polls)."""
    deadline = time.time() + timeout_s
    while True:
        for p in paths:
            marker = read_commit_marker(p)
            if marker is not None and \
                    str(marker.get("extra", {}).get("commit_nonce")) \
                    == str(nonce):
                return p
        if time.time() >= deadline:
            raise CheckpointCommitTimeout(
                f"two-phase commit of {paths[0]!r}: process 0 never "
                f"stamped the commit marker within {timeout_s:.0f}s "
                f"(save nonce {nonce!r}) — process 0 likely died "
                "mid-commit; the save FAILED on this rank too")
        time.sleep(poll_s)


def _index_key(name, index, shape):
    """``name@start-stop;start-stop...`` — one npz key per owned shard,
    reversible by ``_parse_index_key``."""
    parts = []
    for sl, dim in zip(index, shape):
        start, stop, _ = sl.indices(int(dim))
        parts.append(f"{start}-{stop}")
    return f"{name}@{';'.join(parts)}" if parts else name


def _parse_index_key(key):
    """Inverse of ``_index_key``: (name, [(start, stop), ...]) — or
    (key, None) for an unsharded full-value entry."""
    name, _, idx = key.rpartition("@")
    if name and all(
            p.count("-") == 1
            and all(x.isdigit() for x in p.split("-"))
            for p in idx.split(";")):
        return name, [tuple(int(x) for x in p.split("-"))
                      for p in idx.split(";")]
    return key, None


def _save_checkpoint_multihost(path, state, extra, rank, world,
                               publish_path=None, timeout_s=None,
                               nonce=None):
    """The multi-host save: every rank writes the shards it OWNS into
    its own ``__shards__.rank<k>.npz`` (genuinely non-addressable
    jax.Arrays contribute each replica-0 addressable shard under an
    offset key; replicated/host values are round-robined over ranks so
    write bandwidth scales with the pod), then the two-phase commit
    publishes the marker. Requires ``path`` on a filesystem all hosts
    share — the same contract every multi-host checkpoint format has."""
    import jax

    from .flags import flag
    from .resilience.faults import check_save_kill

    timeout_s = (float(flag("dist_commit_timeout_s"))
                 if timeout_s is None else float(timeout_s))
    if nonce is None:
        # unique per save ATTEMPT yet identical across ranks: every
        # rank executes the same SPMD sequence of saves, so the
        # per-process counter stays aligned; the restart generation
        # keeps a resumed world's nonces distinct from the crashed one
        _SAVE_SEQ[0] += 1
        nonce = (f"{extra.get('step', '')}:{extra.get('run_counter', '')}:"
                 f"g{os.environ.get('PADDLE_RESTART_COUNT', '0')}:"
                 f"s{_SAVE_SEQ[0]}")

    # stage-ready handshake: rank 0 clears debris a crashed earlier
    # attempt left in this directory (stale done-files/shards from a
    # possibly DIFFERENT world size would otherwise leak into the
    # manifest and the restore), then posts the ready token; other
    # ranks write nothing until they see THIS attempt's token.
    ready = os.path.join(path, _STAGE_READY)
    if rank == 0:
        os.makedirs(path, exist_ok=True)
        for entry in os.listdir(path):
            if entry.startswith((_SHARD_DONE_PREFIX, "__shards__.",
                                 _COMMIT_MARKER, _STAGE_READY)):
                try:
                    os.remove(os.path.join(path, entry))
                except OSError:
                    pass
        _atomic_json(ready, {"nonce": nonce, "world": world})
    else:
        deadline = time.time() + timeout_s
        while True:
            try:
                with open(ready) as f:
                    if str(json.load(f).get("nonce")) == nonce:
                        break
            except (OSError, ValueError):
                pass
            if time.time() >= deadline:
                raise CheckpointCommitTimeout(
                    f"two-phase commit of {path!r}: process 0 never "
                    f"posted the stage-ready token within "
                    f"{timeout_s:.0f}s (nonce {nonce!r})")
            time.sleep(0.05)

    arrays = {}
    meta_vars = {}
    for i, name in enumerate(sorted(state)):
        val = state[name]
        if isinstance(val, jax.Array) and not val.is_fully_addressable:
            # genuinely non-addressable: this process can only see its
            # local shards — write each replica-0 shard it holds
            for sh in val.addressable_shards:
                if sh.replica_id != 0:
                    continue
                arrays[_index_key(name, sh.index, val.shape)] = \
                    np.asarray(sh.data)
            meta_vars[name] = {"shape": [int(d) for d in val.shape],
                               "dtype": str(np.dtype(val.dtype)),
                               "sharded": True}
        else:
            # replicated / host value: identical on every rank (the
            # deterministic-replay contract), so exactly one rank —
            # round-robin by position — writes it
            if i % world == rank:
                arrays[name] = np.asarray(val)
            meta_vars[name] = {"sharded": False, "owner": i % world}
    shard_path = os.path.join(path, _SHARD_FILE.format(rank=rank))
    tmp = f"{shard_path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, shard_path)
    if rank == 0:
        _atomic_json(os.path.join(path, _SHARD_META),
                     {"format": 1, "world": world, "nonce": nonce,
                      "vars": meta_vars})

    # deterministic fault injection point: a `killsave@N` fault dies
    # HERE — shards durable, done-file missing — the exact torn-save
    # scenario phase 2 exists to absorb
    check_save_kill("before_shard_done")
    write_shard_done(path, rank, nonce)

    if rank == 0:
        finalize_two_phase_commit(path, world, extra=extra, nonce=nonce,
                                  timeout_s=timeout_s)
    else:
        candidates = [path] + ([publish_path] if publish_path else [])
        _wait_for_marker(candidates, nonce, timeout_s)
    return None


def _is_multihost_checkpoint(path):
    return os.path.isfile(os.path.join(path, _SHARD_META))


def load_checkpoint_arrays(path):
    """Read a committed checkpoint directory into {var_name: np.array}
    without touching any scope — both formats (orbax single-host,
    multi-host ``__shards__`` rank files). Sharded vars are assembled
    from every rank's offset-keyed entries; missing coverage raises."""
    if _is_multihost_checkpoint(path):
        with open(os.path.join(path, _SHARD_META)) as f:
            meta = json.load(f)
        state = {}
        filled = {}
        for entry in sorted(os.listdir(path)):
            if not (entry.startswith("__shards__.rank")
                    and entry.endswith(".npz")):
                continue
            with np.load(os.path.join(path, entry)) as z:
                for key in z.files:
                    name, idx = _parse_index_key(key)
                    if idx is None:
                        state[name] = z[key]
                        continue
                    info = meta["vars"].get(name)
                    if info is None or not info.get("sharded"):
                        state[name] = z[key]
                        continue
                    if name not in state:
                        state[name] = np.zeros(
                            tuple(info["shape"]),
                            dtype=np.dtype(info["dtype"]))
                        filled[name] = 0
                    sel = tuple(slice(a, b) for a, b in idx)
                    state[name][sel] = z[key]
                    filled[name] += int(
                        np.prod([b - a for a, b in idx]))
        short = {n: (filled[n], int(np.prod(meta["vars"][n]["shape"])))
                 for n in filled
                 if filled[n] < np.prod(meta["vars"][n]["shape"])}
        if short:
            raise ValueError(
                f"multi-host checkpoint {path!r} is missing shard "
                f"coverage for {sorted(short)} (filled/total elements "
                f"{short}) — a rank's shard file is absent or truncated")
        missing = sorted(set(meta["vars"]) - set(state))
        if missing:
            raise ValueError(
                f"multi-host checkpoint {path!r} is missing vars "
                f"{missing[:5]}{'...' if len(missing) > 5 else ''} — "
                "an owning rank's shard file never landed")
        return state
    import orbax.checkpoint as ocp

    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    return {k: np.asarray(v) for k, v in ckptr.restore(path).items()}


def save_checkpoint(dirname, main_program=None, scope=None, step=None,
                    async_save=False, extra=None, publish_path=None):
    """Sharded checkpoint of all persistables via orbax (SURVEY §5's
    checkpoint/resume target; reference io.py save_persistables +
    fleet util checkpoints, but TPU-native: device/GSPMD-sharded
    arrays are saved in their sharded layout without gathering to one
    host, and async_save overlaps the write with training — orbax's
    job, the reference's CheckpointNotifyOp analogue).

    Every completed save is stamped with a commit marker (manifest +
    caller `extra` metadata); `latest_checkpoint` only ever selects
    committed directories, so a crash mid-save can never be resumed
    from. Async saves commit from a background thread once the write
    lands.

    Multi-host (jax.process_count() > 1): every rank writes its OWN
    shards (non-addressable arrays contribute their local replica-0
    shards; replicated values round-robin across ranks) into a shared
    directory, and the TWO-PHASE protocol — per-rank shard-done files,
    then the process-0 marker — guarantees a host killed mid-save never
    yields a committed checkpoint. ``publish_path`` names where the
    directory will be renamed after commit (CheckpointPolicy's staging
    flow) so non-zero ranks can find the marker either place; async
    saves degrade to sync in this mode (the commit IS the sync point)."""
    import orbax.checkpoint as ocp

    main_program = main_program or framework.default_main_program()
    scope = scope or global_scope()
    state = {}
    for v in _persistable_vars(main_program):
        val = scope.find_var(v.name)
        if val is not None:
            state[v.name] = val
    path = os.path.abspath(dirname)
    if step is not None:
        path = os.path.join(path, str(int(step)))
    rank, world = _dist_info()
    if world > 1:
        return _save_checkpoint_multihost(
            path, state, dict(extra or {}), rank, world,
            publish_path=publish_path)
    if async_save:
        import threading

        ckptr = _async_checkpointer()
        ckptr.save(path, state, force=True)
        # commit once the write lands; wait_until_finished blocks until
        # every save issued so far has finalized, so the marker can
        # only ever cover a complete directory. Non-daemon: interpreter
        # exit must not strand a finished write uncommitted (the same
        # guarantee the atexit wait gives the data itself).
        commit_err: list = []

        def _commit():
            try:
                ckptr.wait_until_finished()
                if _is_commit_process():
                    write_commit_marker(path, extra)
            except BaseException as e:  # noqa: BLE001 — re-raised at wait
                commit_err.append(e)
                raise

        committer = threading.Thread(target=_commit)
        committer.start()
        # the caller's wait must cover the COMMIT, not just the data —
        # otherwise a restore racing the marker thread reads the dir as
        # committed-without-extra (legacy fallback) and loses the
        # resume metadata. Commit failures surface there too instead of
        # dying silently with the thread.
        return _AsyncSaveHandle(ckptr, committer, commit_err)
    ocp.Checkpointer(ocp.StandardCheckpointHandler()).save(
        path, state, force=True)
    if _is_commit_process():
        write_commit_marker(path, extra)
    return None


class _AsyncSaveHandle:
    """Handle for one async save: ``wait_until_finished`` blocks until
    the data AND its commit marker are on disk, re-raising any commit
    failure. Other attributes delegate to the shared
    AsyncCheckpointer."""

    def __init__(self, ckptr, committer, commit_err):
        self._ckptr = ckptr
        self._committer = committer
        self._commit_err = commit_err

    def wait_until_finished(self):
        self._ckptr.wait_until_finished()
        self._committer.join()
        if self._commit_err:
            raise self._commit_err[0]

    def __getattr__(self, name):
        return getattr(self._ckptr, name)


_ASYNC_CKPTR = None


def _async_checkpointer():
    """One shared AsyncCheckpointer: per-call instances leak thread
    pools, and an atexit wait guarantees a fire-and-forget save still
    lands before interpreter exit."""
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        import atexit

        import orbax.checkpoint as ocp

        _ASYNC_CKPTR = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        atexit.register(_ASYNC_CKPTR.wait_until_finished)
    return _ASYNC_CKPTR


def load_checkpoint(dirname, main_program=None, scope=None, step=None,
                    mesh=None):
    """Restore persistables saved by save_checkpoint. Arrays land as
    UNCOMMITTED host values: a checkpoint written on one device
    topology (say dp4) must resume on another (dp2, single chip) — the
    next compile re-places them per ITS mesh, so sharding is a property
    of the compile, not of the checkpoint (elastic resume; the
    reference only restarts on the same topology).

    ``mesh`` (optional) asks for a STRICT topology check: when the
    commit marker records the mesh shape that produced this trajectory
    (the Supervisor stamps it) and it differs from ``mesh``'s, the load
    refuses with an error naming both shapes — instead of the cryptic
    shard-count mismatch the assembly would otherwise die with deep in
    the restore. Multi-host resumes (the Supervisor passes its mesh
    automatically when jax.process_count() > 1) get this check by
    default; single-host elastic resume stays unrestricted."""
    import numpy as np

    main_program = main_program or framework.default_main_program()
    scope = scope or global_scope()
    path = os.path.abspath(dirname)
    if step is not None:
        path = os.path.join(path, str(int(step)))
    if not is_committed_checkpoint(path):
        raise ValueError(
            f"checkpoint {path!r} is uncommitted or corrupt (missing/"
            "invalid commit marker, or manifest files truncated) — it "
            "was likely interrupted mid-save; resume from "
            "latest_checkpoint(), which skips such directories"
        )
    extra = (read_commit_marker(path) or {}).get("extra", {})
    if mesh is not None and extra.get("mesh"):
        want = {str(k): int(v) for k, v in dict(mesh.shape).items()} \
            if hasattr(mesh, "shape") else \
            {str(k): int(v) for k, v in dict(mesh).items()}
        have = {str(k): int(v) for k, v in dict(extra["mesh"]).items()}
        if want != have:
            raise ValueError(
                f"checkpoint {path!r} was committed on mesh {have} but "
                f"the current mesh is {want} — refusing the strict "
                "(mesh=...) restore. Resume on the matching topology, or "
                "load without mesh= for an elastic restore that re-places "
                "arrays under the next compile")
    state = load_checkpoint_arrays(path)
    for name, val in state.items():
        scope.set_var(name, np.asarray(val))
    return sorted(state)


def latest_checkpoint(dirname):
    """Highest COMMITTED numeric step directory under dirname (resume
    helper). Directories left by a crash mid-`save_checkpoint` — no
    commit marker, or a manifest whose files were truncated — are
    skipped, so resume can never pick up a half-written checkpoint."""
    if not os.path.isdir(dirname):
        return None
    steps = [
        int(d) for d in os.listdir(dirname)
        if d.isdigit() and is_committed_checkpoint(os.path.join(dirname, d))
    ]
    return max(steps) if steps else None


def committed_checkpoint_steps(dirname):
    """All committed step directories under dirname, ascending (the
    retention-GC and rollback helpers iterate this)."""
    if not os.path.isdir(dirname):
        return []
    return sorted(
        int(d) for d in os.listdir(dirname)
        if d.isdigit() and is_committed_checkpoint(os.path.join(dirname, d))
    )


def get_program_parameter(program):
    """Reference io.py: all Parameters of a program."""
    from .core.framework import Parameter

    return [v for v in program.global_block().vars.values()
            if isinstance(v, Parameter)]


def get_program_persistable_vars(program):
    return _persistable_vars(program)


def load_program_state(model_path, var_list=None):
    """Reference io.py:2004-ish — read a saved state into a dict."""
    import os

    import numpy as np

    state = {}
    # accept: exact file, <path>.npz, fluid.save's <path>.pdparams.npz,
    # or a directory of per-var .npy files
    candidates = [model_path, model_path + ".npz",
                  model_path + ".pdparams.npz", model_path + ".pdparams"]
    archive = next((c for c in candidates if os.path.isfile(c)), None)
    if archive is not None:
        z = np.load(archive)
        state = {k: z[k] for k in z.files}
    else:
        for fn in os.listdir(model_path):
            if fn.endswith(".npy"):
                state[fn[:-4]] = np.load(os.path.join(model_path, fn))
    if var_list is not None:
        names = {v.name if hasattr(v, "name") else str(v) for v in var_list}
        state = {k: v for k, v in state.items() if k in names}
    return state


def set_program_state(program, state_dict):
    """Reference io.py set_program_state: write values into the current
    scope for the program's matching persistables."""
    import jax.numpy as jnp

    from .core.executor import global_scope

    scope = global_scope()
    n = 0
    for v in _persistable_vars(program):
        if v.name in state_dict:
            scope.set_var(v.name, jnp.asarray(state_dict[v.name]))
            n += 1
    return n


def batch(reader, batch_size, drop_last=False):
    """Reference fluid.io.batch (paddle.batch): group a sample reader
    into batches."""

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched

"""Checkpoint save/load + inference-model export.

Reference: python/paddle/fluid/io.py — save/load_persistables (:556,
:834) iterate persistable vars and run save/load ops;
save/load_inference_model (:1022, :1229) prune the program to
feed/fetch targets; single-file save/load (:1507, :1565).

TPU-native format: one .npz per save directory (or single file) holding
each persistable var by name + a JSON program description. Same
"persistables by name" semantics; no bit-compat with the reference's
binary LoD tensor format (documented divergence).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from .core import framework
from .core.executor import Executor, Scope, global_scope
from .core.framework import Program, Variable

__all__ = [
    "get_program_parameter", "get_program_persistable_vars",
    "load_program_state", "set_program_state", "batch",
    "save_vars",
    "save_params",
    "save_persistables",
    "load_vars",
    "load_params",
    "load_persistables",
    "save",
    "load",
    "save_inference_model",
    "load_inference_model",
]

_PARAMS_FILE = "__params__.npz"
_MODEL_FILE = "__model__"


def _persistable_vars(program: Program) -> List[Variable]:
    return [
        v
        for v in program.global_block().vars.values()
        if v.persistable and not v.is_data
    ]


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None, filename=None):
    main_program = main_program or framework.default_main_program()
    if vars is None:
        vars = [v for v in main_program.global_block().vars.values() if predicate is None or predicate(v)]
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    for v in vars:
        val = scope.find_var(v.name)
        if val is None:
            continue
        arrays[v.name] = np.asarray(val)
    np.savez(os.path.join(dirname, filename or _PARAMS_FILE), **arrays)


def save_params(executor, dirname, main_program=None, filename=None):
    main_program = main_program or framework.default_main_program()
    save_vars(
        executor,
        dirname,
        main_program,
        vars=[p for p in main_program.all_parameters()],
        filename=filename,
    )


def save_persistables(executor, dirname, main_program=None, filename=None):
    main_program = main_program or framework.default_main_program()
    save_vars(
        executor, dirname, main_program, vars=_persistable_vars(main_program),
        filename=filename,
    )


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None, filename=None):
    import jax.numpy as jnp

    main_program = main_program or framework.default_main_program()
    if vars is None:
        vars = [v for v in main_program.global_block().vars.values() if predicate is None or predicate(v)]
    path = os.path.join(dirname, filename or _PARAMS_FILE)
    data = np.load(path)
    scope = global_scope()
    for v in vars:
        if v.name in data:
            scope.set_var(v.name, jnp.asarray(data[v.name]))


def load_params(executor, dirname, main_program=None, filename=None):
    main_program = main_program or framework.default_main_program()
    load_vars(
        executor, dirname, main_program, vars=list(main_program.all_parameters()),
        filename=filename,
    )


def load_persistables(executor, dirname, main_program=None, filename=None):
    main_program = main_program or framework.default_main_program()
    load_vars(
        executor, dirname, main_program, vars=_persistable_vars(main_program),
        filename=filename,
    )


def save(program: Program, model_path: str):
    """Single-call whole-state save (reference io.py:1507): program IR +
    all persistables."""
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    scope = global_scope()
    arrays = {}
    for v in _persistable_vars(program):
        val = scope.find_var(v.name)
        if val is not None:
            arrays[v.name] = np.asarray(val)
    np.savez(model_path + ".pdparams.npz", **arrays)
    with open(model_path + ".pdmodel.json", "w") as f:
        f.write(program.to_json())


def load(program: Program, model_path: str, executor=None):
    import jax.numpy as jnp

    data = np.load(model_path + ".pdparams.npz")
    scope = global_scope()
    for name in data.files:
        scope.set_var(name, jnp.asarray(data[name]))


def _prune_program(program: Program, feed_names, target_vars) -> Program:
    """Keep only ops needed to compute targets from feeds (reference
    Program._prune)."""
    pruned = Program.from_dict(program.to_dict())
    block = pruned.global_block()
    needed = {v.name if isinstance(v, Variable) else str(v) for v in target_vars}
    keep = []
    for op in reversed(block.ops):
        if set(op.output_arg_names) & needed:
            keep.append(op)
            needed |= {n for n in op.input_arg_names}
    block.ops = list(reversed(keep))
    pruned._bump()
    return pruned


def save_inference_model(
    dirname,
    feeded_var_names,
    target_vars,
    executor,
    main_program=None,
    model_filename=None,
    params_filename=None,
    export_for_deployment=True,
    program_only=False,
):
    main_program = main_program or framework.default_main_program()
    os.makedirs(dirname, exist_ok=True)
    inference_program = _prune_program(main_program, feeded_var_names, target_vars)
    meta = {
        "program": inference_program.to_dict(),
        "feed_names": list(feeded_var_names),
        "fetch_names": [
            v.name if isinstance(v, Variable) else str(v) for v in target_vars
        ],
    }
    with open(os.path.join(dirname, model_filename or _MODEL_FILE), "w") as f:
        json.dump(meta, f)
    if not program_only:
        save_persistables(executor, dirname, inference_program, params_filename)
    return meta["fetch_names"]


def load_inference_model(
    dirname, executor, model_filename=None, params_filename=None
):
    with open(os.path.join(dirname, model_filename or _MODEL_FILE)) as f:
        meta = json.load(f)
    program = Program.from_dict(meta["program"])
    load_persistables(executor, dirname, program, params_filename)
    block = program.global_block()
    fetch_vars = [block.var(n) for n in meta["fetch_names"]]
    return program, meta["feed_names"], fetch_vars


# -- sharded / async checkpointing (orbax) ----------------------------------

# Commit protocol (resilience/): a checkpoint directory is COMMITTED
# only once it contains this marker, written AFTER every array file has
# landed. The marker carries a manifest (relative path -> size) of the
# directory at commit time, so a later truncation (crash during GC,
# fault injection, partial copy) is detected, plus caller `extra`
# metadata — the supervisor stores step counter, RNG state and reader
# position here, alongside the persistables.
_COMMIT_MARKER = "_PT_COMMIT.json"


def _checkpoint_manifest(path):
    out = {}
    for root, _, files in os.walk(path):
        for fn in files:
            if fn == _COMMIT_MARKER:
                continue
            full = os.path.join(root, fn)
            out[os.path.relpath(full, path)] = os.path.getsize(full)
    return out


def _is_commit_process():
    """Mesh-aware commit protocol: every process saves its OWN
    addressable shards (orbax coordinates the array writes), but
    exactly one process — process 0 — stamps the commit marker, after
    the collective save completed. A marker written by a straggler
    while another process's shards were still in flight would publish
    a checkpoint the resume path believes complete. Single-process
    (including the 8-emulated-host-device CI mesh) is trivially
    process 0."""
    try:
        import jax

        return jax.process_index() == 0
    except Exception:  # noqa: BLE001 — jax not initialized: lone writer
        return True


def write_commit_marker(path, extra=None):
    """Mark a checkpoint directory committed. Written atomically (temp
    + rename) so a crash mid-write leaves no marker — i.e. the dir
    stays uncommitted — never a truncated JSON that half-parses."""
    import time

    marker = {
        "manifest": _checkpoint_manifest(path),
        "commit_time": time.time(),
        "extra": dict(extra or {}),
    }
    tmp = os.path.join(path, _COMMIT_MARKER + ".tmp")
    with open(tmp, "w") as f:
        json.dump(marker, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(path, _COMMIT_MARKER))
    return marker


def read_commit_marker(path):
    """The commit marker dict, or None when the dir is uncommitted (no
    marker / unparseable marker)."""
    try:
        with open(os.path.join(path, _COMMIT_MARKER)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def is_committed_checkpoint(path):
    """True when `path` holds a complete, committed checkpoint.

    Marker present -> verify every manifest file still exists with its
    committed size (catches truncation after commit). No marker ->
    legacy fallback: accept only directories orbax itself finalized
    (its _CHECKPOINT_METADATA lands last), so checkpoints written
    before this protocol existed still resume, while a crash
    mid-`save_checkpoint` is never picked up.
    """
    if not os.path.isdir(path):
        return False
    marker = read_commit_marker(path)
    if marker is not None:
        for rel, size in marker.get("manifest", {}).items():
            full = os.path.join(path, rel)
            try:
                if os.path.getsize(full) != size:
                    return False
            except OSError:
                return False
        return True
    return os.path.isfile(os.path.join(path, "_CHECKPOINT_METADATA"))


def save_checkpoint(dirname, main_program=None, scope=None, step=None,
                    async_save=False, extra=None):
    """Sharded checkpoint of all persistables via orbax (SURVEY §5's
    checkpoint/resume target; reference io.py save_persistables +
    fleet util checkpoints, but TPU-native: device/GSPMD-sharded
    arrays are saved in their sharded layout without gathering to one
    host, and async_save overlaps the write with training — orbax's
    job, the reference's CheckpointNotifyOp analogue).

    Every completed save is stamped with a commit marker (manifest +
    caller `extra` metadata); `latest_checkpoint` only ever selects
    committed directories, so a crash mid-save can never be resumed
    from. Async saves commit from a background thread once the write
    lands."""
    import orbax.checkpoint as ocp

    main_program = main_program or framework.default_main_program()
    scope = scope or global_scope()
    state = {}
    for v in _persistable_vars(main_program):
        val = scope.find_var(v.name)
        if val is not None:
            state[v.name] = val
    path = os.path.abspath(dirname)
    if step is not None:
        path = os.path.join(path, str(int(step)))
    if async_save:
        import threading

        ckptr = _async_checkpointer()
        ckptr.save(path, state, force=True)
        # commit once the write lands; wait_until_finished blocks until
        # every save issued so far has finalized, so the marker can
        # only ever cover a complete directory. Non-daemon: interpreter
        # exit must not strand a finished write uncommitted (the same
        # guarantee the atexit wait gives the data itself).
        commit_err: list = []

        def _commit():
            try:
                ckptr.wait_until_finished()
                if _is_commit_process():
                    write_commit_marker(path, extra)
            except BaseException as e:  # noqa: BLE001 — re-raised at wait
                commit_err.append(e)
                raise

        committer = threading.Thread(target=_commit)
        committer.start()
        # the caller's wait must cover the COMMIT, not just the data —
        # otherwise a restore racing the marker thread reads the dir as
        # committed-without-extra (legacy fallback) and loses the
        # resume metadata. Commit failures surface there too instead of
        # dying silently with the thread.
        return _AsyncSaveHandle(ckptr, committer, commit_err)
    ocp.Checkpointer(ocp.StandardCheckpointHandler()).save(
        path, state, force=True)
    if _is_commit_process():
        write_commit_marker(path, extra)
    return None


class _AsyncSaveHandle:
    """Handle for one async save: ``wait_until_finished`` blocks until
    the data AND its commit marker are on disk, re-raising any commit
    failure. Other attributes delegate to the shared
    AsyncCheckpointer."""

    def __init__(self, ckptr, committer, commit_err):
        self._ckptr = ckptr
        self._committer = committer
        self._commit_err = commit_err

    def wait_until_finished(self):
        self._ckptr.wait_until_finished()
        self._committer.join()
        if self._commit_err:
            raise self._commit_err[0]

    def __getattr__(self, name):
        return getattr(self._ckptr, name)


_ASYNC_CKPTR = None


def _async_checkpointer():
    """One shared AsyncCheckpointer: per-call instances leak thread
    pools, and an atexit wait guarantees a fire-and-forget save still
    lands before interpreter exit."""
    global _ASYNC_CKPTR
    if _ASYNC_CKPTR is None:
        import atexit

        import orbax.checkpoint as ocp

        _ASYNC_CKPTR = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        atexit.register(_ASYNC_CKPTR.wait_until_finished)
    return _ASYNC_CKPTR


def load_checkpoint(dirname, main_program=None, scope=None, step=None):
    """Restore persistables saved by save_checkpoint. Arrays land as
    UNCOMMITTED host values: a checkpoint written on one device
    topology (say dp4) must resume on another (dp2, single chip) — the
    next compile re-places them per ITS mesh, so sharding is a property
    of the compile, not of the checkpoint (elastic resume; the
    reference only restarts on the same topology)."""
    import numpy as np
    import orbax.checkpoint as ocp

    main_program = main_program or framework.default_main_program()
    scope = scope or global_scope()
    path = os.path.abspath(dirname)
    if step is not None:
        path = os.path.join(path, str(int(step)))
    if not is_committed_checkpoint(path):
        raise ValueError(
            f"checkpoint {path!r} is uncommitted or corrupt (missing/"
            "invalid commit marker, or manifest files truncated) — it "
            "was likely interrupted mid-save; resume from "
            "latest_checkpoint(), which skips such directories"
        )
    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    state = ckptr.restore(path)
    for name, val in state.items():
        scope.set_var(name, np.asarray(val))
    return sorted(state)


def latest_checkpoint(dirname):
    """Highest COMMITTED numeric step directory under dirname (resume
    helper). Directories left by a crash mid-`save_checkpoint` — no
    commit marker, or a manifest whose files were truncated — are
    skipped, so resume can never pick up a half-written checkpoint."""
    if not os.path.isdir(dirname):
        return None
    steps = [
        int(d) for d in os.listdir(dirname)
        if d.isdigit() and is_committed_checkpoint(os.path.join(dirname, d))
    ]
    return max(steps) if steps else None


def committed_checkpoint_steps(dirname):
    """All committed step directories under dirname, ascending (the
    retention-GC and rollback helpers iterate this)."""
    if not os.path.isdir(dirname):
        return []
    return sorted(
        int(d) for d in os.listdir(dirname)
        if d.isdigit() and is_committed_checkpoint(os.path.join(dirname, d))
    )


def get_program_parameter(program):
    """Reference io.py: all Parameters of a program."""
    from .core.framework import Parameter

    return [v for v in program.global_block().vars.values()
            if isinstance(v, Parameter)]


def get_program_persistable_vars(program):
    return _persistable_vars(program)


def load_program_state(model_path, var_list=None):
    """Reference io.py:2004-ish — read a saved state into a dict."""
    import os

    import numpy as np

    state = {}
    # accept: exact file, <path>.npz, fluid.save's <path>.pdparams.npz,
    # or a directory of per-var .npy files
    candidates = [model_path, model_path + ".npz",
                  model_path + ".pdparams.npz", model_path + ".pdparams"]
    archive = next((c for c in candidates if os.path.isfile(c)), None)
    if archive is not None:
        z = np.load(archive)
        state = {k: z[k] for k in z.files}
    else:
        for fn in os.listdir(model_path):
            if fn.endswith(".npy"):
                state[fn[:-4]] = np.load(os.path.join(model_path, fn))
    if var_list is not None:
        names = {v.name if hasattr(v, "name") else str(v) for v in var_list}
        state = {k: v for k, v in state.items() if k in names}
    return state


def set_program_state(program, state_dict):
    """Reference io.py set_program_state: write values into the current
    scope for the program's matching persistables."""
    import jax.numpy as jnp

    from .core.executor import global_scope

    scope = global_scope()
    n = 0
    for v in _persistable_vars(program):
        if v.name in state_dict:
            scope.set_var(v.name, jnp.asarray(state_dict[v.name]))
            n += 1
    return n


def batch(reader, batch_size, drop_last=False):
    """Reference fluid.io.batch (paddle.batch): group a sample reader
    into batches."""

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched

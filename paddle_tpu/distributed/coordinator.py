"""Multi-host coordination: rendezvous, hybrid mesh, heartbeats, and
barriers with restartable-exit semantics.

The reference framework's multi-host story is a static NCCL ring wired
at launch; a dead trainer wedges every peer in a collective until the
operator notices. Here the coordination fabric is explicit:

* ``initialize()`` wraps ``jax.distributed.initialize`` rendezvous
  through the ``PADDLE_*`` env contract the elastic launcher
  (``distributed/launch.py``) exports, and starts the per-rank
  heartbeat the launcher's failure detector watches;
* ``build_mesh()`` arranges the *global* device set process-major so a
  mesh axis spanning hosts groups each host's ICI-local chips
  contiguously — the hybrid DCN+ICI layout
  ``partition.PartitionConfig.resolve`` and the collective planner
  consume unchanged (``spans_processes(mesh)`` is how the planner
  detects that a reduce crosses DCN and picks the bigger
  ``collective_bucket_mb`` bucket for it);
* ``barrier()`` is a named barrier over the jax coordination service
  with a TIMEOUT: a peer that died (or wedged) turns the stall into a
  ``BarrierTimeout`` instead of an unbounded hang, and
  ``restartable_exit()`` converts that into a clean
  ``RESTART_EXIT_CODE`` exit the launcher interprets as "restart the
  world" — the same escalation the PR-4 watchdog applies to hung
  steps;
* ``make_global_array()`` assembles one global jax.Array from this
  process's LOCAL batch (what a rank-sharded ``GeneratorLoader``
  yields), the feed-side contract of multi-host GSPMD execution.

Everything degrades to a no-op in a single-process world, so the same
training script runs unmodified under ``launch.py --nproc_per_node=N``
or bare ``python``.

Note on backends: cross-process GSPMD jit (mesh spanning processes)
requires a real TPU/GPU backend — XLA's CPU backend refuses
multiprocess computations. On CPU the cross-process path is the pmap
collective seam (``GradAllReduce`` transpile +
``Executor._compile_multiprocess``), which is what the chaos harness
(``tools/chaos_multihost.py``) drives in CI; the mesh/feed helpers
here are the TPU-pod path.

Exported gauges (observability registry, ``paddle_dist_*``): world
size, rank, restart count, live ranks + max heartbeat age (scanned
from the heartbeat directory), barrier counters and cumulative barrier
wait.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

__all__ = [
    "Coordinator", "BarrierTimeout", "RESTART_EXIT_CODE",
    "initialize", "get_coordinator", "spans_processes",
]

_log = logging.getLogger("paddle_tpu.distributed")

# Exit status meaning "this failure is restartable: re-rendezvous and
# resume from the last committed checkpoint" (EX_TEMPFAIL). The elastic
# launcher restarts the world on ANY nonzero child exit while restarts
# remain; this code documents intent (vs. 43 = injected kill, other =
# crash) in logs and chaos reports.
RESTART_EXIT_CODE = 75

_HB_PREFIX = "hb.rank"


class BarrierTimeout(RuntimeError):
    """A coordination barrier timed out — some rank died or wedged.

    The clean recovery is a world restart: callers in a multi-process
    world should exit with ``RESTART_EXIT_CODE`` (the Supervisor does
    this automatically when the timeout escapes its loop)."""


class Coordinator:
    """One process's view of the multi-host world.

    Built by ``initialize()``; holds rank/world/restart-count, runs the
    heartbeat thread the launcher's failure detector reads, and scopes
    the barrier sequence numbers (barrier names must be unique per use
    on the coordination service — every rank executes the same barrier
    call sequence, so a per-name counter keeps them aligned)."""

    def __init__(self, rank: int, world_size: int,
                 heartbeat_dir: Optional[str] = None,
                 heartbeat_interval_s: float = 2.5):
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.restart_count = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
        self.heartbeat_dir = heartbeat_dir
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self._progress_fn = None
        self._progress_stall_s = 0.0
        self._progress_last: Any = None
        self._progress_changed = time.time()
        self._barrier_seq: Dict[str, int] = {}
        self._stats_lock = threading.Lock()
        self._stats: Dict[str, float] = {
            "barriers_total": 0,
            "barrier_wait_ms_total": 0.0,
            "barrier_timeouts_total": 0,
            "heartbeats_total": 0,
        }
        from ..observability import watch_coordinator

        watch_coordinator(self)

    # -- identity ------------------------------------------------------------
    @property
    def is_distributed(self) -> bool:
        return self.world_size > 1

    def __repr__(self):
        return (f"Coordinator(rank={self.rank}/{self.world_size}, "
                f"restarts={self.restart_count})")

    # -- heartbeats ----------------------------------------------------------
    def _hb_path(self, rank: Optional[int] = None) -> Optional[str]:
        if not self.heartbeat_dir:
            return None
        return os.path.join(self.heartbeat_dir,
                            f"{_HB_PREFIX}{self.rank if rank is None else rank}")

    def start_heartbeat(self) -> bool:
        """Begin touching this rank's heartbeat file every
        ``heartbeat_interval_s``. The launcher's failure detector
        treats a heartbeat older than its ``--heartbeat_timeout_s`` as
        a hung host and restarts the world — the liveness signal a
        plain ``proc.poll()`` cannot give (a wedged collective keeps
        the process alive forever). No-op without a heartbeat dir."""
        if self._hb_thread is not None or not self.heartbeat_dir:
            return self._hb_thread is not None
        os.makedirs(self.heartbeat_dir, exist_ok=True)
        # a FRESH stop event: after stop_heartbeat() the old (set)
        # event would make the new loop's first wait() return True and
        # silently never beat — the launcher would then kill a healthy
        # rank for staleness. The old thread still holds the old event
        # and exits on it.
        self._hb_stop = stop = threading.Event()
        self._beat()  # first beat lands before the thread is scheduled

        def loop():
            while not stop.wait(self.heartbeat_interval_s):
                if self._progress_stalled():
                    # the heartbeat thread is alive but the WORK is not
                    # — stop beating so the launcher's staleness check
                    # reads this rank as hung (a thread-based beat
                    # would otherwise vouch for a wedged step loop
                    # forever)
                    continue
                try:
                    self._beat()
                except OSError:  # run dir reclaimed mid-shutdown
                    return

        self._hb_thread = threading.Thread(
            target=loop, daemon=True, name=f"paddle-dist-hb-{self.rank}")
        self._hb_thread.start()
        return True

    def attach_progress(self, fn, stall_after_s: float = 60.0):
        """Make the heartbeat PROGRESS-based: ``fn()`` returns any
        value that changes while real work happens (e.g. the
        Supervisor's ``steps_completed``); once it stops changing for
        ``stall_after_s`` the heartbeat goes silent and the launcher
        declares the rank hung. Without this, a process wedged in a
        dead-peer collective keeps its daemon heartbeat alive forever.
        Size the window above the longest legitimate gap between
        progress ticks (first-compile, checkpoint save)."""
        self._progress_fn = fn
        self._progress_stall_s = float(stall_after_s)
        self._progress_last = None
        self._progress_changed = time.time()

    def _progress_stalled(self) -> bool:
        fn = self._progress_fn
        if fn is None or self._progress_stall_s <= 0:
            return False
        try:
            v = fn()
        except Exception:  # noqa: BLE001 — the probe must never kill the beat
            return False
        if v != self._progress_last:
            self._progress_last = v
            self._progress_changed = time.time()
            return False
        return time.time() - self._progress_changed > self._progress_stall_s

    def _beat(self):
        path = self._hb_path()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(time.time()))
        os.replace(tmp, path)  # atomic: the detector never reads a torn file
        with self._stats_lock:
            self._stats["heartbeats_total"] += 1

    def stop_heartbeat(self):
        self._hb_stop.set()
        self._hb_thread = None

    def heartbeat_ages(self) -> Dict[int, float]:
        """rank -> seconds since that rank's last heartbeat, for every
        rank that has ever beaten (the launcher-side failure-detector
        view, also readable by any rank for the gauges)."""
        out: Dict[int, float] = {}
        if not self.heartbeat_dir or not os.path.isdir(self.heartbeat_dir):
            return out
        now = time.time()
        for entry in os.listdir(self.heartbeat_dir):
            if not entry.startswith(_HB_PREFIX):
                continue
            try:
                rank = int(entry[len(_HB_PREFIX):])
                out[rank] = max(
                    0.0,
                    now - os.path.getmtime(
                        os.path.join(self.heartbeat_dir, entry)))
            except (ValueError, OSError):
                continue
        return out

    def live_ranks(self, stale_after_s: Optional[float] = None) -> int:
        """Ranks whose heartbeat is fresher than ``stale_after_s``
        (default 4x the beat interval). Without a heartbeat dir the
        only honest answer is this process itself."""
        ages = self.heartbeat_ages()
        if not ages:
            return 1
        cutoff = (4.0 * self.heartbeat_interval_s
                  if stale_after_s is None else float(stale_after_s))
        return sum(1 for a in ages.values() if a <= cutoff)

    # -- barrier -------------------------------------------------------------
    def barrier(self, name: str, timeout_s: Optional[float] = None) -> float:
        """Named barrier across every process, with a timeout.

        Returns the seconds spent waiting. A stall past ``timeout_s``
        (default: the ``dist_barrier_timeout_s`` flag) raises
        ``BarrierTimeout`` instead of hanging — a dead peer costs one
        bounded wait, after which the caller exits restartably and the
        launcher re-forms the world. Single-process: no-op."""
        if self.world_size <= 1:
            return 0.0
        from ..flags import flag

        timeout_s = (float(flag("dist_barrier_timeout_s"))
                     if timeout_s is None else float(timeout_s))
        seq = self._barrier_seq.get(name, 0)
        self._barrier_seq[name] = seq + 1
        key = f"paddle:{name}:{seq}"
        t0 = time.perf_counter()
        try:
            client = _coordination_client()
            if client is None:
                raise BarrierTimeout(
                    f"barrier {name!r}: jax.distributed is not initialized "
                    "in this process — call distributed.initialize() first")
            client.wait_at_barrier(key, int(timeout_s * 1000))
        except BarrierTimeout:
            raise
        except Exception as e:  # noqa: BLE001 — service errors → timeout
            with self._stats_lock:
                self._stats["barrier_timeouts_total"] += 1
            raise BarrierTimeout(
                f"barrier {name!r} (key {key}) did not complete within "
                f"{timeout_s:.0f}s — a peer rank likely died or wedged; "
                f"exit with RESTART_EXIT_CODE ({RESTART_EXIT_CODE}) so the "
                f"launcher restarts the world ({type(e).__name__}: {e})"
            ) from e
        waited = time.perf_counter() - t0
        with self._stats_lock:
            self._stats["barriers_total"] += 1
            self._stats["barrier_wait_ms_total"] += waited * 1e3
        return waited

    # -- host-side collective -------------------------------------------------
    def host_allreduce(self, arrays: Dict[str, Any], tag: str,
                       timeout_s: Optional[float] = None,
                       op: str = "mean") -> Dict[str, Any]:
        """Average (or sum) small named float arrays across every
        process THROUGH the coordination service's key-value store.

        This is the host-level wire: it needs nothing but the gRPC
        coordination channel, so it works on backends whose device
        runtime cannot lower cross-process collectives (XLA's CPU
        backend — the CI/chaos-harness path) and for small optimizer-
        state syncs not worth a device executable. TPU-pod gradient
        traffic belongs in-graph (the PR-9 planner over a
        ``build_mesh`` mesh), not here — this path serializes through
        the rank-0 coordinator process, so use it for KBs, not GBs.

        Dead-peer semantics match ``barrier()``: a rank that never
        publishes its ``tag`` payload turns the wait into a
        ``BarrierTimeout`` after ``timeout_s`` (default
        ``dist_barrier_timeout_s``), which the Supervisor converts to a
        clean restartable exit."""
        if self.world_size <= 1:
            return {k: np.asarray(v) for k, v in arrays.items()}
        if op not in ("mean", "sum"):
            raise ValueError(f"host_allreduce: op must be 'mean' or "
                             f"'sum', got {op!r}")
        from ..flags import flag

        timeout_s = (float(flag("dist_barrier_timeout_s"))
                     if timeout_s is None else float(timeout_s))
        client = _coordination_client()
        if client is None:
            raise BarrierTimeout(
                f"host_allreduce {tag!r}: jax.distributed is not "
                "initialized in this process")
        import io as _io

        buf = _io.BytesIO()
        np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
        client.key_value_set_bytes(f"paddle:ar:{tag}:{self.rank}",
                                   buf.getvalue())
        total: Dict[str, Any] = {}
        t0 = time.perf_counter()
        for rank in range(self.world_size):
            try:
                payload = client.blocking_key_value_get_bytes(
                    f"paddle:ar:{tag}:{rank}", int(timeout_s * 1000))
            except Exception as e:  # noqa: BLE001 — service timeout/error
                with self._stats_lock:
                    self._stats["barrier_timeouts_total"] += 1
                raise BarrierTimeout(
                    f"host_allreduce {tag!r}: rank {rank} never "
                    f"published its payload within {timeout_s:.0f}s — "
                    "a peer likely died; exit restartably so the "
                    f"launcher re-forms the world ({type(e).__name__})"
                ) from e
            with np.load(_io.BytesIO(payload)) as z:
                for k in z.files:
                    if z[k].dtype.kind != "f":
                        # non-float state is replicated by contract:
                        # keep the first rank's copy, don't sum it
                        total.setdefault(k, z[k])
                        continue
                    # accumulate in f64, in rank order, so every rank
                    # computes the bit-identical reduction
                    v = z[k].astype(np.float64)
                    total[k] = v if k not in total else total[k] + v
        with self._stats_lock:
            self._stats["barriers_total"] += 1
            self._stats["barrier_wait_ms_total"] += \
                (time.perf_counter() - t0) * 1e3
        out = {}
        for k, v in total.items():
            ref = np.asarray(arrays[k])
            if ref.dtype.kind == "f":
                if op == "mean":
                    v = v / self.world_size
                v = v.astype(ref.dtype)
            out[k] = v
        return out

    # -- mesh ----------------------------------------------------------------
    def build_mesh(self, mesh_axes, devices=None):
        """A Mesh over the GLOBAL device set, process-major.

        ``mesh_axes`` is the ``parse_mesh`` dict/str form ("dp=8" or
        "dcn=2,ici=4"). Devices sort by (process_index, id), so an axis
        spanning hosts places each host's chips contiguously — DCN hops
        happen between blocks, ICI within them (the hybrid layout; with
        explicit ``dcn``/``ici`` axes the dcn axis should come first).
        The result drops straight into ``PartitionConfig.resolve
        (mesh=...)`` and ``CompiledProgram.with_partitioning`` — rules
        and planner are mesh-shape-agnostic."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from ..partition.rules import parse_mesh

        axes = parse_mesh(mesh_axes)
        if not axes:
            raise ValueError(
                "build_mesh needs at least one axis, e.g. 'dp=8' or "
                "'dcn=2,ici=4'")
        devs = (list(devices) if devices is not None
                else sorted(jax.devices(),
                            key=lambda d: (d.process_index, d.id)))
        names = tuple(axes)
        shape = tuple(axes[n] for n in names)
        total = int(np.prod(shape))
        if len(devs) < total:
            raise ValueError(
                f"mesh {dict(axes)} needs {total} devices, the world has "
                f"{len(devs)} ({self.world_size} process(es) x "
                f"{len(devs) // max(self.world_size, 1)} local)")
        return Mesh(np.array(devs[:total]).reshape(shape), names)

    # -- feeds ---------------------------------------------------------------
    def make_global_array(self, sharding, local_batch):
        """One global jax.Array from this process's LOCAL batch.

        ``sharding`` is a NamedSharding (or (mesh, spec) pair); the
        local batch is what a rank-sharded GeneratorLoader yields —
        this process's rows only. Every process calls this with its own
        shard and the results line up into one global array the
        jit/partitioned step consumes. Single-process shardings fall
        through to a plain device_put."""
        import jax
        import numpy as np

        if isinstance(sharding, tuple):
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh, spec = sharding
            sharding = NamedSharding(
                mesh, spec if not isinstance(spec, (tuple, list))
                else P(*spec))
        arr = np.asarray(local_batch)
        if getattr(sharding, "is_fully_addressable", True):
            return jax.device_put(arr, sharding)
        return jax.make_array_from_process_local_data(sharding, arr)

    # -- exits ---------------------------------------------------------------
    def restartable_exit(self, reason: str) -> "SystemExit":
        """Log + flight-note ``reason`` and return a SystemExit carrying
        ``RESTART_EXIT_CODE`` for the caller to raise — the clean way
        out of a stalled world (the launcher restarts it)."""
        _log.error("restartable exit (rank %d): %s", self.rank, reason)
        try:
            from ..observability import flight

            flight.note("event", what="restartable_exit", rank=self.rank,
                        reason=reason)
        except Exception:  # noqa: BLE001 — exiting anyway
            pass
        return SystemExit(RESTART_EXIT_CODE)

    # -- telemetry ------------------------------------------------------------
    def stats_numeric(self) -> Dict[str, float]:
        ages = self.heartbeat_ages()
        with self._stats_lock:
            out = dict(self._stats)
        out.update(
            world_size=self.world_size,
            rank=self.rank,
            restarts=self.restart_count,
            live_ranks=self.live_ranks() if ages else self.world_size,
            heartbeat_age_s=round(max(ages.values()), 3) if ages else 0.0,
        )
        return out


def _coordination_client():
    """The jax coordination-service client, or None when
    jax.distributed was never initialized (single process)."""
    try:
        from jax._src import distributed as _jd

        return _jd.global_state.client
    except Exception:  # noqa: BLE001 — layout changed / not initialized
        return None


_COORD: Optional[Coordinator] = None
_COORD_LOCK = threading.Lock()


def initialize(coordinator_address: Optional[str] = None,
               heartbeat: bool = True) -> Coordinator:
    """Rendezvous + heartbeat, from the launcher's env contract.

    Wraps ``parallel.env.init_parallel_env`` (PADDLE_TRAINER_ID /
    PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS ->
    ``jax.distributed.initialize`` at the rank-0 endpoint), then starts
    the heartbeat thread when the launcher exported
    ``PADDLE_HEARTBEAT_DIR``. Idempotent — the second call returns the
    live Coordinator."""
    global _COORD
    with _COORD_LOCK:
        if _COORD is not None:
            return _COORD
        from ..parallel.env import init_parallel_env

        env = init_parallel_env(coordinator_address)
        coord = Coordinator(
            env.rank, env.world_size,
            heartbeat_dir=os.environ.get("PADDLE_HEARTBEAT_DIR") or None,
            heartbeat_interval_s=float(
                os.environ.get("PADDLE_HEARTBEAT_INTERVAL_S", "2.5")))
        if heartbeat:
            coord.start_heartbeat()
        _COORD = coord
        _log.info("coordinator up: rank %d/%d restart=%d heartbeat=%s",
                  coord.rank, coord.world_size, coord.restart_count,
                  coord.heartbeat_dir or "off")
        return coord


def get_coordinator() -> Optional[Coordinator]:
    """The live Coordinator, or None before ``initialize()``."""
    return _COORD


def spans_processes(mesh) -> bool:
    """True when ``mesh`` places devices from more than one process —
    i.e. its collectives cross DCN. The collective planner keys the
    per-axis ``collective_bucket_mb`` choice on this."""
    if mesh is None or not hasattr(mesh, "devices"):
        return False
    try:
        procs = {d.process_index for d in mesh.devices.flat}
    except Exception:  # noqa: BLE001 — emulated/stub device objects
        return False
    return len(procs) > 1

"""Multi-process trainer launcher.

Reference: python/paddle/distributed/launch.py:175 (proc per selected
GPU, env contract :105-110 PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS
/ PADDLE_CURRENT_ENDPOINT, log redirect, kill-all-on-failure :169).

TPU-native: one process per HOST (not per chip — a jax process drives
all its local chips), env contract preserved, rendezvous through
jax.distributed's coordination service at the rank-0 endpoint.

Usage: python -m paddle_tpu.distributed.launch --nproc_per_node=2 \
           train.py --args...
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _parse_args():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--cluster_node_ips", default="127.0.0.1")
    p.add_argument("--node_ip", default="127.0.0.1")
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--log_dir", default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def launch(args):
    node_ips = args.cluster_node_ips.split(",")
    node_id = node_ips.index(args.node_ip)
    nproc = args.nproc_per_node
    world = len(node_ips) * nproc
    endpoints = [
        f"{ip}:{args.started_port + i}" for ip in node_ips for i in range(nproc)
    ]
    procs = []
    log_fds = []
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    for local_rank in range(nproc):
        rank = node_id * nproc + local_rank
        env = dict(os.environ)
        env.update(
            {
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
                "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
                "FLAGS_selected_tpus": str(local_rank),
            }
        )
        cmd = [sys.executable, "-u", args.training_script] + args.training_script_args
        if args.log_dir:
            fd = open(os.path.join(args.log_dir, f"workerlog.{local_rank}"), "w")
            log_fds.append(fd)
            proc = subprocess.Popen(cmd, env=env, stdout=fd, stderr=fd)
        else:
            proc = subprocess.Popen(cmd, env=env)
        procs.append(proc)

    # reference launch.py:169/:342 — if any proc dies, kill the job
    try:
        alive = True
        while alive:
            alive = False
            for proc in procs:
                ret = proc.poll()
                if ret is None:
                    alive = True
                elif ret != 0:
                    sys.stderr.write(
                        f"[launch] a worker exited with code {ret}; terminating job\n"
                    )
                    for p2 in procs:
                        if p2.poll() is None:
                            p2.send_signal(signal.SIGTERM)
                    sys.exit(ret)
            time.sleep(1)
    finally:
        for fd in log_fds:
            fd.close()


if __name__ == "__main__":
    launch(_parse_args())

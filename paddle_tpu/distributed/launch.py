"""Elastic multi-process trainer launcher.

Reference: python/paddle/distributed/launch.py:175 (proc per selected
GPU, env contract :105-110 PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS
/ PADDLE_CURRENT_ENDPOINT, log redirect, kill-all-on-failure :169).

TPU-native: one process per HOST (not per chip — a jax process drives
all its local chips), env contract preserved, rendezvous through
jax.distributed's coordination service at the rank-0 endpoint.

Beyond the reference, this launcher is an elastic
supervisor-of-supervisors (the in-process Supervisor owns the step
loop's faults; this parent owns the WORLD's):

* **failure detection** — a child that exits nonzero, OR whose
  heartbeat file (written by ``distributed.coordinator``) goes stale
  past ``--heartbeat_timeout_s``, marks the world failed. A hung
  collective keeps a process alive forever; the heartbeat is the only
  honest liveness signal.
* **coordinated teardown** — on failure every survivor gets SIGTERM
  (the Supervisor flushes a checkpoint at the next step boundary),
  then SIGKILL after ``--kill_grace_s`` — a rank wedged inside a
  dead-peer collective never reaches a step boundary, so the
  escalation is what guarantees nobody lingers.
* **world restart** — with ``--max_restarts`` > 0 the world is
  relaunched with a FRESH rendezvous (new coordination-service port,
  ``PADDLE_RESTART_COUNT`` bumped) and training auto-resumes from the
  last committed checkpoint (bit-exact, the PR-4 contract) — proven by
  ``tools/chaos_multihost.py``.
* **honest exit codes** — the FIRST nonzero child exit code is
  recorded and propagated once restarts are exhausted (never exit 0
  under a dead trainer), and every child line is prefixed with its
  rank (``[rank N] ...``) so interleaved logs stay attributable.

Usage: python -m paddle_tpu.distributed.launch --nproc_per_node=4 \\
           --max_restarts=2 train.py --args...
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

# exit code reported when the failure was a stale heartbeat (the child
# was still "alive"; there is no child exit code to propagate)
HANG_EXIT_CODE = 75  # == coordinator.RESTART_EXIT_CODE, kept import-free


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--cluster_node_ips", default="127.0.0.1")
    p.add_argument("--node_ip", default="127.0.0.1")
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--log_dir", default=None)
    p.add_argument("--max_restarts", type=int, default=0,
                   help="world restarts after a rank failure (elastic); "
                        "0 = fail fast (legacy behavior)")
    p.add_argument("--kill_grace_s", type=float, default=10.0,
                   help="SIGTERM -> SIGKILL escalation grace per teardown")
    p.add_argument("--heartbeat_timeout_s", type=float, default=30.0,
                   help="a rank whose heartbeat (written once it calls "
                        "distributed.initialize()) is older than this is "
                        "declared hung; 0 disables")
    p.add_argument("--heartbeat_interval_s", type=float, default=2.5)
    p.add_argument("--run_dir", default=None,
                   help="scratch dir for heartbeats/launcher state "
                        "(default: a fresh temp dir)")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _free_port() -> int:
    from ..parallel.env import free_port

    return free_port()


class _LogPump(threading.Thread):
    """Reads one child's merged stdout/stderr and re-emits every line
    prefixed with its rank — concurrent children interleave at line,
    not byte, granularity."""

    def __init__(self, rank: int, pipe, sink):
        super().__init__(daemon=True, name=f"launch-logpump-{rank}")
        self.prefix = f"[rank {rank}] ".encode()
        self.pipe = pipe
        self.sink = sink
        self.start()

    def run(self):
        try:
            for line in iter(self.pipe.readline, b""):
                self.sink.write(self.prefix + line)
                self.sink.flush()
        except (ValueError, OSError):
            pass  # pipe torn down during kill-all
        finally:
            try:
                self.pipe.close()
            except OSError:
                pass


class _Child:
    def __init__(self, rank: int, proc, pump, log_fd):
        self.rank = rank
        self.proc = proc
        self.pump = pump
        self.log_fd = log_fd


def _spawn_world(args, generation: int, base_port: int, hb_dir: str):
    node_ips = args.cluster_node_ips.split(",")
    node_id = node_ips.index(args.node_ip)
    nproc = args.nproc_per_node
    world = len(node_ips) * nproc
    # deterministic per-generation ports: every node derives the same
    # endpoint list without cross-node coordination (the old rank-0
    # coordination port may sit in TIME_WAIT after a kill-all)
    endpoints = [
        f"{ip}:{base_port + i}" for ip in node_ips for i in range(nproc)
    ]
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    os.makedirs(hb_dir, exist_ok=True)
    children = []
    for local_rank in range(nproc):
        rank = node_id * nproc + local_rank
        env = dict(os.environ)
        env.update(
            {
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
                "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
                "FLAGS_selected_tpus": str(local_rank),
                "PADDLE_RESTART_COUNT": str(generation),
                "PADDLE_HEARTBEAT_DIR": hb_dir,
                "PADDLE_HEARTBEAT_INTERVAL_S": str(args.heartbeat_interval_s),
            }
        )
        cmd = [sys.executable, "-u", args.training_script] \
            + args.training_script_args
        log_fd = pump = None
        if args.log_dir:
            # per-rank file, named by GLOBAL rank + generation so a
            # restarted world never clobbers the evidence of the one
            # that failed
            log_fd = open(
                os.path.join(args.log_dir,
                             f"workerlog.{rank}.gen{generation}"), "wb")
            proc = subprocess.Popen(cmd, env=env, stdout=log_fd,
                                    stderr=subprocess.STDOUT)
        else:
            proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT)
            pump = _LogPump(rank, proc.stdout, sys.stderr.buffer)
        children.append(_Child(rank, proc, pump, log_fd))
    return children


def _kill_world(children, grace_s: float):
    """SIGTERM everyone, then SIGKILL whoever ignored it. Always reaps
    — no zombies, no still-running siblings after the launcher
    returns."""
    alive = [c for c in children if c.proc.poll() is None]
    for c in alive:
        try:
            c.proc.send_signal(signal.SIGTERM)
        except OSError:
            pass
    deadline = time.time() + max(0.0, grace_s)
    for c in alive:
        remaining = deadline - time.time()
        try:
            c.proc.wait(timeout=max(0.1, remaining))
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"[launch] rank {c.rank} ignored SIGTERM for "
                f"{grace_s:.0f}s; escalating to SIGKILL\n")
            try:
                c.proc.kill()
            except OSError:
                pass
            c.proc.wait()
    for c in children:
        if c.log_fd is not None:
            c.log_fd.close()


def _stale_ranks(hb_dir: str, timeout_s: float):
    """Ranks whose heartbeat file exists but stopped updating. Ranks
    that never wrote one (script doesn't use the coordinator) are never
    declared hung — only silence AFTER a first beat is evidence."""
    out = []
    if timeout_s <= 0 or not os.path.isdir(hb_dir):
        return out
    now = time.time()
    for entry in os.listdir(hb_dir):
        if not entry.startswith("hb.rank"):
            continue
        try:
            rank = int(entry[len("hb.rank"):])
            if now - os.path.getmtime(os.path.join(hb_dir, entry)) \
                    > timeout_s:
                out.append(rank)
        except (ValueError, OSError):
            continue
    return sorted(out)


def _run_generation(args, generation: int, base_port: int,
                    run_dir: str) -> int:
    """Spawn + monitor one world; returns 0 on clean success or the
    FIRST failure's exit code (HANG_EXIT_CODE for a stale-heartbeat
    hang)."""
    hb_dir = os.path.join(run_dir, f"hb.gen{generation}")
    children = _spawn_world(args, generation, base_port, hb_dir)
    first_bad: int | None = None
    try:
        while True:
            running = []
            for c in children:
                ret = c.proc.poll()
                if ret is None:
                    running.append(c)
                elif ret != 0 and first_bad is None:
                    first_bad = ret
                    sys.stderr.write(
                        f"[launch] rank {c.rank} exited with code {ret}; "
                        "terminating the world\n")
            if first_bad is not None:
                break
            if not running:
                return 0  # every rank exited 0
            hung = _stale_ranks(hb_dir, args.heartbeat_timeout_s)
            hung = [r for r in hung
                    if any(c.rank == r and c.proc.poll() is None
                           for c in children)]
            if hung:
                first_bad = HANG_EXIT_CODE
                sys.stderr.write(
                    f"[launch] rank(s) {hung} heartbeat stale "
                    f"(> {args.heartbeat_timeout_s:.0f}s) — declaring "
                    "hung; terminating the world\n")
                break
            time.sleep(0.2)
    finally:
        _kill_world(children, args.kill_grace_s)
    return int(first_bad)


def launch(args) -> int:
    run_dir = args.run_dir or tempfile.mkdtemp(prefix="paddle_launch_")
    os.makedirs(run_dir, exist_ok=True)
    first_bad: int | None = None
    nproc = args.nproc_per_node
    world = len(args.cluster_node_ips.split(",")) * nproc
    for generation in range(args.max_restarts + 1):
        # restarts re-rendezvous on a fresh port (the dead world's may
        # sit in TIME_WAIT). With an explicit --started_port the ladder
        # is DETERMINISTIC — started_port + generation*world — so every
        # node's launcher derives the same endpoint list without
        # cross-node coordination (a node-local free port would leave
        # node B rendezvousing at its own idea of rank 0's endpoint).
        # --started_port=0 = "pick one for me": single-node only, where
        # the one launcher owns the whole endpoint list.
        if args.started_port:
            base_port = args.started_port + generation * world
        else:
            base_port = _free_port()
        if generation:
            sys.stderr.write(
                f"[launch] restarting world (restart {generation}/"
                f"{args.max_restarts}) with fresh rendezvous port "
                f"{base_port}\n")
        code = _run_generation(args, generation, base_port, run_dir)
        if code == 0:
            if generation:
                sys.stderr.write(
                    f"[launch] world completed after {generation} "
                    "restart(s)\n")
            return 0
        if first_bad is None:
            first_bad = code
    sys.stderr.write(
        f"[launch] restart budget exhausted; exiting with the first "
        f"failure's code {first_bad}\n")
    # propagate the FIRST nonzero child exit code (negative = killed by
    # signal N -> conventional 128+N so the shell sees it)
    return first_bad if first_bad > 0 else 128 - first_bad


if __name__ == "__main__":
    sys.exit(launch(_parse_args()))

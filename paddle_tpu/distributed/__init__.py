"""Multi-process launchers + distributed utilities.

Reference: python/paddle/distributed/ (launch.py:175,353 multi-proc GPU
launcher; launch_ps.py pserver launcher).

Beyond the reference: ``launch.py`` is an ELASTIC launcher (heartbeat
failure detector, SIGTERM->SIGKILL teardown, world restart with fresh
rendezvous) and ``coordinator.py`` is the in-process coordination
fabric (jax.distributed rendezvous, hybrid DCN+ICI mesh construction,
barriers with restartable-exit timeouts, per-rank heartbeats, the
``paddle_dist_*`` gauges). ``tools/chaos_multihost.py`` proves the
kill-one-of-N -> restart -> bit-exact-resume loop end to end.
"""

from ..parallel.env import (ParallelEnv, get_rank, get_world_size,
                            init_parallel_env)
from .coordinator import (RESTART_EXIT_CODE, BarrierTimeout, Coordinator,
                          get_coordinator, initialize, spans_processes)

__all__ = [
    "ParallelEnv", "get_rank", "get_world_size", "init_parallel_env",
    "Coordinator", "BarrierTimeout", "RESTART_EXIT_CODE",
    "initialize", "get_coordinator", "spans_processes",
]

"""Multi-process launchers + distributed utilities.

Reference: python/paddle/distributed/ (launch.py:175,353 multi-proc GPU
launcher; launch_ps.py pserver launcher).
"""

from ..parallel.env import ParallelEnv, get_rank, get_world_size, init_parallel_env

"""Parameter-server job launcher.

Reference: python/paddle/distributed/launch_ps.py — spawns pserver
procs + trainer procs on one node with the PADDLE_* PS env contract
(PADDLE_PSERVERS_IP_PORT_LIST, TRAINING_ROLE, PADDLE_TRAINER_ID).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _parse_args():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch_ps")
    p.add_argument("--worker_num", type=int, default=2)
    p.add_argument("--server_num", type=int, default=2)
    p.add_argument("--started_port", type=int, default=6180)
    p.add_argument("--log_dir", default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def launch_ps(args):
    server_eps = [f"127.0.0.1:{args.started_port + i}" for i in range(args.server_num)]
    procs = []
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    def spawn(role, idx):
        env = dict(os.environ)
        env.update(
            {
                "PADDLE_PSERVERS_IP_PORT_LIST": ",".join(server_eps),
                "PADDLE_TRAINERS_NUM": str(args.worker_num),
                "TRAINING_ROLE": role,
            }
        )
        if role == "PSERVER":
            env["PADDLE_CURRENT_ENDPOINT"] = server_eps[idx]
        else:
            env["PADDLE_TRAINER_ID"] = str(idx)
        cmd = [sys.executable, "-u", args.training_script] + args.training_script_args
        if args.log_dir:
            fd = open(os.path.join(args.log_dir, f"{role.lower()}.{idx}.log"), "w")
            return subprocess.Popen(cmd, env=env, stdout=fd, stderr=fd)
        return subprocess.Popen(cmd, env=env)

    for i in range(args.server_num):
        procs.append(spawn("PSERVER", i))
    for i in range(args.worker_num):
        procs.append(spawn("TRAINER", i))

    trainer_procs = procs[args.server_num :]
    try:
        while any(p.poll() is None for p in trainer_procs):
            for p in trainer_procs:
                if p.poll() not in (None, 0):
                    raise SystemExit(p.returncode)
            time.sleep(1)
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)


if __name__ == "__main__":
    launch_ps(_parse_args())

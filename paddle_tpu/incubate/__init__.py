from . import fleet

"""Dataset-authoring API (reference
python/paddle/fluid/incubate/data_generator/__init__.py:21
DataGenerator, :241 MultiSlotStringDataGenerator, :282
MultiSlotDataGenerator).

Users subclass a generator, implement generate_sample(line), and run
it as the dataset pipe command (or write files directly); the emitted
MultiSlot text lines — per slot: "<n> v1 ... vn" — are exactly what
paddle_tpu.dataset's parser (python or native/datafeed.cpp) consumes,
so a generator round-trips into Dataset.set_filelist/load_into_memory.
"""

from __future__ import annotations

import os
import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    """Reference data_generator/__init__.py:21."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    # -- user hooks -----------------------------------------------------------
    def generate_sample(self, line):
        """Subclass hook: return a generator yielding ONE sample — a
        list of (slot_name, value_list) pairs — or None to drop the
        line."""
        raise NotImplementedError(
            "Please rewrite this function to return a list or tuple: " +
            "[(name, [value1, value2]), ...]")

    def generate_batch(self, samples):
        """Subclass hook: batch-level postprocessing; yields samples."""
        for sample in samples:
            yield sample

    # -- drivers --------------------------------------------------------------
    def run_from_stdin(self):
        """Pipe-command mode: stdin lines -> stdout MultiSlot lines."""
        batch = []
        for line in sys.stdin:
            it = self.generate_sample(line)
            if it is None:
                continue
            for sample in it():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    self._flush(batch, sys.stdout)
                    batch = []
        if batch:
            self._flush(batch, sys.stdout)

    def run_from_memory(self):
        """Memory mode: generate_sample(None) produces every sample."""
        batch = []
        it = self.generate_sample(None)
        for sample in it():
            if sample is None:
                continue
            batch.append(sample)
            if len(batch) == self.batch_size_:
                self._flush(batch, sys.stdout)
                batch = []
        if batch:
            self._flush(batch, sys.stdout)

    def write_to_files(self, lines_per_file, prefix):
        """Convenience beyond the reference: materialize the generated
        samples as dataset shard files and return their paths (what a
        pipe command would have produced)."""
        paths = []
        f = None
        n = 0
        it = self.generate_sample(None)
        for sample in it():
            if sample is None:
                continue
            if f is None or n >= lines_per_file:
                if f:
                    f.close()
                paths.append(f"{prefix}.{len(paths):04d}.txt")
                f = open(paths[-1], "w")
                n = 0
            f.write(self._gen_str(sample))
            n += 1
        if f:
            f.close()
        return paths

    def _flush(self, batch, out):
        for sample in self.generate_batch(batch):
            out.write(self._gen_str(sample))

    def _gen_str(self, line):
        raise NotImplementedError(
            "Please inherit MultiSlotDataGenerator or "
            "MultiSlotStringDataGenerator to use this function")


class MultiSlotStringDataGenerator(DataGenerator):
    """Reference :241 — values are already strings."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type")
        out = []
        for _, elements in line:
            out.append(str(len(elements)))
            out.extend(str(e) for e in elements)
        return " ".join(out) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """Reference :282 — validates slot names/arity are stable across
    samples, values numeric."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type")
        if self._proto_info is None:
            self._proto_info = [(name, "uint64"
                                 if all(isinstance(e, int) for e in elements)
                                 else "float") for name, elements in line]
        else:
            if len(line) != len(self._proto_info):
                raise ValueError(
                    "the complete field set of two given line are "
                    "inconsistent.")
            for (name, elements), (pname, _) in zip(line, self._proto_info):
                if name != pname:
                    raise ValueError(
                        "the field name of two given line are not match: "
                        f"{name} != {pname}")
        out = []
        for name, elements in line:
            if not elements:
                raise ValueError(
                    f"the field {name} of a sample must have at least one "
                    "element")
            out.append(str(len(elements)))
            out.extend(str(e) for e in elements)
        return " ".join(out) + "\n"

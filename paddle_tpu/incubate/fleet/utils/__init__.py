from .fleet_util import FleetUtil

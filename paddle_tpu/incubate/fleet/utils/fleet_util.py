"""Fleet training utilities (reference
incubate/fleet/utils/fleet_util.py): rank-0 logging, metric-state
reset, globally-reduced AUC/metrics from the auc op's stat buckets,
and model save/load wrappers. The reference reduces stats over MPI;
here worker stats reduce over the fleet's collective path (single
process: identity)."""

from __future__ import annotations

import logging
import os

import numpy as np

_logger = logging.getLogger("paddle_tpu.fleet_util")


class FleetUtil(object):
    def __init__(self, mode="pslib"):
        self._mode = mode

    # -- rank-0 logging ----------------------------------------------------
    def _rank(self):
        try:
            from ...parallel.fleet import fleet

            return fleet.worker_index()
        except Exception:
            return 0

    def rank0_print(self, s):
        if self._rank() == 0:
            print(s, flush=True)

    def rank0_info(self, s):
        if self._rank() == 0:
            _logger.info(s)

    def rank0_error(self, s):
        if self._rank() == 0:
            _logger.error(s)

    # -- metric state ------------------------------------------------------
    def set_zero(self, var_name, scope=None, place=None, param_type="int64"):
        """Reset a metric-state variable to zeros (reference :121)."""
        import paddle_tpu as fluid

        scope = scope or fluid.global_scope()
        var = scope.find_var(var_name)
        if var is None:
            raise KeyError(f"variable {var_name!r} not found in scope")
        scope.set_var(var_name, np.zeros_like(np.asarray(var)))

    def get_global_auc(self, scope=None, stat_pos="_generated_var_2",
                       stat_neg="_generated_var_3"):
        """AUC from the auc op's positive/negative bucket stats,
        summed across workers (reference :186)."""
        import paddle_tpu as fluid

        scope = scope or fluid.global_scope()
        pos = np.asarray(scope.find_var(stat_pos)).astype("float64").ravel()
        neg = np.asarray(scope.find_var(stat_neg)).astype("float64").ravel()
        pos, neg = self._all_reduce(pos), self._all_reduce(neg)
        # trapezoid over buckets, descending threshold
        tot_pos = tot_neg = area = 0.0
        for b in range(len(pos) - 1, -1, -1):
            new_pos = tot_pos + pos[b]
            new_neg = tot_neg + neg[b]
            area += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0.0 or tot_neg == 0.0:
            return 0.5
        return float(area / (tot_pos * tot_neg))

    def print_global_auc(self, scope=None, stat_pos="_generated_var_2",
                         stat_neg="_generated_var_3",
                         print_prefix=""):
        auc = self.get_global_auc(scope, stat_pos, stat_neg)
        self.rank0_print(f"{print_prefix} global auc = {auc:.6f}")
        return auc

    def get_global_metrics(self, scope=None, stat_pos_name="_generated_var_2",
                           stat_neg_name="_generated_var_3",
                           sqrerr_name=None, abserr_name=None,
                           prob_name=None, q_name=None, pos_ins_num_name=None,
                           total_ins_num_name=None):
        """auc + error metrics from named stat vars (reference :1268).
        Unavailable stats come back as None."""
        import paddle_tpu as fluid

        scope = scope or fluid.global_scope()
        out = {"auc": self.get_global_auc(scope, stat_pos_name,
                                          stat_neg_name)}

        def mean_of(name, denom):
            if name is None or scope.find_var(name) is None:
                return None
            v = float(self._all_reduce(
                np.asarray(scope.find_var(name)).astype("float64")).sum())
            return v / denom if denom else None

        total = None
        if total_ins_num_name and scope.find_var(total_ins_num_name) is not None:
            total = float(self._all_reduce(np.asarray(
                scope.find_var(total_ins_num_name)).astype("float64")).sum())
            out["total_ins_num"] = total
        out["mae"] = mean_of(abserr_name, total)
        out["rmse"] = (mean_of(sqrerr_name, total) ** 0.5
                       if mean_of(sqrerr_name, total) is not None else None)
        out["predicted_ctr"] = mean_of(prob_name, total)
        if pos_ins_num_name and scope.find_var(pos_ins_num_name) is not None and total:
            pos_n = float(self._all_reduce(np.asarray(
                scope.find_var(pos_ins_num_name)).astype("float64")).sum())
            out["actual_ctr"] = pos_n / total
        return out

    def print_global_metrics(self, print_prefix="", **kwargs):
        m = self.get_global_metrics(**kwargs)
        self.rank0_print(f"{print_prefix} global metrics: " + ", ".join(
            f"{k}={v}" for k, v in m.items() if v is not None))
        return m

    # -- checkpoints -------------------------------------------------------
    def save_fleet_model(self, path, mode=0):
        import paddle_tpu as fluid
        from ...parallel.fleet import fleet

        fleet.save_persistables(fluid.Executor(fluid.CPUPlace()), path)

    def load_fleet_model(self, path, mode=0):
        import paddle_tpu as fluid

        fluid.io.load_persistables(
            fluid.Executor(fluid.CPUPlace()), path)

    def save_model(self, output_path, day, pass_id):
        self.save_fleet_model(os.path.join(
            str(output_path), str(day), str(pass_id)))

    # -- scheduling helper -------------------------------------------------
    def get_online_pass_interval(self, days, hours, split_interval,
                                 split_per_pass, is_data_hourly_placed):
        """Pass interval layout for online training (reference :1207)."""
        split_interval = int(split_interval)
        split_per_pass = int(split_per_pass)
        splits_per_day = 24 * 60 // split_interval
        pass_per_day = splits_per_day // split_per_pass
        left_train_hour = int(hours.split(" ")[0]) if isinstance(
            hours, str) else int(hours[0])
        online_pass_interval = []
        for i in range(pass_per_day):
            online_pass_interval.append([])
            for j in range(split_per_pass):
                split_idx = i * split_per_pass + j
                h = split_idx * split_interval // 60
                m = split_idx * split_interval % 60
                if is_data_hourly_placed:
                    online_pass_interval[-1].append(f"{h:02d}")
                else:
                    online_pass_interval[-1].append(f"{h:02d}{m:02d}")
        return online_pass_interval

    def _all_reduce(self, arr):
        try:
            from ...parallel.fleet import fleet

            if fleet.worker_num() > 1:
                from ...ps import util as _psu  # pragma: no cover

                return _psu.all_reduce_sum(arr)
        except Exception:
            pass
        return arr

from . import utils

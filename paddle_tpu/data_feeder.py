"""DataFeeder: converts user mini-batch rows into the feed dict.

Reference: python/paddle/fluid/data_feeder.py — converts a list of
sample tuples into LoDTensors per feed var. Dense-only here (raggedness
is handled by padding at the pipeline level).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from .core.framework import Variable, convert_dtype


class DataFeeder:
    def __init__(self, feed_list: Sequence, place=None, program=None):
        self.feed_vars = list(feed_list)
        self.place = place

    def feed(self, iterable) -> Dict[str, np.ndarray]:
        rows = list(iterable)
        out = {}
        for i, var in enumerate(self.feed_vars):
            name = var.name if isinstance(var, Variable) else str(var)
            cols = [np.asarray(r[i]) for r in rows]
            arr = np.stack(cols, axis=0)
            if isinstance(var, Variable):
                want = convert_dtype(var.dtype)
                arr = arr.astype(want, copy=False)
                # reshape flat rows to the declared trailing shape
                if var.shape and len(var.shape) > arr.ndim and all(
                    d and d > 0 for d in var.shape[1:]
                ):
                    arr = arr.reshape((arr.shape[0],) + tuple(var.shape[1:]))
            out[name] = arr
        return out

    def feed_parallel(self, iterable, num_places=None):
        return self.feed(iterable)

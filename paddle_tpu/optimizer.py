"""Optimizer hierarchy.

Reference: python/paddle/fluid/optimizer.py:54 (Optimizer base:
backward :608, apply_gradients :672, minimize :780) + 20 subclasses.
Each optimizer appends per-parameter update ops (ops/optim.py) plus
state-accumulator vars initialized in the startup program. Because the
executor compiles the whole block, all per-param updates fuse into the
single train-step executable (the reference's fuse_all_optimizer_ops
pass exists to approximate this).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from .core.framework import (
    OpRole,
    Parameter,
    Variable,
    default_main_program,
    default_startup_program,
    unique_name,
)
from .core.backward import append_backward
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from . import clip as clip_mod
from .regularizer import append_regularization_ops

__all__ = [
    "Optimizer",
    "SGD",
    "SGDOptimizer",
    "Momentum",
    "MomentumOptimizer",
    "Adagrad",
    "AdagradOptimizer",
    "Adam",
    "AdamOptimizer",
    "Adamax",
    "AdamaxOptimizer",
    "Dpsgd",
    "DpsgdOptimizer",
    "DecayedAdagrad",
    "DecayedAdagradOptimizer",
    "Adadelta",
    "AdadeltaOptimizer",
    "RMSProp",
    "RMSPropOptimizer",
    "Ftrl",
    "FtrlOptimizer",
    "Lamb",
    "LambOptimizer",
    "LarsMomentum",
    "LarsMomentumOptimizer",
    "DGCMomentumOptimizer",
    "ExponentialMovingAverage",
    "ModelAverage",
    "RecomputeOptimizer",
    "LookaheadOptimizer",
    "PipelineOptimizer",
]


class Optimizer:
    def __init__(
        self,
        learning_rate,
        regularization=None,
        name=None,
        grad_clip=None,
    ):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self._accumulators: Dict[str, Dict[str, Variable]] = defaultdict(dict)
        self._lr_var: Optional[Variable] = None
        self.type = getattr(self, "type", "sgd")
        self.helper = None

    # -- learning rate --------------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
            return
        if self._lr_var is not None:
            return
        from .layers.tensor import create_global_var

        self._lr_var = create_global_var(
            shape=[1],
            value=float(self._learning_rate),
            dtype="float32",
            persistable=True,
            name=unique_name.generate("learning_rate"),
        )

    def _global_learning_rate(self) -> Variable:
        return self._lr_var

    def _create_param_lr(self, param: Parameter) -> Variable:
        base = self._lr_var
        plr = float(param.optimize_attr.get("learning_rate", 1.0)) if param.optimize_attr else 1.0
        if plr == 1.0:
            return base
        from .layers.nn import scale

        return scale(base, scale=plr)

    # -- accumulators ---------------------------------------------------------
    def _add_accumulator(
        self, name: str, param: Parameter, dtype=None, fill_value=0.0, shape=None
    ) -> Variable:
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        helper = LayerHelper(self.type)
        var_name = unique_name.generate(f"{param.name}_{name}")
        gb = default_main_program().global_block()
        var = gb.create_var(
            name=var_name,
            shape=shape if shape is not None else param.shape,
            dtype=dtype or param.dtype,
            persistable=True,
            stop_gradient=True,
        )
        # structural tag consumed by parallel/sharding.py (ZeRO) and
        # megatron sharding inheritance — name heuristics were fragile
        # (round-2 verdict weak #5: an optimizer with deviant accumulator
        # naming silently got dense state)
        var.is_accumulator = True
        var.accumulator_owner = param.name
        helper.set_variable_initializer(var, ConstantInitializer(fill_value))
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name: str, param: Parameter) -> Variable:
        return self._accumulators[name][param.name]

    # -- hooks subclasses implement -------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, params_grads):
        pass

    # -- reference API --------------------------------------------------------
    def backward(
        self, loss, startup_program=None, parameter_list=None, no_grad_set=None,
        callbacks=None,
    ):
        return append_backward(loss, parameter_list, no_grad_set)

    def _fusion_active(self, params_grads) -> bool:
        # exact optimizer classes whose update the fused one-pass
        # Pallas ops (kernels/fused_optim.py) can replace — exact, not
        # isinstance: subclasses (Lamb, DGC) append their own ops and
        # must stay unfused
        if type(self).__name__ not in ("AdamOptimizer", "MomentumOptimizer"):
            return False
        from .kernels.fused_optim import optimizer_fuse_enabled

        return optimizer_fuse_enabled()

    def apply_gradients(self, params_grads) -> List:
        params_grads = sorted(params_grads, key=lambda pg: pg[0].name)
        # the raw backward grads, BEFORE clip/regularization rewrite
        # them: the collective planner buckets exactly these, so the
        # cross-replica reduce happens first and clip-by-global-norm
        # sees the true (global) gradient, matching the monolithic path
        raw_params_grads = list(params_grads)
        # fused one-pass optimizer (optimizer_fuse flag): when the clip
        # is ByGlobalNorm and nothing else rewrites the grads, fold the
        # clip into the fused ops' ClipScale scalar operand — the norm
        # reduction stays in-graph, the per-grad multiply moves inside
        # the one-pass update (no clipped gradient copies). Any other
        # grad rewrite (per-param clip attrs, regularizers) keeps the
        # standard clip/reg chain; the fused op then consumes the
        # rewritten grads exactly like the unfused one did.
        self._fuse_active = self._fusion_active(params_grads)
        self._fused_clip_scale = None
        effective_clip = self._grad_clip or clip_mod._global_clip
        can_fold_clip = (
            self._fuse_active
            and isinstance(effective_clip, clip_mod.GradientClipByGlobalNorm)
            and not any(getattr(p, "gradient_clip_attr", None)
                        for p, _ in params_grads)
            and self.regularization is None
            and not any(getattr(p, "regularizer", None)
                        for p, _ in params_grads)
        )
        if can_fold_clip:
            self._fused_clip_scale = effective_clip._append_scale_op(
                params_grads)
        else:
            # gradient clipping (global set or per-param attr)
            params_grads = clip_mod.append_gradient_clip_ops(
                params_grads, self._grad_clip)
        # weight decay
        params_grads = append_regularization_ops(params_grads, self.regularization)

        block = default_main_program().global_block()
        self._create_accumulators(block, [pg[0] for pg in params_grads])
        opt_ops = []
        for pg in params_grads:
            op = self._append_optimize_op(block, pg)
            if op is not None:
                op.attrs["op_role"] = OpRole.Optimize
                opt_ops.append(op)
        self._finish_update(block, params_grads)
        # flag-gated (collective_bucket_mb / collective_quantization):
        # bucket the DP gradient all-reduce and repoint clip/reg/opt at
        # the reduced values — a no-op when the flags are off
        from .parallel.collectives import ensure_planned

        ensure_planned(default_main_program(),
                       params_grads=raw_params_grads)
        default_main_program()._bump()
        return opt_ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(
        self, loss, startup_program=None, parameter_list=None, no_grad_set=None,
        grad_clip=None,
    ) -> Tuple[List, List[Tuple[Variable, Variable]]]:
        from .dygraph.base import VarBase

        if isinstance(loss, VarBase):
            return self._eager_minimize(loss, parameter_list)
        if grad_clip is not None:
            self._grad_clip = grad_clip
        self._create_global_learning_rate()
        params_grads = self.backward(loss, startup_program, parameter_list, no_grad_set)
        opt_ops = self.apply_gradients(params_grads)
        return opt_ops, params_grads

    # -- eager (dygraph) path -------------------------------------------------
    # Reference: in dygraph mode the same Optimizer objects apply updates
    # directly to VarBase params after loss.backward()
    # (fluid/optimizer.py dygraph branches). Updates run through the SAME
    # optimizer-op lowerings as graph mode, with eager state arrays.
    def _eager_state_for(self, p):
        key = id(p)
        if not hasattr(self, "_eager_states"):
            self._eager_states = {}
        return self._eager_states.setdefault(key, {})

    def _eager_lr(self):
        import jax.numpy as jnp

        lr = self._learning_rate
        if hasattr(lr, "value"):
            return jnp.asarray(lr.value)
        if callable(lr):
            return jnp.asarray(float(lr()))
        return jnp.asarray(float(lr), jnp.float32)

    def _eager_minimize(self, loss, parameter_list):
        import jax.numpy as jnp

        from .core.registry import get_op_def
        from .dygraph.base import _PseudoOp

        if parameter_list is None:
            raise ValueError("dygraph minimize requires parameter_list")
        lr = self._eager_lr().reshape(1)
        opdef = get_op_def(self.type)
        for p in parameter_list:
            if p.grad is None or p.stop_gradient:
                continue
            state = self._eager_state_for(p)
            ins = self._eager_inputs(p, state, lr)
            pseudo = _PseudoOp(self.type, self._eager_attrs())
            outs = opdef.lower(None, pseudo, ins)
            self._eager_writeback(p, state, outs)
        return [], []

    def _eager_attrs(self):
        return {}

    def _eager_inputs(self, p, state, lr):
        return {"Param": [p.value], "Grad": [p.grad], "LearningRate": [lr]}

    def _eager_writeback(self, p, state, outs):
        p.value = outs["ParamOut"][0]


# --------------------------------------------------------------------------


class SGDOptimizer(Optimizer):
    type = "sgd"

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            type="sgd",
            inputs={"Param": [p], "Grad": [g], "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p]},
        )


class MomentumOptimizer(Optimizer):
    type = "momentum"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _eager_attrs(self):
        return {"mu": self._momentum, "use_nesterov": self._use_nesterov}

    def _eager_inputs(self, p, state, lr):
        import jax.numpy as jnp

        if "velocity" not in state:
            state["velocity"] = jnp.zeros_like(p.value)
        return {"Param": [p.value], "Grad": [p.grad], "Velocity": [state["velocity"]],
                "LearningRate": [lr]}

    def _eager_writeback(self, p, state, outs):
        p.value = outs["ParamOut"][0]
        state["velocity"] = outs["VelocityOut"][0]

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        if getattr(self, "_fuse_active", False):
            inputs = {
                "Param": [p],
                "Grad": [g],
                "Velocity": [v],
                "LearningRate": [self._create_param_lr(p)],
            }
            if getattr(self, "_fused_clip_scale", None) is not None:
                inputs["ClipScale"] = [self._fused_clip_scale]
            return block.append_op(
                type="fused_momentum",
                inputs=inputs,
                outputs={"ParamOut": [p], "VelocityOut": [v]},
                attrs={"mu": self._momentum,
                       "use_nesterov": self._use_nesterov},
            )
        return block.append_op(
            type="momentum",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Velocity": [v],
                "LearningRate": [self._create_param_lr(p)],
            },
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentumOptimizer(Optimizer):
    """Reference optimizer.py:1442."""

    type = "lars_momentum"

    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        return block.append_op(
            type="lars_momentum",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Velocity": [v],
                "LearningRate": [self._create_param_lr(p)],
            },
            outputs={"ParamOut": [p], "VelocityOut": [v]},
            attrs={
                "mu": self._momentum,
                "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
            },
        )


class DGCMomentumOptimizer(MomentumOptimizer):
    """Reference optimizer.py:1042 — momentum with deep gradient
    compression (operators/dgc_op.cc): each param keeps U (momentum-
    corrected accumulator) and V (local residual); every step the
    top-s% of |V| ships as the gradient, the rest stays local. The
    momentum lives INSIDE the dgc op (paper's momentum correction), so
    the parameter update itself is plain sgd on the sparsified grad.
    Before rampup_begin_step gradients pass through dense."""

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False, **kw):
        super().__init__(learning_rate, momentum, use_nesterov, **kw)
        self._rampup_begin_step = rampup_begin_step
        self._rampup_step = rampup_step
        self._sparsity = list(sparsity)
        self._dgc_step_var = None

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("dgc_u", p)
            self._add_accumulator("dgc_v", p)
        if self._dgc_step_var is None:
            from .layers.tensor import create_global_var

            self._dgc_step_var = create_global_var(
                [1], 0.0, "float32", persistable=True,
                name=unique_name.generate("dgc_step"),
            )

    def _append_optimize_op(self, block, pg):
        p, g = pg
        u = self._get_accumulator("dgc_u", p)
        v = self._get_accumulator("dgc_v", p)
        enc = block.create_var(
            name=unique_name.generate(f"{p.name}.dgc_enc"),
            shape=p.shape, dtype=p.dtype, stop_gradient=True,
        )
        block.append_op(
            type="dgc",
            inputs={"U": [u], "V": [v], "Grad": [g],
                    "CurrentStep": [self._dgc_step_var]},
            outputs={"UOut": [u], "VOut": [v], "EncodeGrad": [enc]},
            attrs={
                "m": float(self._momentum),
                "use_nesterov": self._use_nesterov,
                "rampup_begin_step": float(self._rampup_begin_step),
                "rampup_step": float(self._rampup_step),
                "sparsity": self._sparsity,
                "op_role": OpRole.Optimize,
            },
        )
        return block.append_op(
            type="sgd",
            inputs={"Param": [p], "Grad": [enc],
                    "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p]},
            attrs={"op_role": OpRole.Optimize},
        )

    def _finish_update(self, block, params_grads):
        block.append_op(
            type="increment",
            inputs={"X": [self._dgc_step_var]},
            outputs={"Out": [self._dgc_step_var]},
            attrs={"step": 1.0, "op_role": OpRole.Optimize},
        )


class AdagradOptimizer(Optimizer):
    type = "adagrad"

    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        return block.append_op(
            type="adagrad",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Moment": [m],
                "LearningRate": [self._create_param_lr(p)],
            },
            outputs={"ParamOut": [p], "MomentOut": [m]},
            attrs={"epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _eager_attrs(self):
        return {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon}

    def _eager_inputs(self, p, state, lr):
        import jax.numpy as jnp

        if "m1" not in state:
            state["m1"] = jnp.zeros_like(p.value)
            state["m2"] = jnp.zeros_like(p.value)
            state["b1p"] = jnp.full((1,), self._beta1, jnp.float32)
            state["b2p"] = jnp.full((1,), self._beta2, jnp.float32)
        return {
            "Param": [p.value], "Grad": [p.grad], "LearningRate": [lr],
            "Moment1": [state["m1"]], "Moment2": [state["m2"]],
            "Beta1Pow": [state["b1p"]], "Beta2Pow": [state["b2p"]],
        }

    def _eager_writeback(self, p, state, outs):
        p.value = outs["ParamOut"][0]
        state["m1"] = outs["Moment1Out"][0]
        state["m2"] = outs["Moment2Out"][0]
        state["b1p"] = outs["Beta1PowOut"][0]
        state["b2p"] = outs["Beta2PowOut"][0]

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2, shape=[1])

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        if getattr(self, "_fuse_active", False):
            # one-pass fused update (kernels/fused_optim.py) over the
            # SAME accumulator vars — ZeRO/partition specs, checkpoints
            # and the donation audit see an identical state surface
            inputs = {
                "Param": [p],
                "Grad": [g],
                "LearningRate": [self._create_param_lr(p)],
                "Moment1": [m1],
                "Moment2": [m2],
                "Beta1Pow": [b1p],
                "Beta2Pow": [b2p],
            }
            if getattr(self, "_fused_clip_scale", None) is not None:
                inputs["ClipScale"] = [self._fused_clip_scale]
            return block.append_op(
                type="fused_adam",
                inputs=inputs,
                outputs={
                    "ParamOut": [p],
                    "Moment1Out": [m1],
                    "Moment2Out": [m2],
                    "Beta1PowOut": [b1p],
                    "Beta2PowOut": [b2p],
                },
                attrs={"beta1": self._beta1, "beta2": self._beta2,
                       "epsilon": self._epsilon},
            )
        return block.append_op(
            type="adam",
            inputs={
                "Param": [p],
                "Grad": [g],
                "LearningRate": [self._create_param_lr(p)],
                "Moment1": [m1],
                "Moment2": [m2],
                "Beta1Pow": [b1p],
                "Beta2Pow": [b2p],
            },
            outputs={
                "ParamOut": [p],
                "Moment1Out": [m1],
                "Moment2Out": [m2],
                "Beta1PowOut": [b1p],
                "Beta2PowOut": [b2p],
            },
            attrs={"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )


class AdamaxOptimizer(Optimizer):
    type = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            type="adamax",
            inputs={
                "Param": [p],
                "Grad": [g],
                "LearningRate": [self._create_param_lr(p)],
                "Moment": [self._get_accumulator("moment", p)],
                "InfNorm": [self._get_accumulator("inf_norm", p)],
                "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)],
            },
            outputs={
                "ParamOut": [p],
                "MomentOut": [self._get_accumulator("moment", p)],
                "InfNormOut": [self._get_accumulator("inf_norm", p)],
            },
            attrs={"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )

    def _finish_update(self, block, params_grads):
        # beta1_pow *= beta1 once per step (reference adamax semantics)
        for p, _ in params_grads:
            b1p = self._get_accumulator("beta1_pow_acc", p)
            block.append_op(
                type="scale",
                inputs={"X": [b1p]},
                outputs={"Out": [b1p]},
                attrs={"scale": self._beta1, "op_role": OpRole.Optimize},
            )


class DpsgdOptimizer(Optimizer):
    type = "dpsgd"

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0, sigma=1.0, **kw):
        super().__init__(learning_rate, **kw)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            type="dpsgd",
            inputs={"Param": [p], "Grad": [g], "LearningRate": [self._create_param_lr(p)]},
            outputs={"ParamOut": [p]},
            attrs={"clip": self._clip, "batch_size": self._batch_size, "sigma": self._sigma},
        )


class DecayedAdagradOptimizer(Optimizer):
    type = "decayed_adagrad"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        return block.append_op(
            type="decayed_adagrad",
            inputs={
                "Param": [p], "Grad": [g], "Moment": [m],
                "LearningRate": [self._create_param_lr(p)],
            },
            outputs={"ParamOut": [p], "MomentOut": [m]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    type = "adadelta"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("__avg_squared_grad", p)
            self._add_accumulator("__avg_squared_update", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            type="adadelta",
            inputs={
                "Param": [p],
                "Grad": [g],
                "AvgSquaredGrad": [self._get_accumulator("__avg_squared_grad", p)],
                "AvgSquaredUpdate": [self._get_accumulator("__avg_squared_update", p)],
            },
            outputs={
                "ParamOut": [p],
                "AvgSquaredGradOut": [self._get_accumulator("__avg_squared_grad", p)],
                "AvgSquaredUpdateOut": [self._get_accumulator("__avg_squared_update", p)],
            },
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    type = "rmsprop"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            type="rmsprop",
            inputs={
                "Param": [p],
                "Grad": [g],
                "Moment": [self._get_accumulator("momentum", p)],
                "MeanSquare": [self._get_accumulator("mean_square", p)],
                "MeanGrad": [self._get_accumulator("mean_grad", p)],
                "LearningRate": [self._create_param_lr(p)],
            },
            outputs={
                "ParamOut": [p],
                "MomentOut": [self._get_accumulator("momentum", p)],
                "MeanSquareOut": [self._get_accumulator("mean_square", p)],
                "MeanGradOut": [self._get_accumulator("mean_grad", p)],
            },
            attrs={
                "epsilon": self._epsilon,
                "decay": self._rho,
                "momentum": self._momentum,
                "centered": self._centered,
            },
        )


class FtrlOptimizer(Optimizer):
    type = "ftrl"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        return block.append_op(
            type="ftrl",
            inputs={
                "Param": [p],
                "SquaredAccumulator": [self._get_accumulator("squared", p)],
                "LinearAccumulator": [self._get_accumulator("linear", p)],
                "Grad": [g],
                "LearningRate": [self._create_param_lr(p)],
            },
            outputs={
                "ParamOut": [p],
                "SquaredAccumOut": [self._get_accumulator("squared", p)],
                "LinearAccumOut": [self._get_accumulator("linear", p)],
            },
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class LambOptimizer(AdamOptimizer):
    """Reference optimizer.py:2699."""

    type = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, exclude_from_weight_decay_fn=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, **kw)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, block, pg):
        p, g = pg
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        return block.append_op(
            type="lamb",
            inputs={
                "Param": [p],
                "Grad": [g],
                "LearningRate": [self._create_param_lr(p)],
                "Moment1": [m1],
                "Moment2": [m2],
                "Beta1Pow": [b1p],
                "Beta2Pow": [b2p],
            },
            outputs={
                "ParamOut": [p],
                "Moment1Out": [m1],
                "Moment2Out": [m2],
                "Beta1PowOut": [b1p],
                "Beta2PowOut": [b2p],
            },
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
                "weight_decay": wd,
            },
        )


# --------------------------------------------------------------------------
# meta-optimizers
# --------------------------------------------------------------------------


class ExponentialMovingAverage:
    """Reference optimizer.py:3166 — shadow vars updated each step via
    in-graph ops; apply() swaps bias-corrected averages in for eval
    (reference applies the 1/(1-decay^t) correction the same way)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or ""
        self._shadows: Dict[str, Variable] = {}
        self._counter: Optional[Variable] = None

    def update(self):
        from .layers.tensor import create_global_var
        from .layers.control_flow import increment

        helper = LayerHelper("ema")
        block = default_main_program().global_block()
        if self._counter is None:
            self._counter = create_global_var(
                [1], 0, "float32", persistable=True,
                name=unique_name.generate("ema_step"),
            )
        increment(self._counter, 1.0)
        for p in default_main_program().all_parameters():
            if not p.trainable:
                continue
            shadow = block.create_var(
                name=unique_name.generate(f"{p.name}.ema"),
                shape=p.shape,
                dtype=p.dtype,
                persistable=True,
                stop_gradient=True,
            )
            helper.set_variable_initializer(shadow, ConstantInitializer(0.0))
            self._shadows[p.name] = shadow
            # shadow = decay*shadow + (1-decay)*param
            block.append_op(
                type="scale",
                inputs={"X": [shadow]},
                outputs={"Out": [shadow]},
                attrs={"scale": self._decay, "op_role": OpRole.Optimize},
            )
            tmp = block.create_var(
                name=unique_name.generate(f"{p.name}.ema_tmp"), stop_gradient=True
            )
            block.append_op(
                type="scale",
                inputs={"X": [p]},
                outputs={"Out": [tmp]},
                attrs={"scale": 1 - self._decay, "op_role": OpRole.Optimize},
            )
            block.append_op(
                type="sum",
                inputs={"X": [shadow, tmp]},
                outputs={"Out": [shadow]},
                attrs={"op_role": OpRole.Optimize},
            )
        default_main_program()._bump()

    def apply(self, executor=None, need_restore=True):
        import contextlib

        import numpy as np

        from .core.executor import global_scope

        @contextlib.contextmanager
        def _ctx():
            import jax.numpy as jnp

            scope = global_scope()
            t = float(np.asarray(scope.find_var(self._counter.name)).reshape(-1)[0]) \
                if self._counter is not None and scope.find_var(self._counter.name) is not None else 0.0
            correction = 1.0 - self._decay**t if t > 0 else 1.0
            saved = {}
            for pname, shadow in self._shadows.items():
                saved[pname] = scope.find_var(pname)
                sv = scope.find_var(shadow.name)
                if sv is not None and correction > 0:
                    scope.set_var(pname, jnp.asarray(sv) / correction)
            try:
                yield
            finally:
                if need_restore:
                    for pname, v in saved.items():
                        scope.set_var(pname, v)

        return _ctx()

    def restore(self, executor=None):
        pass


class ModelAverage(Optimizer):
    """Reference optimizer.py:2862 — running average of params over the
    training trajectory; apply() swaps `sum/count` in for eval,
    restore() puts raw weights back. Construction appends the
    accumulation ops to the current main program (reference attaches in
    __init__ the same way)."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kw):
        super().__init__(0.0, **kw)
        self._window = max_average_window
        self._sums: Dict[str, Variable] = {}
        self._count: Optional[Variable] = None
        self._attach()

    def _attach(self):
        from .layers.tensor import create_global_var
        from .layers.control_flow import increment

        helper = LayerHelper("model_average")
        block = default_main_program().global_block()
        params = [p for p in default_main_program().all_parameters() if p.trainable]
        if not params:
            return
        self._count = create_global_var(
            [1], 0, "float32", persistable=True,
            name=unique_name.generate("avg_count"),
        )
        increment(self._count, 1.0)
        for p in params:
            s = block.create_var(
                name=unique_name.generate(f"{p.name}.avg_sum"),
                shape=p.shape, dtype=p.dtype, persistable=True, stop_gradient=True,
            )
            helper.set_variable_initializer(s, ConstantInitializer(0.0))
            self._sums[p.name] = s
            block.append_op(
                type="sum", inputs={"X": [s, p]}, outputs={"Out": [s]},
                attrs={"op_role": OpRole.Optimize},
            )
        default_main_program()._bump()

    def apply(self, executor=None, need_restore=True):
        import contextlib

        import numpy as np

        from .core.executor import global_scope

        @contextlib.contextmanager
        def _ctx():
            import jax.numpy as jnp

            scope = global_scope()
            cnt = scope.find_var(self._count.name) if self._count is not None else None
            count = float(np.asarray(cnt).reshape(-1)[0]) if cnt is not None else 0.0
            saved = {}
            for pname, svar in self._sums.items():
                saved[pname] = scope.find_var(pname)
                sv = scope.find_var(svar.name)
                if sv is not None and count > 0:
                    scope.set_var(pname, jnp.asarray(sv) / count)
            try:
                yield
            finally:
                if need_restore:
                    for pname, v in saved.items():
                        scope.set_var(pname, v)

        return _ctx()

    def restore(self, executor=None):
        pass


class RecomputeOptimizer(Optimizer):
    """Reference optimizer.py:3714 — wraps an optimizer, marking
    checkpoint vars; backward recomputes segments between checkpoints
    instead of storing activations.

    TPU-native: backward emits one `recompute_segment_grad` op per
    checkpoint-delimited forward segment
    (core/backward.py append_backward_with_recompute); its lowering
    re-runs the segment under jax.checkpoint, so XLA rematerializes the
    segment in the backward pass instead of keeping its activations
    live (reference backward.py:618)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        if self._checkpoints:
            from .core.backward import append_backward_with_recompute

            return append_backward_with_recompute(
                loss, self._checkpoints, parameter_list, no_grad_set
            )
        return self._optimizer.backward(loss, startup_program, parameter_list, no_grad_set)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        self._optimizer._create_global_learning_rate()
        pgs = self.backward(loss, startup_program, parameter_list, no_grad_set)
        ops = self.apply_gradients(pgs)
        return ops, pgs

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


class LookaheadOptimizer:
    """Reference optimizer.py:4007 — fast/slow weights: every k steps,
    slow += alpha*(fast-slow); fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k

    def minimize(self, loss, startup_program=None):
        opt_ops, params_grads = self.inner_optimizer.minimize(loss, startup_program)
        helper = LayerHelper("lookahead")
        block = default_main_program().global_block()
        from .layers.tensor import create_global_var
        from .layers.control_flow import increment, equal
        from .layers.nn import cast, elementwise_mod, where as where_layer
        from .layers.tensor import fill_constant

        step = create_global_var([1], 0, "float32", persistable=True,
                                name=unique_name.generate("lookahead_step"))
        increment(step, 1.0)
        kvar = fill_constant([1], "float32", float(self.k))
        rem = elementwise_mod(step, kvar)
        sync = equal(rem, fill_constant([1], "float32", 0.0))
        for p, g in params_grads:
            slow = block.create_var(
                name=unique_name.generate(f"{p.name}.slow"),
                shape=p.shape, dtype=p.dtype, persistable=True, stop_gradient=True,
            )
            # slow weights start AS the params (reference assigns
            # slow=param in startup), not zero — zero-init would scale
            # all params by alpha at the first sync
            startup_gb = helper.startup_program.global_block()
            startup_gb.create_var(
                name=slow.name, shape=p.shape, dtype=p.dtype, persistable=True
            )
            startup_gb.append_op(
                type="assign", inputs={"X": [p.name]}, outputs={"Out": [slow.name]}
            )
            helper.startup_program._bump()
            # new_slow = slow + alpha*(p - slow) when sync else slow
            from .layers.nn import elementwise_sub, elementwise_add, scale as scale_layer

            upd = elementwise_add(slow, scale_layer(elementwise_sub(p, slow), scale=self.alpha))
            new_slow = where_layer(_bcast_cond(sync, p), upd, slow)
            new_fast = where_layer(_bcast_cond(sync, p), upd, p)
            block.append_op(type="assign", inputs={"X": [new_slow]}, outputs={"Out": [slow]},
                            attrs={"op_role": OpRole.Optimize})
            block.append_op(type="assign", inputs={"X": [new_fast]}, outputs={"Out": [p]},
                            attrs={"op_role": OpRole.Optimize})
        default_main_program()._bump()
        return opt_ops, params_grads


def _bcast_cond(cond_var, template):
    """broadcast a [1] bool to template's shape for where()"""
    from .layers.nn import cast, expand_as
    from .layers.tensor import fill_constant_batch_size_like

    c = cast(cond_var, "float32")
    from .layers.nn import elementwise_mul
    from .layers.tensor import ones as ones_layer

    ones_t = ones_layer(list(template.shape), "float32") if template.shape and all(
        d and d > 0 for d in template.shape
    ) else None
    if ones_t is None:
        raise NotImplementedError("lookahead needs static param shapes")
    b = elementwise_mul(ones_t, c)
    return cast(b, "bool")


class GradientMergeOptimizer:
    """Gradient accumulation over k microbatches with one optimizer
    apply (reference ir/multi_batch_merge_pass.cc — repeat fwd/bwd k
    times, single update; exposed as batch_merge_repeat in dist
    training).

    TPU-native: marks the program; the executor compiles the step as a
    lax.scan over k microbatch slices of the feeds with a running-mean
    grad accumulator, then the optimizer ops run once
    (core/executor.py _build_gradient_merge_fn). The feed batch must be
    divisible by k."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self.inner_optimizer = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = bool(avg)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        # the scan-based merge path owns its gradient flow (running-mean
        # accumulator inside lax.scan) and build_block_fn routes there
        # before the collective branch — a plan stamped by the inner
        # minimize's flag seam would lower its bucket ops as identity
        # while the gauges claim wire savings that never happen
        from .parallel.collectives import suppress_planning

        with suppress_planning():
            out = self.inner_optimizer.minimize(
                loss, startup_program, parameter_list, no_grad_set
            )
        program = loss.block.program
        program._gradient_merge_k = self.k_steps
        program._gradient_merge_avg = self.avg
        program._bump()
        return out

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)


class PipelineOptimizer:
    """Reference optimizer.py:3414 — splits the program at cut points
    into pipeline sections run by SectionWorker threads over scope
    queues (section_worker.cc).

    TPU-native: `cut_list` marks the program; when the executor runs it
    on a mesh with a `pp` axis (CompiledProgram.with_pipeline), the
    step compiles into ONE SPMD GPipe schedule over that axis
    (core/pipeline_program.py): stage activations flow by
    lax.ppermute, jax.grad through the schedule is the pipelined
    backward, the optimizer ops run once on merged grads. Without a pp
    mesh the program trains unpipelined (numerically identical).
    `num_microbatches` replaces the reference's queue/concurrency
    knobs: the feed batch is split into that many microbatches."""

    def __init__(self, optimizer, cut_list=None, place_list=None, concurrency_list=None,
                 queue_size=30, sync_steps=1, start_cpu_core_id=0,
                 num_microbatches=4, schedule="gpipe"):
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"schedule must be 'gpipe' or '1f1b', got {schedule!r}")
        self._optimizer = optimizer
        self._cut_list = cut_list
        self._num_microbatches = int(num_microbatches)
        self._schedule = schedule

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        # the pipeline schedule owns its gradient flow (per-stage
        # grads merged by the schedule itself) — the collective
        # planner's flag seam must not rewrite a program whose cuts
        # are stamped only after this inner minimize returns
        from .parallel.collectives import suppress_planning

        with suppress_planning():
            out = self._optimizer.minimize(loss, startup_program, parameter_list, no_grad_set)
        cuts = []
        for c in self._cut_list or []:
            cs = c if isinstance(c, (list, tuple)) else [c]
            for v in cs:
                n = v.name if isinstance(v, Variable) else str(v)
                if n not in cuts:
                    cuts.append(n)
        if cuts:
            program = loss.block.program
            # fail HERE, at the user-facing API, not deep in lowering
            # (round-2 verdict weak #9): forward-role writes to
            # persistable vars (train-mode batch-norm running stats)
            # have no well-defined per-microbatch merge
            blk = loss.block
            bad = sorted({
                n
                for op in blk.ops
                if int(op.attrs.get("op_role", 0))
                & (OpRole.Backward | OpRole.Optimize | OpRole.LRSched) == 0
                for names in op.outputs.values()
                for n in names
                if blk.has_var(n) and getattr(blk.var(n), "persistable", False)
            })
            if bad:
                raise NotImplementedError(
                    f"PipelineOptimizer: the forward writes persistable "
                    f"vars {bad} — per-microbatch state writes (e.g. "
                    "train-mode batch_norm running stats) are not "
                    "supported under pipelining; use "
                    "batch_norm(use_global_stats=True) or move the op "
                    "out of the pipelined region"
                )
            program._pipeline_cuts = cuts
            program._pipeline_microbatches = self._num_microbatches
            program._pipeline_schedule = self._schedule
            program._bump()
        return out

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


# reference short aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Dpsgd = DpsgdOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer

"""Trace spans with parentage, layered on ``profiler.record_event``.

``profiler.record_event`` gives named host ranges; what it cannot say
is which serving request a micro-batch served, or which supervisor
step a rollback undid — ranges on different threads have no shared
identity. A span adds exactly that: a ``trace_id`` (one per root
request/step), a ``span_id``, and a ``parent_id``, carried in the
event's ``args`` so ``tools_timeline`` can draw Perfetto flow arrows
across threads (serving request -> admission queue -> micro-batch ->
worker -> dispatch -> jit step).

Propagation is ambient within a thread (a thread-local stack: nested
``span()`` calls parent automatically) and explicit across threads —
the submitting side stores ``ctx = span(...)``'s yielded context on
the work item, and the consuming thread opens its span with
``parent=ctx`` (or wraps its whole handling in ``attach(ctx)``).

Cost model: with ``observability_tracing`` off (the default), ``span``
is exactly ``profiler.record_event`` — the pre-existing behavior of
every call site this API replaced. With it on, a span is a slotted
class-based context manager (no generator frames on the hot path):
two lock-free id draws, one TraceAnnotation, one conditional
host-event append, one flight-ring append. ``tools/obs_bench.py``
gates the combined metrics+tracing per-step cost at <3% of a bare
step.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Any, Dict, NamedTuple, Optional

from .. import profiler
from ..flags import _flags  # hot path: direct flag-store reads
from . import flight

__all__ = ["SpanContext", "span", "traced", "attach", "current", "enabled"]


class SpanContext(NamedTuple):
    trace_id: str
    span_id: str


_tls = threading.local()

# process-unique ids without locks or syscalls: a per-process random
# prefix + a per-thread random prefix + a per-thread counter. Spans
# from two processes (or a reused OS thread ident) stay distinct.
_proc_prefix = os.urandom(4).hex()


def _new_id() -> str:
    n = getattr(_tls, "id_n", None)
    if n is None:
        _tls.id_prefix = f"{_proc_prefix}{os.urandom(3).hex()}"
        n = 0
    _tls.id_n = n + 1
    return f"{_tls.id_prefix}{n:08x}"


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def enabled() -> bool:
    return bool(_flags["observability_tracing"])


def current() -> Optional[SpanContext]:
    """The innermost active span on THIS thread (the ambient parent),
    or None outside any span."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


class _AmbientType:
    """Sentinel for "parent from the thread-local stack". Stable repr:
    the api-spec ratchet records default values, and a bare object()'s
    repr embeds a memory address."""

    def __repr__(self):
        return "<ambient parent>"


_AMBIENT = _AmbientType()


class _Span:
    """One traced range. Slotted class CM instead of a
    @contextmanager generator: the per-step/per-request path cannot
    afford two generator frames per span."""

    __slots__ = ("name", "meta", "ctx", "t0", "_ta", "_stack")

    def __init__(self, name: str, args: Optional[Dict[str, Any]], parent):
        st = _stack()
        par = (st[-1] if st else None) if parent is _AMBIENT else parent
        ctx = SpanContext(par.trace_id if par is not None else _new_id(),
                          _new_id())
        meta = dict(args) if args else {}
        meta["trace_id"] = ctx.trace_id
        meta["span_id"] = ctx.span_id
        if par is not None:
            meta["parent_id"] = par.span_id
        self.name = name
        self.meta = meta
        self.ctx = ctx
        self._stack = st

    def __enter__(self) -> SpanContext:
        self._stack.append(self.ctx)
        # the device-trace annotation only matters inside a profiling
        # session (sessions started via paddle_tpu.profiler flip
        # _recording); outside one, skipping it keeps the per-step
        # span within the obs_bench overhead budget
        if profiler._recording:
            import jax

            self._ta = jax.profiler.TraceAnnotation(self.name)
            self._ta.__enter__()
        else:
            self._ta = None
        self.t0 = time.time()
        return self.ctx

    # entry keys the recorder owns: user span args must not be able to
    # collide with them (a span("x", {"name": ...}) would otherwise
    # TypeError at exit)
    _RESERVED = frozenset(("kind", "t", "name", "ts", "dur", "tid"))

    def __exit__(self, *exc):
        dur = time.time() - self.t0
        if self._ta is not None:
            self._ta.__exit__(*exc)
        self._stack.pop()
        profiler.emit_event(self.name, self.t0, dur, self.meta)
        entry = {"kind": "span", "t": self.t0, "name": self.name,
                 "ts": self.t0, "dur": dur, "tid": profiler.thread_tid()}
        for k, v in self.meta.items():
            if k not in self._RESERVED:
                entry[k] = v
        flight.append_entry(entry)
        return False


def span(name: str, args: Optional[Dict[str, Any]] = None, parent=_AMBIENT):
    """Context manager for one traced range. Yields the SpanContext
    (or None when tracing is off — it then degrades to a plain
    ``profiler.record_event``, which is what these call sites did
    before tracing existed).

    ``parent``: default is the ambient thread-local span; pass an
    explicit SpanContext to stitch across threads, or None to force a
    new root trace."""
    if not _flags["observability_tracing"]:
        return profiler.record_event(name, args)
    return _Span(name, args, parent)


class _Attach:
    __slots__ = ("ctx", "_st")

    def __init__(self, ctx):
        self.ctx = ctx

    def __enter__(self):
        self._st = _stack() if self.ctx is not None else None
        if self._st is not None:
            self._st.append(self.ctx)
        return self.ctx

    def __exit__(self, *exc):
        if self._st is not None:
            self._st.pop()
        return False


def attach(ctx: Optional[SpanContext]) -> _Attach:
    """Adopt ``ctx`` as this thread's ambient parent for the duration
    — the cross-thread handoff primitive (a worker wraps its handling
    in ``attach(req.ctx)`` and every span inside parents correctly)."""
    return _Attach(ctx)


def traced(name: Optional[str] = None, args: Optional[Dict[str, Any]] = None):
    """Decorator form: ``@traced("serving/rebatch")``."""

    def deco(fn):
        span_name = name or f"{fn.__module__}.{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with span(span_name, args):
                return fn(*a, **kw)

        return wrapper

    return deco

"""Crash-time flight recorder: the last N telemetry events, always on,
constant memory, dumped as JSON the moment something goes wrong.

The postmortem problem with a training failure at step 40k is that the
evidence — which spans were in flight, what the step time was doing,
what compiled right before — is gone unless someone was already
profiling. An aircraft solves this with a flight recorder: a ring
buffer that is ALWAYS recording and costs the same whether the flight
is 2 minutes or 20 hours. Same here:

* ``note()`` appends one entry (span completions from ``tracing``,
  compile events from the dispatch cache, supervisor lifecycle events
  like retry/rollback/nan, step-metric samples) to a bounded deque —
  O(1), a few hundred ns, capacity ``observability_flight_capacity``.
* ``dump(reason)`` snapshots the ring plus the full metrics registry
  and the recent compile-event history into one JSON file. It is
  called from failure paths — the supervisor's NaN rollback, watchdog
  hang, uncaught loop exception and SIGTERM flush — and from SIGUSR2
  (``install_signal_handlers``), the live-debugging poke for a wedged
  process. A dump path must never make a crash worse: every failure
  inside ``dump`` is swallowed and reported as ``None``.

Deterministic coverage: ``resilience.faults`` (``nan@N``, ``hang@N``)
drives these triggers on demand — tests/test_observability.py asserts
a parseable dump containing the spans and metric samples leading up to
the injected fault.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import signal
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["note", "entries", "clear", "dump", "last_dump_path",
           "install_signal_handlers"]

_log = logging.getLogger("paddle_tpu.observability")

from ..flags import _flags  # the live flag store: note() is hot-path

_lock = threading.Lock()
_ring: Optional[collections.deque] = None
_ring_flag_cap = None  # the RAW flag value the ring was last sized from
_dump_count = [0]
_last_dump: List[Optional[str]] = [None]


def _enabled() -> bool:
    return bool(_flags["observability_flight"])


def _get_ring() -> collections.deque:
    """The ring is sized from the flag at first use and re-sized when
    the flag changes (keeping the newest entries). The resize guard
    remembers the RAW flag value, not the clamped capacity — an
    out-of-range flag must not make every note() rebuild the ring."""
    global _ring, _ring_flag_cap
    raw = _flags["observability_flight_capacity"]
    if _ring is None or raw != _ring_flag_cap:
        cap = max(16, int(raw))
        old = list(_ring) if _ring is not None else []
        _ring = collections.deque(old[-cap:], maxlen=cap)
        _ring_flag_cap = raw
    return _ring


def note(kind: str, **fields) -> None:
    """Append one entry. Safe from any thread; silently a no-op when
    the recorder is disabled. This runs per STEP and per span — the
    direct flag-store read and the single uncontended lock keep it at
    ~1us (covered by the obs_bench <3% gate)."""
    if not _flags["observability_flight"]:
        return
    entry = {"kind": kind, "t": fields.pop("t", None) or time.time()}
    entry.update(fields)
    append_entry(entry)


def append_entry(entry: Dict[str, Any]) -> None:
    """Append a caller-built entry dict (the recorder takes ownership).
    The fast path for span exits, which already hold a dict and must
    not pay a kwargs re-splat; callers are responsible for the
    ``kind``/``t`` keys."""
    if not _flags["observability_flight"]:
        return
    with _lock:
        ring = _ring
        if ring is None or _ring_flag_cap != _flags["observability_flight_capacity"]:
            ring = _get_ring()
        ring.append(entry)


def entries() -> List[Dict[str, Any]]:
    """Consistent snapshot of the ring, oldest first."""
    with _lock:
        return list(_ring) if _ring is not None else []


def clear() -> None:
    with _lock:
        if _ring is not None:
            _ring.clear()


def last_dump_path() -> Optional[str]:
    return _last_dump[0]


def _json_default(o):
    try:
        import numpy as np

        if isinstance(o, np.integer):
            return int(o)
        if isinstance(o, np.floating):
            return float(o)
        if isinstance(o, np.ndarray):
            return o.tolist()
    except Exception:  # noqa: BLE001
        pass
    return str(o)


def dump(reason: str, extra: Optional[Dict[str, Any]] = None,
         path: Optional[str] = None) -> Optional[str]:
    """Write the flight snapshot; returns the file path or None (a
    crash path must never raise out of its own postmortem)."""
    try:
        from .. import profiler, version
        from .registry import registry

        payload = {
            "flight_recorder": 1,
            "reason": reason,
            "time": time.time(),
            "pid": os.getpid(),
            "version": version.full_version,
            "entries": entries(),
            "metrics": registry().snapshot(),
            "compile_events": profiler.compile_events()[-64:],
        }
        if extra:
            payload["extra"] = extra
        if path is None:
            from ..flags import flag

            d = os.path.expanduser(flag("observability_dump_dir") or "")
            if not d:
                d = tempfile.gettempdir()
            os.makedirs(d, exist_ok=True)
            safe = "".join(c if c.isalnum() or c in "-_" else "-"
                           for c in reason)[:48]
            _dump_count[0] += 1
            path = os.path.join(
                d, f"flight_{os.getpid()}_{_dump_count[0]:03d}_{safe}.json")
        with open(path, "w") as f:
            json.dump(payload, f, default=_json_default)
        _last_dump[0] = path
        _log.warning("flight recorder dumped (%s) -> %s", reason, path)
        return path
    except Exception as e:  # noqa: BLE001 — never worsen a crash
        try:
            _log.error("flight recorder dump failed: %r", e)
        except Exception:  # noqa: BLE001
            pass
        return None


def install_signal_handlers() -> bool:
    """SIGUSR2 -> dump (chains any existing handler). Main thread
    only — returns False (installed nothing) elsewhere, since signal
    handlers cannot be set from worker threads.

    The dump runs on a freshly-spawned thread, never in the handler
    itself: the handler executes on the main thread, which may be
    holding the flight/telemetry locks mid-append — dumping inline
    would self-deadlock on those non-reentrant locks. The side thread
    just waits its turn for them."""
    if threading.current_thread() is not threading.main_thread():
        return False
    prev = signal.getsignal(signal.SIGUSR2)

    def _handler(signum, frame):
        threading.Thread(target=dump, args=("sigusr2",),
                         name="pt-flight-dump", daemon=True).start()
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)

    signal.signal(signal.SIGUSR2, _handler)
    return True


def install_excepthook() -> None:
    """Chain sys.excepthook so ANY uncaught exception in the process
    produces a flight dump before the traceback prints. Opt-in (the
    supervisor already dumps on its own failure paths)."""
    prev = sys.excepthook

    def _hook(exc_type, exc, tb):
        dump(f"uncaught:{exc_type.__name__}")
        prev(exc_type, exc, tb)

    sys.excepthook = _hook

"""Cross-process trace-context propagation (W3C-traceparent-style).

tracing.py gives one process spans with trace/span/parent ids, ambient
within a thread and explicit across threads. What it cannot do is
follow a request across a PROCESS boundary: the HTTP hop into
serving/server.py, the disagg prefill->decode handoff, the page-store
TCP wire, and a WorkerPool child all started fresh traces, so a single
disaggregated request's story was shredded across four processes.

This module is the codec for every one of those boundaries:

* **headers** — ``inject``/``extract`` read and write a
  ``traceparent``-style header (plus the ``X-Trace`` alias) on any
  dict-like carrier: ``00-<trace_id>-<span_id>-01``. The field widths
  are tolerant (our ids are 22 hex chars, W3C's are 32/16 — both
  parse), which keeps the codec round-trip-exact for internal ids
  while still accepting a standards-shaped header from an external
  proxy.
* **wire heads** — the page-store client stamps
  ``current_traceparent()`` into each RPC frame's JSON head under the
  ``"trace"`` key; the server attaches it before dispatching, so the
  RPC's span joins the caller's trace across the TCP hop.
* **env** — ``to_env``/``from_env`` carry the context through
  ``PADDLE_TRACE_*`` environment variables into spawned children
  (traffic.WorkerPool stamps its workers at spawn and over the
  control pipe).

The per-process record of a trace is the flight recorder ring itself:
every completed span already lands there with its trace/span/parent
ids (tracing._Span.__exit__), bounded by
``observability_flight_capacity``. ``trace_spans``/``local_trace``
index that ring by trace id — this is what the
``/v1/admin/trace/<id>`` endpoint serves, with the process's pid
stamped on every span so tools/timeline.py can draw process lanes for
the assembled cross-process trace.
"""

from __future__ import annotations

import os
import re
import socket
from typing import Any, Dict, List, Optional

from . import flight, tracing
from .tracing import SpanContext

__all__ = [
    "TRACEPARENT_HEADER", "TRACE_HEADER", "REQUEST_ID_HEADER",
    "ENV_TRACE_CONTEXT", "ENV_TRACE_ID",
    "format_traceparent", "parse_traceparent", "inject", "extract",
    "current_traceparent", "new_request_id", "to_env", "from_env",
    "trace_spans", "local_trace", "orphan_spans",
]

TRACEPARENT_HEADER = "traceparent"
TRACE_HEADER = "X-Trace"
REQUEST_ID_HEADER = "X-Request-Id"
ENV_TRACE_CONTEXT = "PADDLE_TRACE_CONTEXT"
ENV_TRACE_ID = "PADDLE_TRACE_ID"

_VERSION = "00"
_FLAGS_SAMPLED = "01"
# tolerant field widths: internal ids are 22 hex chars (tracing._new_id),
# W3C ids are 32/16 — accept 2..64 so both round-trip exactly
_TRACEPARENT = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{2,64})-([0-9a-f]{2,64})-([0-9a-f]{2})$")


def format_traceparent(ctx: SpanContext) -> str:
    """``SpanContext`` -> the on-the-wire header value."""
    return f"{_VERSION}-{ctx.trace_id}-{ctx.span_id}-{_FLAGS_SAMPLED}"


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """Header value -> ``SpanContext``; None for anything malformed
    (a bad header from a client must never 500 the request)."""
    if not value or not isinstance(value, str):
        return None
    m = _TRACEPARENT.match(value.strip().lower())
    if m is None:
        return None
    return SpanContext(m.group(2), m.group(3))


def inject(ctx: Optional[SpanContext],
           carrier: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Stamp ``ctx`` into a header-dict carrier (both the
    ``traceparent`` spelling and the ``X-Trace`` alias); returns the
    carrier. A None ctx injects nothing — callers can pass
    ``tracing.current()`` unconditionally."""
    if carrier is None:
        carrier = {}
    if ctx is not None:
        tp = format_traceparent(ctx)
        carrier[TRACEPARENT_HEADER] = tp
        carrier[TRACE_HEADER] = tp
    return carrier


def extract(carrier) -> Optional[SpanContext]:
    """Pull a trace context out of any ``.get``-able carrier (a plain
    dict, ``http.client.HTTPMessage`` headers, a wire-frame head).
    ``traceparent`` wins over ``X-Trace``; a bare trace id in
    ``X-Trace`` (no span field) is accepted as a parentless trace."""
    if carrier is None:
        return None
    for key in (TRACEPARENT_HEADER, TRACE_HEADER):
        ctx = parse_traceparent(carrier.get(key))
        if ctx is not None:
            return ctx
    raw = carrier.get(TRACE_HEADER)
    if raw and isinstance(raw, str) and re.match(r"^[0-9a-f]{2,64}$",
                                                 raw.strip().lower()):
        tid = raw.strip().lower()
        return SpanContext(tid, tid)
    return None


def current_traceparent() -> Optional[str]:
    """The ambient span's header value, or None outside any span —
    what a client stamps on an outgoing hop."""
    ctx = tracing.current()
    return format_traceparent(ctx) if ctx is not None else None


def new_request_id() -> str:
    """A fresh correlation id (same generator as span ids, so ids are
    unique across processes) for requests that arrive without an
    ``X-Request-Id``."""
    return tracing._new_id()


# -- env stamping (WorkerPool children) --------------------------------------

def to_env(ctx: Optional[SpanContext]) -> Dict[str, str]:
    """``PADDLE_TRACE_*`` variables carrying ``ctx`` into a spawned
    child; {} when there is no ambient context."""
    if ctx is None:
        return {}
    return {ENV_TRACE_CONTEXT: format_traceparent(ctx),
            ENV_TRACE_ID: ctx.trace_id}


def from_env(environ=None) -> Optional[SpanContext]:
    """Read the context a parent stamped (``to_env``) out of the
    environment — the child's boot spans attach to it."""
    env = os.environ if environ is None else environ
    return parse_traceparent(env.get(ENV_TRACE_CONTEXT))


# -- the per-process trace index ---------------------------------------------
#
# The "bounded completed-span ring" is the flight recorder itself:
# span exits already append {kind: "span", trace_id, span_id,
# parent_id, ts, dur, tid, ...} entries, capped at
# observability_flight_capacity. Indexing by trace id is a scan of at
# most that many entries, paid at query time (an admin endpoint), not
# on the span hot path.

def trace_spans(trace_id: str) -> List[Dict[str, Any]]:
    """Completed spans of ``trace_id`` still in this process's ring,
    oldest first."""
    return [e for e in flight.entries()
            if e.get("kind") == "span" and e.get("trace_id") == trace_id]


def local_trace(trace_id: str, *,
                phase: Optional[str] = None) -> Dict[str, Any]:
    """The ``/v1/admin/trace/<id>`` payload: this process's spans for
    the trace, each stamped with the pid (the process-lane key for
    tools/timeline.py) and the worker identity when known."""
    pid = os.getpid()
    worker = os.environ.get("PADDLE_WORKER_ID") or None
    spans = []
    for e in trace_spans(trace_id):
        s = dict(e)
        s["pid"] = pid
        if worker:
            s.setdefault("worker", worker)
        spans.append(s)
    out: Dict[str, Any] = {
        "trace_id": trace_id,
        "pid": pid,
        "host": socket.gethostname(),
        "spans": spans,
    }
    if worker:
        out["worker"] = worker
    if phase:
        out["phase"] = phase
    return out


def orphan_spans(spans: List[Dict[str, Any]],
                 known_parents=()) -> List[Dict[str, Any]]:
    """Spans whose ``parent_id`` names no span in ``spans`` and none
    of ``known_parents`` (e.g. the client-side span id that arrived in
    the traceparent header). Empty list == the trace is fully
    connected — the propagation round-trip gate."""
    ids = {s.get("span_id") for s in spans} | set(known_parents)
    return [s for s in spans
            if s.get("parent_id") and s["parent_id"] not in ids]

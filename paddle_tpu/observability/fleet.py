"""Fleet metrics aggregation + SLO burn-rate signals.

One process's ``/metrics`` is the unified registry (registry.py); the
deployed system is a FLEET — WorkerPool serving processes behind one
port, disaggregated prefill/decode tiers, multi-host trainers. ROADMAP
item 9's closed-loop autoscaler needs exactly one input this package
did not have: every worker's ``paddle_traffic_*`` /
``paddle_generation_*`` / ``paddle_disagg_*`` series in ONE scrape,
with labels saying which process each sample came from, plus an SLO
verdict computed over the merged view.

* ``FleetAggregator`` — scrapes every known worker endpoint
  (explicitly added, discovered from a ``traffic.WorkerPool``'s
  backend list, or from ``PADDLE_TRAINER_ENDPOINTS`` /
  ``observability_fleet_endpoints``) concurrently with a hard
  per-endpoint timeout; a dead or hung backend marks its series STALE
  (last-good values keep serving, ``paddle_fleet_stale{worker=}``
  flips to 1) and can never stall the scrape. Merged samples are
  re-labeled ``{worker=,phase=,rank=}`` and served by
  ``ServingServer``'s ``/metrics/fleet`` and
  ``observability.fleet_snapshot()``.
* ``SLOMonitor`` — windowed deadline-miss ratio vs an error budget,
  TTFT/ITL p99 vs configured targets (``slo_*`` flags), exported as
  ``paddle_slo_*{cls=}`` gauges. ``burn`` is the classic burn rate:
  miss-ratio / budget, 1.0 = consuming budget exactly as provisioned.
  Sustained burn above ``slo_burn_threshold`` for a full window
  triggers ONE fleet-wide flight dump (local ring + a
  ``POST /v1/admin/flight/dump`` to every live worker) and latches
  until the burn recedes — the postmortem is captured at the moment
  the SLO story turns, not after someone notices the pager.
* ``assemble_trace`` — pulls ``/v1/admin/trace/<id>`` from every
  fleet endpoint and merges the per-process span lists into one
  cross-process trace (tools/timeline.py renders it with process
  lanes).

The monitor's clock is injectable (tests drive burn-rate math on a
fake clock); the aggregator's scrape is pull-only and holds no lock
while any socket is in flight.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import flight

__all__ = [
    "FleetAggregator", "SLOMonitor", "parse_prometheus_text",
    "discover_endpoints", "configure_fleet", "default_aggregator",
    "fleet_snapshot", "fetch_trace", "assemble_trace",
]

_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+([^\s]+)$")
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus_text(text: str) -> List[Tuple[str, Dict[str, str],
                                                   float]]:
    """Exposition text -> ``[(name, labels, value)]``; comments and
    unparseable lines are skipped (a half-written scrape from a dying
    worker must not take down the merge)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if m is None:
            continue
        name, raw_labels, raw_val = m.groups()
        try:
            val = float(raw_val)
        except ValueError:
            continue
        labels = ({k: v for k, v in _LABEL.findall(raw_labels)}
                  if raw_labels else {})
        out.append((name, labels, val))
    return out


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    items = sorted(labels.items())
    return "{" + ",".join(f'{k}="{v}"' for k, v in items) + "}"


def discover_endpoints() -> List[Dict[str, Any]]:
    """Endpoints named by the environment/flags contract:
    ``observability_fleet_endpoints`` (comma list, ``name=url`` or
    bare url) wins; ``PADDLE_TRAINER_ENDPOINTS`` (the multi-host
    trainer contract) adds one rank-labeled endpoint per peer."""
    from ..flags import flag

    eps: List[Dict[str, Any]] = []
    raw = str(flag("observability_fleet_endpoints") or "").strip()
    for i, item in enumerate(p for p in raw.split(",") if p.strip()):
        item = item.strip()
        if "=" in item.split("://")[0]:
            name, url = item.split("=", 1)
        else:
            name, url = f"worker-{i}", item
        if "://" not in url:
            url = f"http://{url}"
        eps.append({"url": url, "worker": name})
    peers = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").strip()
    if peers:
        for rank, ep in enumerate(p for p in peers.split(",")
                                  if p.strip()):
            eps.append({"url": f"http://{ep.strip()}",
                        "worker": f"trainer-{rank}", "rank": rank,
                        "phase": "train"})
    return eps


class _Endpoint:
    __slots__ = ("url", "worker", "phase", "rank", "text", "ok_at",
                 "stale", "errors_total", "last_error")

    def __init__(self, url: str, worker: str,
                 phase: Optional[str] = None,
                 rank: Optional[int] = None):
        self.url = url.rstrip("/")
        self.worker = worker
        self.phase = phase
        self.rank = rank
        self.text: Optional[str] = None   # last-good exposition text
        self.ok_at: Optional[float] = None
        self.stale = True
        self.errors_total = 0
        self.last_error: Optional[str] = None

    def labels(self) -> Dict[str, str]:
        lbl = {"worker": self.worker}
        if self.phase:
            lbl["phase"] = str(self.phase)
        if self.rank is not None:
            lbl["rank"] = str(self.rank)
        return lbl


class FleetAggregator:
    """Merge every known worker's ``/metrics`` into one exposition.

        agg = FleetAggregator()
        agg.add_endpoint(server.address, worker="router", phase="both")
        agg.watch_pool(pool)            # WorkerPool/ThinRouter backends
        text = agg.to_prometheus_text() # scrape + merge, {worker=} labels

    Scrapes run one thread per endpoint with a hard ``timeout_s``; a
    hung socket's thread is abandoned at the deadline (daemon), its
    endpoint marked stale with last-good values still exported.
    """

    def __init__(self, endpoints: Optional[List[Any]] = None, *,
                 timeout_s: Optional[float] = None,
                 slo: Optional["SLOMonitor"] = None,
                 clock: Callable[[], float] = time.monotonic):
        from ..flags import flag

        self._timeout = (float(flag("observability_fleet_timeout_s"))
                         if timeout_s is None else float(timeout_s))
        self._clock = clock
        self._lock = threading.Lock()
        self._eps: List[_Endpoint] = []
        self._pools: List[Any] = []
        self.slo = slo
        self.scrapes_total = 0
        self.last_scrape_ms = 0.0
        for ep in (endpoints or []):
            if isinstance(ep, dict):
                self.add_endpoint(**ep)
            else:
                self.add_endpoint(str(ep))
        for ep in discover_endpoints():
            self.add_endpoint(**ep)

    # -- membership ----------------------------------------------------------
    def add_endpoint(self, url: str, *, worker: Optional[str] = None,
                     phase: Optional[str] = None,
                     rank: Optional[int] = None) -> None:
        if "://" not in url:
            url = f"http://{url}"
        url = url.rstrip("/")
        with self._lock:
            for ep in self._eps:
                if ep.url == url:
                    if worker:
                        ep.worker = worker
                    if phase:
                        ep.phase = phase
                    if rank is not None:
                        ep.rank = rank
                    return
            self._eps.append(_Endpoint(
                url, worker or f"worker-{len(self._eps)}", phase, rank))

    def watch_pool(self, pool) -> None:
        """Track a ``traffic.WorkerPool`` (or anything exposing
        ``metrics_endpoints()``): its current backend list is re-read
        at every scrape, so rolling restarts and scale events never
        leave the fleet view pointing at dead ports."""
        with self._lock:
            if pool not in self._pools:
                self._pools.append(pool)

    def endpoints(self) -> List[Dict[str, Any]]:
        self._refresh_pools()
        with self._lock:
            return [{"url": ep.url, **ep.labels(), "stale": ep.stale,
                     "errors_total": ep.errors_total}
                    for ep in self._eps]

    def _refresh_pools(self) -> None:
        with self._lock:
            pools = list(self._pools)
        for pool in pools:
            try:
                for ep in pool.metrics_endpoints():
                    self.add_endpoint(**ep)
            except Exception:  # noqa: BLE001 — a closing pool mid-scrape
                continue

    # -- scraping ------------------------------------------------------------
    def _fetch(self, ep: _Endpoint) -> None:
        try:
            with urllib.request.urlopen(f"{ep.url}/metrics",
                                        timeout=self._timeout) as r:
                text = r.read().decode("utf-8", "replace")
            ep.text = text
            ep.ok_at = self._clock()
            ep.stale = False
            ep.last_error = None
        except Exception as e:  # noqa: BLE001 — dead/hung backends expected
            ep.stale = True
            ep.errors_total += 1
            ep.last_error = f"{type(e).__name__}: {e}"[:200]

    def scrape(self) -> Dict[str, Any]:
        """One concurrent pass over every endpoint. Wall time is
        bounded by ``timeout_s`` (plus join slack), NOT by the number
        of dead backends — each endpoint gets its own thread and a
        thread past its deadline is abandoned, never joined on."""
        self._refresh_pools()
        with self._lock:
            eps = list(self._eps)
        t0 = time.monotonic()
        threads = [threading.Thread(target=self._fetch, args=(ep,),
                                    name=f"pt-fleet-scrape-{ep.worker}",
                                    daemon=True)
                   for ep in eps]
        for t in threads:
            t.start()
        deadline = time.monotonic() + self._timeout + 0.25
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        self.scrapes_total += 1
        self.last_scrape_ms = (time.monotonic() - t0) * 1e3
        live = sum(1 for ep in eps if not ep.stale)
        return {"endpoints": len(eps), "live": live,
                "stale": len(eps) - live,
                "scrape_ms": round(self.last_scrape_ms, 2)}

    # -- views ---------------------------------------------------------------
    def series(self, name: str) -> List[Tuple[Dict[str, str], float]]:
        """All samples of one family across the last scrape, each
        stamped with its endpoint labels — the SLO monitor's ingest
        path (and any autoscaler's)."""
        out = []
        with self._lock:
            eps = list(self._eps)
        for ep in eps:
            if not ep.text:
                continue
            lbl = ep.labels()
            for fam, labels, val in parse_prometheus_text(ep.text):
                if fam == name:
                    out.append(({**labels, **lbl}, val))
        return out

    def _self_series(self, eps: List[_Endpoint]) -> List[str]:
        lines = [
            "# TYPE paddle_fleet_endpoints gauge",
            f"paddle_fleet_endpoints {len(eps)}",
            "# TYPE paddle_fleet_live gauge",
            f"paddle_fleet_live {sum(1 for e in eps if not e.stale)}",
            "# TYPE paddle_fleet_scrape_ms gauge",
            f"paddle_fleet_scrape_ms {round(self.last_scrape_ms, 3)}",
            "# TYPE paddle_fleet_scrapes_total counter",
            f"paddle_fleet_scrapes_total {self.scrapes_total}",
            "# TYPE paddle_fleet_stale gauge",
            "# TYPE paddle_fleet_scrape_errors_total counter",
        ]
        for ep in eps:
            ls = _label_str(ep.labels())
            lines.append(f"paddle_fleet_stale{ls} {int(ep.stale)}")
            lines.append(
                f"paddle_fleet_scrape_errors_total{ls} {ep.errors_total}")
        return lines

    def to_prometheus_text(self, scrape: bool = True) -> str:
        """The merged fleet exposition (what ``/metrics/fleet``
        serves): every worker's families re-labeled
        ``{worker=,phase=,rank=}``, the aggregator's own
        ``paddle_fleet_*`` health series, and — when an ``SLOMonitor``
        is attached — the ``paddle_slo_*`` burn-rate gauges."""
        if scrape:
            self.scrape()
        with self._lock:
            eps = list(self._eps)
        lines: List[str] = []
        seen_types: Dict[str, str] = {}
        for ep in eps:
            if not ep.text:
                continue
            lbl = ep.labels()
            for name, labels, val in parse_prometheus_text(ep.text):
                if name not in seen_types:
                    kind = ("counter" if name.endswith("_total")
                            else "gauge")
                    seen_types[name] = kind
                    lines.append(f"# TYPE {name} {kind}")
                lines.append(
                    f"{name}{_label_str({**labels, **lbl})} {val}")
        lines.extend(self._self_series(eps))
        if self.slo is not None:
            try:
                self.slo.ingest(self)
            except Exception:  # noqa: BLE001 — the merge must survive
                pass
            lines.extend(self.slo.to_prometheus_lines())
        return "\n".join(lines) + "\n"

    def snapshot(self, scrape: bool = True) -> Dict[str, Any]:
        """JSON view: per-worker family dump + fleet health + SLO
        verdicts — ``observability.fleet_snapshot()``."""
        if scrape:
            self.scrape()
        with self._lock:
            eps = list(self._eps)
        workers = []
        for ep in eps:
            series: Dict[str, Any] = {}
            if ep.text:
                for name, labels, val in parse_prometheus_text(ep.text):
                    series.setdefault(name, []).append(
                        {"labels": labels, "value": val})
            workers.append({"url": ep.url, **ep.labels(),
                            "stale": ep.stale,
                            "errors_total": ep.errors_total,
                            "last_error": ep.last_error,
                            "series": series})
        out: Dict[str, Any] = {
            "fleet": {"endpoints": len(eps),
                      "live": sum(1 for e in eps if not e.stale),
                      "scrapes_total": self.scrapes_total,
                      "scrape_ms": round(self.last_scrape_ms, 2)},
            "workers": workers,
        }
        if self.slo is not None:
            try:
                self.slo.ingest(self)
            except Exception:  # noqa: BLE001
                pass
            out["slo"] = self.slo.snapshot()
        return out

    # -- fleet-wide actions --------------------------------------------------
    def trigger_flight_dump(self, reason: str) -> Dict[str, Any]:
        """Dump the local flight ring AND ask every live worker to
        dump its own (``POST /v1/admin/flight/dump``) — the sustained-
        burn action. Best-effort everywhere: a worker that died
        mid-incident must not stop the others' evidence."""
        local = flight.dump(reason)
        remote: Dict[str, Any] = {}
        with self._lock:
            eps = list(self._eps)

        def ask(ep: _Endpoint):
            try:
                req = urllib.request.Request(
                    f"{ep.url}/v1/admin/flight/dump", data=b"{}",
                    headers={"Content-Type": "application/json"},
                    method="POST")
                with urllib.request.urlopen(req,
                                            timeout=self._timeout) as r:
                    remote[ep.worker] = json.loads(r.read()).get("path")
            except Exception as e:  # noqa: BLE001
                remote[ep.worker] = f"error: {type(e).__name__}"

        threads = [threading.Thread(target=ask, args=(ep,), daemon=True)
                   for ep in eps]
        for t in threads:
            t.start()
        deadline = time.monotonic() + self._timeout + 0.25
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        return {"reason": reason, "local": local, "workers": dict(remote)}


# -- SLO burn rate -----------------------------------------------------------

class _ClsWindow:
    __slots__ = ("samples", "ttft_p99", "itl_p99", "burn_since",
                 "latched")

    def __init__(self):
        # (t, completed_total, missed_total) cumulative samples
        self.samples: List[Tuple[float, float, float]] = []
        self.ttft_p99: Optional[float] = None
        self.itl_p99: Optional[float] = None
        self.burn_since: Optional[float] = None
        self.latched = False


class SLOMonitor:
    """Windowed SLO math over cumulative counters.

    ``record(cls, completed_total=, deadline_missed_total=)`` feeds
    CUMULATIVE totals (what counters are); the monitor differences
    them across a sliding ``window_s`` window:

        miss_ratio = d(missed) / d(completed)      over the window
        burn       = miss_ratio / budget           (1.0 = on budget)

    ``ingest(aggregator)`` pulls the same samples from a fleet scrape
    (summing ``paddle_traffic_*_total`` across workers per ``cls``).
    When ``burn > burn_threshold`` holds for a FULL window the monitor
    fires ``on_burn`` once (default: the aggregator's fleet-wide
    flight dump) and latches until the burn recedes below threshold.

    All timing flows through the injected ``clock`` — burn-rate math
    is testable on a fake clock with zero sleeps.
    """

    def __init__(self, *, budget: Optional[float] = None,
                 ttft_p99_ms: Optional[float] = None,
                 itl_p99_ms: Optional[float] = None,
                 window_s: Optional[float] = None,
                 burn_threshold: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_burn: Optional[Callable[[str], Any]] = None):
        from ..flags import flag

        self.budget = float(flag("slo_deadline_miss_budget")
                            if budget is None else budget)
        self.ttft_p99_ms = float(flag("slo_ttft_p99_ms")
                                 if ttft_p99_ms is None else ttft_p99_ms)
        self.itl_p99_ms = float(flag("slo_itl_p99_ms")
                                if itl_p99_ms is None else itl_p99_ms)
        self.window_s = float(flag("slo_window_s")
                              if window_s is None else window_s)
        self.burn_threshold = float(flag("slo_burn_threshold")
                                    if burn_threshold is None
                                    else burn_threshold)
        self._clock = clock
        self._on_burn = on_burn
        self._lock = threading.Lock()
        self._cls: Dict[str, _ClsWindow] = {}
        self.dumps_total = 0

    def _win(self, cls: str) -> _ClsWindow:
        w = self._cls.get(cls)
        if w is None:
            w = self._cls[cls] = _ClsWindow()
        return w

    def record(self, cls: str = "all", *,
               completed_total: float = 0.0,
               deadline_missed_total: float = 0.0,
               ttft_p99_ms: Optional[float] = None,
               itl_p99_ms: Optional[float] = None,
               t: Optional[float] = None) -> None:
        """Feed one cumulative sample for ``cls`` (call once per
        scrape/tick)."""
        now = self._clock() if t is None else float(t)
        with self._lock:
            w = self._win(cls)
            w.samples.append((now, float(completed_total),
                              float(deadline_missed_total)))
            horizon = now - self.window_s
            # keep one sample at-or-before the horizon as the window's
            # left edge so d(counter) spans the full window
            while len(w.samples) >= 2 and w.samples[1][0] <= horizon:
                w.samples.pop(0)
            if ttft_p99_ms is not None:
                w.ttft_p99 = float(ttft_p99_ms)
            if itl_p99_ms is not None:
                w.itl_p99 = float(itl_p99_ms)
        self._evaluate_burn(cls, now)

    def ingest(self, aggregator: FleetAggregator) -> None:
        """Pull the cumulative counters out of the aggregator's last
        scrape: completed/missed summed across workers per ``cls``,
        TTFT/ITL p99 as the fleet-wide max (the SLO is violated by the
        worst worker, not the average)."""
        done: Dict[str, float] = {}
        miss: Dict[str, float] = {}
        for labels, v in aggregator.series("paddle_traffic_completed_total"):
            cls = labels.get("cls", "all")
            done[cls] = done.get(cls, 0.0) + v
        for labels, v in aggregator.series(
                "paddle_traffic_deadline_miss_total"):
            cls = labels.get("cls", "all")
            miss[cls] = miss.get(cls, 0.0) + v
        ttfts = [v for _l, v in aggregator.series(
            "paddle_generation_ttft_ms_p99")]
        itls = [v for _l, v in aggregator.series(
            "paddle_generation_itl_ms_p99")]
        ttft = max(ttfts) if ttfts else None
        itl = max(itls) if itls else None
        for cls in sorted(set(done) | set(miss)) or ["all"]:
            self.record(cls, completed_total=done.get(cls, 0.0),
                        deadline_missed_total=miss.get(cls, 0.0),
                        ttft_p99_ms=ttft, itl_p99_ms=itl)

    # -- the math -------------------------------------------------------------
    def _window_ratio(self, w: _ClsWindow) -> Tuple[float, float]:
        if len(w.samples) < 2:
            return 0.0, 0.0
        t0, c0, m0 = w.samples[0]
        t1, c1, m1 = w.samples[-1]
        dc = max(0.0, c1 - c0)
        dm = max(0.0, m1 - m0)
        ratio = (dm / dc) if dc > 0 else 0.0
        return ratio, dc

    def _evaluate_burn(self, cls: str, now: float) -> None:
        if self.burn_threshold <= 0:
            return
        with self._lock:
            w = self._win(cls)
            ratio, dc = self._window_ratio(w)
            burn = (ratio / self.budget) if self.budget > 0 else 0.0
            if burn > self.burn_threshold and dc > 0:
                if w.burn_since is None:
                    w.burn_since = now
                sustained = (now - w.burn_since) >= self.window_s
                fire = sustained and not w.latched
                if fire:
                    w.latched = True
                    self.dumps_total += 1
            else:
                w.burn_since = None
                w.latched = False
                fire = False
        if fire:
            cb = self._on_burn
            if cb is not None:
                try:
                    cb(f"slo-burn-{cls}")
                except Exception:  # noqa: BLE001 — monitoring must not crash serving
                    pass
            else:
                flight.dump(f"slo-burn-{cls}")

    # -- exports --------------------------------------------------------------
    def gauges(self) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
        """``paddle_slo_*`` series, one sample per ``cls``."""
        out: Dict[str, List[Tuple[Dict[str, str], float]]] = {
            "paddle_slo_deadline_miss_ratio": [],
            "paddle_slo_error_budget_burn": [],
            "paddle_slo_window_completed": [],
            "paddle_slo_sustained_burn": [],
        }
        with self._lock:
            for cls, w in sorted(self._cls.items()):
                lbl = {"cls": cls}
                ratio, dc = self._window_ratio(w)
                burn = (ratio / self.budget) if self.budget > 0 else 0.0
                out["paddle_slo_deadline_miss_ratio"].append((lbl, ratio))
                out["paddle_slo_error_budget_burn"].append(
                    (lbl, round(burn, 4)))
                out["paddle_slo_window_completed"].append((lbl, dc))
                out["paddle_slo_sustained_burn"].append(
                    (lbl, float(w.latched)))
                if w.ttft_p99 is not None:
                    out.setdefault("paddle_slo_ttft_p99_ms", []).append(
                        (lbl, w.ttft_p99))
                    if self.ttft_p99_ms > 0:
                        out.setdefault("paddle_slo_ttft_target_ratio",
                                       []).append(
                            (lbl, round(w.ttft_p99 / self.ttft_p99_ms, 4)))
                if w.itl_p99 is not None:
                    out.setdefault("paddle_slo_itl_p99_ms", []).append(
                        (lbl, w.itl_p99))
                    if self.itl_p99_ms > 0:
                        out.setdefault("paddle_slo_itl_target_ratio",
                                       []).append(
                            (lbl, round(w.itl_p99 / self.itl_p99_ms, 4)))
            out["paddle_slo_flight_dumps_total"] = [
                ({}, float(self.dumps_total))]
        return out

    def to_prometheus_lines(self) -> List[str]:
        lines: List[str] = []
        for name, series in self.gauges().items():
            kind = "counter" if name.endswith("_total") else "gauge"
            lines.append(f"# TYPE {name} {kind}")
            for labels, val in series:
                lines.append(f"{name}{_label_str(labels)} {val}")
        return lines

    def snapshot(self) -> Dict[str, Any]:
        return {name: [{"labels": dict(l), "value": v}
                       for l, v in series]
                for name, series in self.gauges().items()}

    def register(self) -> "SLOMonitor":
        """Export the gauges through the process-wide registry too, so
        a worker's OWN ``/metrics`` carries its slice of the SLO story
        even when nobody asks the router."""
        from .registry import registry

        registry().register_collector("slo", self.gauges)
        return self


# -- module-default aggregator (observability.fleet_snapshot) ----------------

_default_lock = threading.Lock()
_default: Optional[FleetAggregator] = None


def configure_fleet(endpoints: Optional[List[Any]] = None,
                    **kwargs) -> FleetAggregator:
    """Build (or rebuild) the process-default aggregator behind
    ``observability.fleet_snapshot()``."""
    global _default
    with _default_lock:
        _default = FleetAggregator(endpoints, **kwargs)
        return _default


def default_aggregator() -> FleetAggregator:
    global _default
    with _default_lock:
        if _default is None:
            _default = FleetAggregator(slo=SLOMonitor())
        return _default


def fleet_snapshot(scrape: bool = True) -> Dict[str, Any]:
    """One JSON view of the whole fleet — the programmatic twin of
    ``GET /metrics/fleet`` (endpoints come from ``configure_fleet``,
    the ``observability_fleet_endpoints`` flag, or
    ``PADDLE_TRAINER_ENDPOINTS``)."""
    return default_aggregator().snapshot(scrape=scrape)


# -- cross-process trace assembly --------------------------------------------

def fetch_trace(url: str, trace_id: str, *,
                timeout_s: float = 2.0) -> Optional[Dict[str, Any]]:
    """One process's ``/v1/admin/trace/<id>`` payload, or None."""
    try:
        with urllib.request.urlopen(
                f"{url.rstrip('/')}/v1/admin/trace/{trace_id}",
                timeout=timeout_s) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        return None
    except Exception:  # noqa: BLE001 — a dead worker has no spans to give
        return None


def assemble_trace(trace_id: str, endpoints: List[str], *,
                   timeout_s: float = 2.0) -> Dict[str, Any]:
    """Pull a trace's spans from every process and merge them into one
    cross-process view: ``spans`` sorted by start time (each already
    pid-stamped by ``propagate.local_trace``), ``processes`` naming
    each pid's lane. tools/timeline.py renders this directly."""
    spans: List[Dict[str, Any]] = []
    processes: Dict[int, Dict[str, Any]] = {}
    for url in endpoints:
        payload = fetch_trace(url, trace_id, timeout_s=timeout_s)
        if not payload:
            continue
        pid = int(payload.get("pid", 0))
        processes[pid] = {
            "pid": pid, "url": url,
            "host": payload.get("host"),
            "worker": payload.get("worker"),
            "phase": payload.get("phase"),
        }
        seen = {(s.get("span_id"), s.get("ts")) for s in spans}
        for s in payload.get("spans", []):
            if (s.get("span_id"), s.get("ts")) not in seen:
                spans.append(s)
    spans.sort(key=lambda s: s.get("ts", 0.0))
    return {"trace_id": trace_id, "spans": spans,
            "processes": sorted(processes.values(),
                                key=lambda p: p["pid"])}

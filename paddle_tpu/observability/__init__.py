"""paddle_tpu.observability — unified telemetry for the whole stack.

Reference: the reference treated profiling as a platform layer
(platform/profiler.h RecordEvent + tools/timeline.py); this package
extends that idea to the three things a production deployment actually
needs from one place:

* ``registry`` — ONE process-wide MetricsRegistry. Serving, the
  dispatch/compile caches, executors, supervisors and data loaders all
  register into it, so a single ``/metrics`` scrape (or
  ``observability.snapshot()``) shows the whole stack.
* ``tracing`` — spans with trace/span/parent ids layered on
  ``profiler.record_event``, propagated across threads (serving
  request -> micro-batch -> worker -> jit step; supervisor step ->
  retry/rollback), rendered as Perfetto flow arrows by
  ``tools_timeline``.
* ``flight`` — an always-on constant-memory flight recorder dumped to
  JSON on NaN rollback, watchdog hang, uncaught loop exception,
  SIGTERM and SIGUSR2.
* ``propagate`` — the cross-process trace-context codec
  (traceparent-style headers, page-store wire heads, ``PADDLE_TRACE_*``
  env for spawned workers) plus the per-process trace index behind
  ``/v1/admin/trace/<id>``.
* ``fleet`` — ``FleetAggregator`` merges every worker's ``/metrics``
  into one ``{worker=,phase=,rank=}``-labeled exposition
  (``/metrics/fleet`` / ``fleet_snapshot()``); ``SLOMonitor`` computes
  windowed deadline-miss ratio and error-budget burn over it
  (``paddle_slo_*`` gauges, fleet-wide flight dump on sustained burn).

Live flags (flags.py): ``observability_metrics``,
``observability_tracing``, ``observability_flight``,
``observability_flight_capacity``, ``observability_dump_dir``,
``observability_xla_analysis``, ``observability_fleet_endpoints``,
``observability_fleet_timeout_s``, plus the ``slo_*`` family.
``tools/obs_bench.py --smoke`` gates the enabled-path per-step
overhead at <3% of a bare step (propagation codec included).
"""

from __future__ import annotations

from . import fleet, flight, propagate, registry, tracing
from .fleet import (FleetAggregator, SLOMonitor, assemble_trace,
                    configure_fleet, default_aggregator, fleet_snapshot)
from .flight import dump as flight_dump
from .flight import install_signal_handlers
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       overlap_telemetry, step_telemetry, watch_adapters,
                       watch_collectives, watch_coordinator, watch_disagg,
                       watch_engine, watch_executor, watch_generation,
                       watch_loader, watch_partition, watch_serving,
                       watch_supervisor, watch_traffic)
from .registry import registry as get_registry
from .tracing import SpanContext, attach, current, span, traced

__all__ = [
    "registry", "tracing", "flight", "propagate", "fleet",
    "FleetAggregator", "SLOMonitor", "configure_fleet",
    "default_aggregator", "fleet_snapshot", "assemble_trace",
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "get_registry",
    "span", "traced", "attach", "current", "SpanContext",
    "flight_dump", "install_signal_handlers",
    "watch_serving", "watch_engine", "watch_executor", "watch_supervisor",
    "watch_loader", "watch_generation", "watch_partition",
    "watch_collectives", "watch_coordinator", "watch_traffic",
    "watch_disagg", "watch_adapters", "step_telemetry",
    "overlap_telemetry", "snapshot",
    "to_prometheus_text",
]


def snapshot():
    """One JSON-serializable view of every registered metric family —
    the programmatic twin of ``GET /metrics``."""
    return get_registry().snapshot()


def to_prometheus_text() -> str:
    """The unified Prometheus exposition (what ServingServer's
    ``/metrics`` serves)."""
    return get_registry().to_prometheus_text()

"""Process-wide metrics registry: every subsystem's counters in ONE
scrape.

Reference: the reference stack grew its accounting ad hoc —
platform/profiler.cc events here, per-predictor QPS there — and so did
this reproduction (ServingMetrics, Executor.cache_stats(),
Supervisor.stats(), dispatch cache counters, reader queue depth), four
disjoint surfaces with no shared export path. This module is the one
place they all land:

* **instruments** — first-class labeled Counter/Gauge/Histogram
  handles for code that pushes values on a hot path (step wall time,
  compile counts). Histograms reuse the serving
  ``StreamingHistogram`` (constant memory, log-spaced buckets).
* **collectors** — pull-at-scrape-time callables for subsystems that
  already keep their own locked counters (ServingMetrics, Executor,
  Supervisor, GeneratorLoader). Nothing is double-counted and the hot
  paths pay nothing extra; the registry walks live instances (weak
  sets — a dead Executor stops being scraped, never pins memory) only
  when someone actually asks for ``/metrics`` or ``snapshot()``.

Naming convention (README "Observability"): every family is
``paddle_<subsystem>_<what>[_<unit>]``; counters end in ``_total``,
durations carry ``_ms``/``_s``, and per-instance series are told apart
by labels (``engine=``, ``sup=``, ``loader=``), never by name suffixes.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from ..serving.metrics import StreamingHistogram
from . import flight

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
    "watch_serving", "watch_engine", "watch_executor", "watch_supervisor",
    "watch_loader", "watch_generation", "watch_traffic", "watch_disagg",
    "step_telemetry", "overlap_telemetry",
]


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_str(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


class _Instrument:
    """One (family, labelset) series. The registry hands back the same
    object for the same name+labels, so hot paths can resolve once and
    hold the reference."""

    __slots__ = ("_lock", "_value", "_hist")

    def __init__(self, hist: bool = False):
        self._lock = threading.Lock()
        self._value = 0.0
        self._hist = StreamingHistogram() if hist else None

    # counters / gauges
    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1) -> None:
        with self._lock:
            self._value -= n

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def get(self) -> float:
        with self._lock:
            return self._value

    # histograms
    def observe(self, v: float) -> None:
        with self._lock:
            self._hist.record(v)

    def hist_snapshot(self) -> Dict[str, float]:
        with self._lock:
            return self._hist.snapshot()


class _Family:
    """A named metric family: kind + help + labeled children. Calling
    the instrument methods directly on the family addresses the
    unlabeled child (the common case)."""

    def __init__(self, name: str, kind: str, help: str = ""):
        self.name = name
        self.kind = kind
        self.help = help
        self._lock = threading.Lock()
        self._children: Dict[Tuple, _Instrument] = {}

    def labels(self, **labels) -> _Instrument:
        key = _label_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = _Instrument(hist=self.kind == "histogram")
                self._children[key] = child
            return child

    # unlabeled convenience forwards
    def inc(self, n: float = 1) -> None:
        self.labels().inc(n)

    def dec(self, n: float = 1) -> None:
        self.labels().dec(n)

    def set(self, v: float) -> None:
        self.labels().set(v)

    def get(self) -> float:
        return self.labels().get()

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    def children(self) -> List[Tuple[Tuple, _Instrument]]:
        with self._lock:
            return list(self._children.items())


# Counter/Gauge/Histogram are the same machinery with a declared kind;
# the split exists so the exposition format can say which is which.
Counter = Gauge = Histogram = _Family


class MetricsRegistry:
    """One process-wide registry; ``registry()`` below is the global
    instance everything shares. Instrument creation is idempotent
    (same name -> same family), so rebinding call sites is safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: "Dict[str, _Family]" = {}
        self._collectors: "Dict[str, Callable[[], Dict[str, Any]]]" = {}

    # -- instruments ---------------------------------------------------------
    def _family(self, name: str, kind: str, help: str) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = _Family(name, kind, help)
                self._families[name] = fam
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"not {kind}")
            return fam

    def counter(self, name: str, help: str = "") -> _Family:
        return self._family(name, "counter", help)

    def gauge(self, name: str, help: str = "") -> _Family:
        return self._family(name, "gauge", help)

    def histogram(self, name: str, help: str = "") -> _Family:
        return self._family(name, "histogram", help)

    # -- collectors ----------------------------------------------------------
    def register_collector(self, name: str,
                           fn: Callable[[], Dict[str, Any]]) -> None:
        """``fn()`` is called at scrape time and returns either
        ``{metric_name: number}`` or ``{metric_name: [(labels, number),
        ...]}``. Names ending in ``_total`` export as counters,
        everything else as gauges."""
        with self._lock:
            self._collectors[name] = fn

    def unregister_collector(self, name: str) -> None:
        with self._lock:
            self._collectors.pop(name, None)

    def _collect(self) -> Dict[str, List[Tuple[Tuple, float]]]:
        """Run every collector; one bad collector must not take down
        the whole scrape (its families just vanish until it heals)."""
        with self._lock:
            collectors = list(self._collectors.items())
        merged: Dict[str, List[Tuple[Tuple, float]]] = {}
        for _cname, fn in collectors:
            try:
                produced = fn() or {}
            except Exception:  # noqa: BLE001 — scrape must survive
                continue
            for name, v in produced.items():
                series = merged.setdefault(name, [])
                if isinstance(v, list):
                    for labels, val in v:
                        series.append((_label_key(labels or {}), float(val)))
                elif isinstance(v, (int, float)) and not isinstance(v, bool):
                    series.append(((), float(v)))
        return merged

    # -- exporters -----------------------------------------------------------
    def to_prometheus_text(self) -> str:
        lines: List[str] = []
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            children = fam.children()
            if not children:
                continue
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            if fam.kind == "histogram":
                lines.append(f"# TYPE {fam.name} summary")
                for key, child in children:
                    h = child.hist_snapshot()
                    base = _label_str(key)
                    for q, k in (("0.5", "p50"), ("0.95", "p95"),
                                 ("0.99", "p99")):
                        qkey = key + (("quantile", q),)
                        lines.append(f"{fam.name}{_label_str(qkey)} {h[k]}")
                    lines.append(f"{fam.name}_sum{base} {h['sum']}")
                    lines.append(f"{fam.name}_count{base} {h['count']}")
            else:
                lines.append(f"# TYPE {fam.name} {fam.kind}")
                for key, child in children:
                    lines.append(f"{fam.name}{_label_str(key)} {child.get()}")
        for name, series in sorted(self._collect().items()):
            kind = "counter" if name.endswith("_total") else "gauge"
            lines.append(f"# TYPE {name} {kind}")
            for key, val in series:
                lines.append(f"{name}{_label_str(key)} {val}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable point-in-time view of everything the
        registry knows (instruments + collector output)."""
        inst: Dict[str, Any] = {}
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            vals: Dict[str, Any] = {}
            for key, child in fam.children():
                vals[_label_str(key) or "_"] = (
                    child.hist_snapshot() if fam.kind == "histogram"
                    else child.get())
            if vals:
                inst[fam.name] = {"kind": fam.kind, "values": vals}
        coll: Dict[str, Any] = {}
        for name, series in self._collect().items():
            coll[name] = {_label_str(k) or "_": v for k, v in series}
        return {"instruments": inst, "collected": coll}


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


# -- built-in subsystem collectors ------------------------------------------
#
# Subsystems self-register at construction time (watch_* below); each
# watched instance gets a stable small id for its label. WeakSets keep
# registration from extending any object's lifetime — a test that
# creates 400 Executors leaks nothing into the scrape once they die.

_ids = {"count": 0}
_ids_lock = threading.Lock()


def _obs_id(obj) -> str:
    oid = getattr(obj, "_obs_id", None)
    if oid is None:
        with _ids_lock:
            _ids["count"] += 1
            oid = str(_ids["count"])
        try:
            obj._obs_id = oid
        except AttributeError:  # __slots__ without _obs_id
            oid = str(id(obj))
    return oid


_serving: "weakref.WeakSet" = weakref.WeakSet()
_engines: "weakref.WeakSet" = weakref.WeakSet()
_executors: "weakref.WeakSet" = weakref.WeakSet()
_supervisors: "weakref.WeakSet" = weakref.WeakSet()
_loaders: "weakref.WeakSet" = weakref.WeakSet()
_generation: "weakref.WeakSet" = weakref.WeakSet()
_partitions: "weakref.WeakSet" = weakref.WeakSet()
_collectives: "weakref.WeakSet" = weakref.WeakSet()
_traffic: "weakref.WeakSet" = weakref.WeakSet()
_coordinators: "weakref.WeakSet" = weakref.WeakSet()
_disagg: "weakref.WeakSet" = weakref.WeakSet()
_adapters: "weakref.WeakSet" = weakref.WeakSet()


def watch_serving(metrics) -> None:
    """Called by ServingMetrics.__init__: its snapshot becomes the
    ``paddle_serving_*`` family group, one labeled series per live
    instance."""
    _obs_id(metrics)
    _serving.add(metrics)


def watch_engine(engine) -> None:
    _obs_id(engine)
    _engines.add(engine)


def watch_executor(exe) -> None:
    _executors.add(exe)


def watch_supervisor(sup) -> None:
    _obs_id(sup)
    _supervisors.add(sup)


def watch_loader(loader) -> None:
    _obs_id(loader)
    _loaders.add(loader)


def watch_generation(metrics) -> None:
    """Called by generation.GenerationMetrics.__init__: the engine's
    counters/histograms + page-pool stats become the
    ``paddle_generation_*{engine=}`` family group — per-phase
    prefill/decode occupancy, page-pool utilization, tokens/sec, the
    TTFT / inter-token latency quantiles, and the speculative-decoding
    health series (``paddle_generation_spec_proposed_total`` /
    ``_spec_accepted_total`` / ``_spec_acceptance_rate`` /
    ``_spec_accepted_tokens_per_step``) and the radix prefix-cache
    group (``paddle_generation_radix_*``: hit volume/rate, the
    shared/private/trie page split, CoW forks, leaf evictions) in the
    one scrape."""
    _obs_id(metrics)
    _generation.add(metrics)


def watch_disagg(obj) -> None:
    """Called by disagg ctors (HostPageStore / PageStoreClient /
    DisaggService): anything exposing ``stats_numeric()`` exports as
    the ``paddle_disagg_*{svc=}`` family — pages shipped and pulled,
    wire bytes vs the fp32 bytes they replace (the <=0.3x gate is one
    division away), store hit rate, and the prefill->decode handoff
    latency quantiles."""
    _obs_id(obj)
    _disagg.add(obj)


def watch_adapters(store) -> None:
    """Called by adapters.AdapterStore.__init__: residency + pool
    accounting export as the ``paddle_adapter_*{store=}`` family —
    resident/pinned adapter counts, used vs capacity pool bytes, and
    the upload/evict churn counters (LRU and tenant-quota self-evicts
    broken out) — so "which adapters live where and is the pool
    thrashing" is the same one scrape the router reads."""
    _obs_id(store)
    _adapters.add(store)


def watch_partition(resolved) -> None:
    """Called by partition.ResolvedPartition.__init__: each live
    resolve exports as the ``paddle_partition_*{resolve=}`` family —
    mesh shape (one ``_mesh_<axis>`` gauge per axis), sharded vs
    replicated state bytes, and per-kind var counts — so "how much of
    my model actually sharded" is one scrape, not an HLO dump."""
    _obs_id(resolved)
    _partitions.add(resolved)


def watch_collectives(plan) -> None:
    """Called by parallel.collectives.CollectivePlan.__init__: each
    live plan exports as the ``paddle_collective_*{plan=}`` family —
    bucket count/size, the wire-byte model (fp32 vs quantized, bytes
    saved per step) and the bench-measured overlap hidden fraction and
    max quantization error — so "is the all-reduce actually cheaper"
    is one scrape."""
    _obs_id(plan)
    _collectives.add(plan)


def watch_traffic(controller) -> None:
    """Called by traffic.TrafficController.__init__: per-class/
    per-tenant admit/shed/goodput counters, queue depths, the
    deadline-miss ratio and the shed-before-batch counter become the
    ``paddle_traffic_*{ctrl=}`` family group — the admission story of
    every live controller in the one scrape a router/autoscaler
    already reads."""
    _obs_id(controller)
    _traffic.add(controller)


def watch_coordinator(coord) -> None:
    """Called by distributed.Coordinator.__init__: the multi-host
    world's health becomes the ``paddle_dist_*{coord=}`` family —
    world size / rank / restart count, live ranks + max heartbeat age
    (scanned from the heartbeat dir), and barrier counters with
    cumulative wait — so "is the pod whole and is anyone stalling" is
    one scrape on every rank."""
    _obs_id(coord)
    _coordinators.add(coord)


def _flatten(prefix: str, d: Dict[str, Any], out: Dict[str, float]) -> None:
    for k, v in d.items():
        if isinstance(v, dict):
            _flatten(f"{prefix}_{k}", v, out)
        elif isinstance(v, bool):
            out[f"{prefix}_{k}"] = int(v)
        elif isinstance(v, (int, float)):
            out[f"{prefix}_{k}"] = v


def _labeled(instances: Iterable, label: str, prefix: str,
             snap_fn) -> Dict[str, List]:
    merged: Dict[str, List] = {}
    for obj in list(instances):
        try:
            flat: Dict[str, float] = {}
            _flatten(prefix, snap_fn(obj), flat)
        except Exception:  # noqa: BLE001 — a closing instance mid-scrape
            continue
        lbl = {label: getattr(obj, "_obs_id", "?")}
        for name, v in flat.items():
            merged.setdefault(name, []).append((lbl, v))
    return merged


def _collect_serving():
    # counter families keep their _total suffix from ServingMetrics;
    # nested histogram snapshots flatten to _p50/_p95/... gauges
    return _labeled(_serving, "engine", "paddle_serving",
                    lambda m: m.snapshot())


def _collect_engines():
    return _labeled(_engines, "engine", "paddle_serving_predictor",
                    lambda e: e.predictor_stats_numeric())


def _collect_executors():
    """Aggregated across live executors (per-instance labels would be
    noise: tests mint hundreds). The process-wide dispatch/compile
    cache counters export separately under paddle_dispatch_*."""
    agg: Dict[str, float] = {"paddle_executor_live": 0}
    for exe in list(_executors):
        agg["paddle_executor_live"] += 1
        for k, v in exe._stats.items():
            if isinstance(v, (int, float)):
                agg[f"paddle_executor_{k}"] = agg.get(
                    f"paddle_executor_{k}", 0) + v
        agg["paddle_executor_bound_steps"] = agg.get(
            "paddle_executor_bound_steps", 0) + len(exe._bound)
        agg["paddle_executor_compiled_blocks"] = agg.get(
            "paddle_executor_compiled_blocks", 0) + len(exe._cache)
    return agg


def _collect_dispatch():
    from ..runtime import dispatch

    out: Dict[str, float] = {}
    _flatten("paddle_dispatch", dispatch.cache_stats(), out)
    return out


def _collect_supervisors():
    return _labeled(_supervisors, "sup", "paddle_resilience",
                    lambda s: {k: v for k, v in s.stats().items()
                               if isinstance(v, (int, float, bool))
                               and v is not None})


def _collect_loaders():
    merged: Dict[str, List] = {}
    for loader in list(_loaders):
        lbl = {"loader": getattr(loader, "_obs_id", "?")}
        q = getattr(loader, "_obs_queue", None)
        depth = 0
        if q is not None:
            try:
                depth = q.qsize()
            except Exception:  # noqa: BLE001
                depth = 0
        for name, v in (
                ("paddle_reader_queue_depth", depth),
                ("paddle_reader_position", loader.position()),
                ("paddle_reader_capacity", loader.capacity),
                # feed-starvation visibility: full = producer blocked
                # (consumer/device is the bottleneck), empty = consumer
                # blocked (the input pipeline is the bottleneck)
                ("paddle_reader_buffer_full_stall_total",
                 getattr(loader, "_stall_full", 0)),
                ("paddle_reader_buffer_empty_stall_total",
                 getattr(loader, "_stall_empty", 0)),
                ("paddle_reader_prefetch_depth",
                 getattr(loader, "_active_depth", 0)),
                # multi-host: which slice of the sample stream this
                # loader feeds (rank sharding from the launcher env)
                ("paddle_reader_trainer_id",
                 getattr(loader, "trainer_id", 0)),
                ("paddle_reader_num_trainers",
                 getattr(loader, "num_trainers", 1)),
        ):
            merged.setdefault(name, []).append((lbl, v))
    return merged


def _collect_generation():
    # engines expose stats_numeric(): counters + flattened hist
    # snapshots + cache pool stats; nested dicts flatten to
    # paddle_generation_<group>_<field> gauges
    return _labeled(_generation, "engine", "paddle_generation",
                    lambda e: e.stats_numeric())


def _collect_partition():
    def snap(rp):
        d = dict(rp.summary)
        d["mesh_devices"] = int(rp.mesh.devices.size)
        d["mesh"] = {str(k): int(v) for k, v in rp.mesh_axes().items()}
        d["zero"] = int(rp.config.zero)
        return d

    return _labeled(_partitions, "resolve", "paddle_partition", snap)


def _collect_collectives():
    return _labeled(_collectives, "plan", "paddle_collective",
                    lambda p: p.snapshot())


def _collect_traffic():
    """TrafficMetrics.collect() already emits labeled series (cls=,
    tenant=, reason=); this just stamps each with the controller's
    ctrl= id so two controllers in one process stay distinguishable."""
    merged: Dict[str, List] = {}
    for ctl in list(_traffic):
        try:
            series = ctl.metrics.collect()
        except Exception:  # noqa: BLE001 — a closing controller mid-scrape
            continue
        cid = getattr(ctl, "_obs_id", "?")
        for name, items in series.items():
            out = merged.setdefault(name, [])
            for labels, val in items:
                out.append(({**{"ctrl": cid}, **(labels or {})}, val))
    return merged


def _collect_dist():
    return _labeled(_coordinators, "coord", "paddle_dist",
                    lambda c: c.stats_numeric())


def _collect_disagg():
    return _labeled(_disagg, "svc", "paddle_disagg",
                    lambda s: s.stats_numeric())


def _collect_adapters():
    return _labeled(_adapters, "store", "paddle_adapter",
                    lambda s: s.stats_numeric())


def _collect_build_info():
    from .. import version

    return {"paddle_build_info": [({"version": version.full_version,
                                    "tpu": version.with_tpu}, 1)]}


for _name, _fn in (
    ("serving", _collect_serving),
    ("serving_predictor", _collect_engines),
    ("executor", _collect_executors),
    ("dispatch", _collect_dispatch),
    ("resilience", _collect_supervisors),
    ("reader", _collect_loaders),
    ("generation", _collect_generation),
    ("partition", _collect_partition),
    ("collective", _collect_collectives),
    ("traffic", _collect_traffic),
    ("dist", _collect_dist),
    ("disagg", _collect_disagg),
    ("adapter", _collect_adapters),
    ("build_info", _collect_build_info),
):
    _REGISTRY.register_collector(_name, _fn)


# -- step telemetry ----------------------------------------------------------


class _StepTelemetry:
    """Per-step telemetry. NOT registry instruments per field: a step
    is the hottest path in the process, so all counters live behind
    ONE lock and export through a scrape-time collector like every
    other subsystem (the <3% obs_bench gate covers ``record``)."""

    __slots__ = ("_lock", "steps", "examples", "wall_ms_sum", "hist",
                 "last_ms", "last_eps")

    def __init__(self):
        self._lock = threading.Lock()
        self.steps = 0
        self.examples = 0
        self.wall_ms_sum = 0.0
        self.hist = StreamingHistogram()
        self.last_ms = 0.0
        self.last_eps = 0.0

    def record(self, ms: float, rows: int, step: Optional[int] = None) -> None:
        with self._lock:
            self.steps += 1
            self.examples += rows
            self.wall_ms_sum += ms
            self.hist.record(ms)
            self.last_ms = ms
            if rows and ms > 0:
                self.last_eps = rows / (ms / 1e3)
        # metric sample into the crash-time ring: a flight dump shows
        # the step-time trajectory right up to the fault
        flight.note("step", step=step, ms=round(ms, 4), rows=rows)

    def collect(self) -> Dict[str, float]:
        with self._lock:
            h = self.hist.snapshot()
            out = {
                "paddle_step_total": self.steps,
                "paddle_step_examples_total": self.examples,
                "paddle_step_wall_ms_sum": round(self.wall_ms_sum, 3),
                "paddle_step_wall_ms_p50": h["p50"],
                "paddle_step_wall_ms_p99": h["p99"],
                "paddle_step_last_wall_ms": round(self.last_ms, 4),
                "paddle_step_last_examples_per_s": round(self.last_eps, 1),
            }
            if self.wall_ms_sum > 0:
                out["paddle_step_examples_per_s_avg"] = round(
                    self.examples / (self.wall_ms_sum / 1e3), 1)
            return out


_step_tel = _StepTelemetry()
_REGISTRY.register_collector("step", _step_tel.collect)


def step_telemetry() -> _StepTelemetry:
    return _step_tel


class _OverlapTelemetry:
    """Async-pipeline overlap accounting (BoundStep.run_pipelined).

    Per pipelined step the feeder thread spends ``feed_ms`` of host
    work (normalize + pad + device_put) and the consumer waits
    ``wait_ms`` for the prepared feed. Host work that the consumer did
    NOT wait for ran while the device was busy with the previous step
    — it was hidden. ``hidden_fraction`` is therefore
    ``1 - wait_ms_sum / feed_ms_sum`` (clamped to [0, 1]): 1.0 means
    every host-feed millisecond overlapped the device step, 0.0 means
    the pipeline is fully feed-bound and the async stage bought
    nothing."""

    __slots__ = ("_lock", "steps", "feed_ms_sum", "wait_ms_sum")

    def __init__(self):
        self._lock = threading.Lock()
        self.steps = 0
        self.feed_ms_sum = 0.0
        self.wait_ms_sum = 0.0

    def record(self, feed_ms: float, wait_ms: float) -> None:
        with self._lock:
            self.steps += 1
            self.feed_ms_sum += feed_ms
            self.wait_ms_sum += wait_ms

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            steps = self.steps
            feed = self.feed_ms_sum
            wait = self.wait_ms_sum
        hidden = 1.0 - (min(wait, feed) / feed) if feed > 0 else 0.0
        return {
            "steps": steps,
            "feed_ms_sum": round(feed, 3),
            "wait_ms_sum": round(wait, 3),
            "hidden_fraction": round(hidden, 4),
        }

    def collect(self) -> Dict[str, float]:
        s = self.snapshot()
        return {
            "paddle_step_overlap_steps_total": s["steps"],
            "paddle_step_overlap_feed_ms_sum": s["feed_ms_sum"],
            "paddle_step_overlap_wait_ms_sum": s["wait_ms_sum"],
            "paddle_step_overlap_hidden_fraction": s["hidden_fraction"],
        }


_overlap_tel = _OverlapTelemetry()
_REGISTRY.register_collector("step_overlap", _overlap_tel.collect)


def overlap_telemetry() -> _OverlapTelemetry:
    return _overlap_tel

"""Build helper for the C inference API (paddle_capi.cpp).

Reference: inference/capi/ is compiled into the main inference .so by
CMake; here a g++ one-liner embeds CPython (no pybind11 in the image).
"""

from __future__ import annotations

import os
import subprocess
import sysconfig

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "paddle_capi.cpp")
_SO = os.path.join(_HERE, "build", "libpaddle_capi.so")


def embed_flags():
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION")
    return ([f"-I{inc}"], [f"-L{libdir}", f"-lpython{ver}", "-ldl", "-lm"])


def build(force: bool = False) -> str:
    """Compile (if stale) and return the shared-library path."""
    if (not force and os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        return _SO
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    cflags, ldflags = embed_flags()
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO]
        + cflags + ldflags,
        check=True, capture_output=True,
    )
    return _SO

// C inference API over the paddle_tpu predictor.
//
// Reference: paddle/fluid/inference/capi/paddle_c_api.h + c_api.cc —
// a C ABI (PD_* functions, opaque handles) so non-C++ hosts (Go, R,
// plain C services) can serve models. There the C layer wraps the
// C++ AnalysisPredictor; here the runtime is the Python/JAX stack, so
// the C layer EMBEDS CPython (Py_Initialize + object calls) and holds
// the predictor as an opaque PyObject*. All entry points take the GIL
// (PyGILState), so the handle may be driven from any host thread —
// matching the reference's clone-per-thread serving pattern.
//
// Build: g++ -shared -fPIC paddle_capi.cpp $(python3-config --includes
//        --ldflags --embed) -o libpaddle_capi.so
// (paddle_tpu/capi/build.py does this and caches the .so.)

#include <Python.h>

#include <cstdint>
#include <cstdio>
#include <cstring>

#define PD_CAPI extern "C" __attribute__((visibility("default")))

namespace {

PyObject *g_inference_mod = nullptr;
PyObject *g_np_mod = nullptr;

// Fetch+clear any pending python error into a STICKY thread-local
// buffer (callers must hold the GIL). Sticky: PD_GetLastError returns
// the last captured message even after the canonical fprintf path
// consumed the python-side error state; thread-local so concurrent
// serving threads don't race on one buffer.
thread_local char g_err_buf[4096] = {0};

const char *capture_error() {
  if (!PyErr_Occurred()) return g_err_buf;
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyObject *s = value ? PyObject_Str(value) : nullptr;
  if (s) {
    const char *c = PyUnicode_AsUTF8(s);
    if (c) snprintf(g_err_buf, sizeof(g_err_buf), "%s", c);
    Py_DECREF(s);
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return g_err_buf;
}

struct Gil {
  PyGILState_STATE st;
  Gil() : st(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(st); }
};

// numpy array from a host buffer: frombuffer(mv, dtype).reshape.copy()
PyObject *np_array_from(const void *data, const int64_t *shape, int ndim,
                        const char *dtype, size_t elem_size) {
  int64_t numel = 1;
  for (int i = 0; i < ndim; ++i) numel *= shape[i];
  // allocation failures must surface as a capturable error, not a
  // nullptr deref in the embedding host (round-4 advisor finding)
  PyObject *shape_t = PyTuple_New(ndim);
  if (!shape_t) {
    capture_error();
    return nullptr;
  }
  for (int i = 0; i < ndim; ++i)
    PyTuple_SetItem(shape_t, i, PyLong_FromLongLong(shape[i]));
  PyObject *mv = PyMemoryView_FromMemory(
      (char *)data, numel * (int64_t)elem_size, PyBUF_READ);
  if (!mv) {
    capture_error();
    Py_DECREF(shape_t);
    return nullptr;
  }
  PyObject *arr = PyObject_CallMethod(g_np_mod, "frombuffer", "Os", mv, dtype);
  Py_DECREF(mv);
  if (!arr) {
    Py_DECREF(shape_t);
    return nullptr;
  }
  PyObject *reshaped = PyObject_CallMethod(arr, "reshape", "O", shape_t);
  Py_DECREF(arr);
  Py_DECREF(shape_t);
  if (!reshaped) return nullptr;
  PyObject *copied = PyObject_CallMethod(reshaped, "copy", nullptr);
  Py_DECREF(reshaped);
  return copied;
}


}  // namespace

// -- lifecycle ---------------------------------------------------------------

PD_CAPI int PD_Init() {
  bool we_initialized = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    we_initialized = true;
  }
  {
    Gil gil;
    if (!g_inference_mod) {
      g_inference_mod = PyImport_ImportModule("paddle_tpu.inference");
      if (!g_inference_mod) {
        fprintf(stderr, "PD_Init: %s\n", capture_error());
        return -1;
      }
    }
    if (!g_np_mod) {
      g_np_mod = PyImport_ImportModule("numpy");
      if (!g_np_mod) return -1;
    }
  }
  if (we_initialized) {
    // Py_InitializeEx leaves this thread holding the GIL; release it
    // so other host threads' PyGILState_Ensure can proceed (the
    // clone-per-thread serving pattern). When embedded inside an
    // existing Python process (ctypes), the host owns the GIL.
    PyEval_SaveThread();
  }
  return 0;
}

PD_CAPI const char *PD_GetLastError() {
  Gil gil;  // PyErr_* need the GIL like every other entry point
  return capture_error();
}

// -- predictor ---------------------------------------------------------------

PD_CAPI void *PD_NewPredictor(const char *model_dir) {
  Gil gil;
  PyObject *cfg = PyObject_CallMethod(g_inference_mod, "Config", "s", model_dir);
  if (!cfg) {
    fprintf(stderr, "PD_NewPredictor(Config): %s\n", capture_error());
    return nullptr;
  }
  PyObject *pred =
      PyObject_CallMethod(g_inference_mod, "create_predictor", "O", cfg);
  Py_DECREF(cfg);
  if (!pred) {
    fprintf(stderr, "PD_NewPredictor: %s\n", capture_error());
    return nullptr;
  }
  return pred;
}

PD_CAPI void *PD_ClonePredictor(void *pred) {
  Gil gil;
  PyObject *c = PyObject_CallMethod((PyObject *)pred, "clone", nullptr);
  if (!c) capture_error();  // clear pending state; message kept sticky
  return c;
}

PD_CAPI void PD_DeletePredictor(void *pred) {
  Gil gil;
  Py_XDECREF((PyObject *)pred);
}

// -- IO metadata -------------------------------------------------------------

static int name_list_size(void *pred, const char *method) {
  Gil gil;
  PyObject *names = PyObject_CallMethod((PyObject *)pred, method, nullptr);
  if (!names) {
    capture_error();
    return -1;
  }
  int n = (int)PyList_Size(names);
  Py_DECREF(names);
  return n;
}

// copies the i-th name into out (truncated to cap)
static int name_at(void *pred, const char *method, int i, char *out, int cap) {
  Gil gil;
  PyObject *names = PyObject_CallMethod((PyObject *)pred, method, nullptr);
  if (!names) {
    capture_error();
    return -1;
  }
  PyObject *item = PyList_GetItem(names, i);  // borrowed
  const char *s = item ? PyUnicode_AsUTF8(item) : nullptr;
  int rc = -1;
  if (s) {
    snprintf(out, cap, "%s", s);
    rc = 0;
  } else {
    capture_error();  // clear the IndexError — a pending exception
                      // would poison the next CPython call
  }
  Py_DECREF(names);
  return rc;
}

PD_CAPI int PD_GetInputNum(void *pred) {
  return name_list_size(pred, "get_input_names");
}
PD_CAPI int PD_GetOutputNum(void *pred) {
  return name_list_size(pred, "get_output_names");
}
PD_CAPI int PD_GetInputName(void *pred, int i, char *out, int cap) {
  return name_at(pred, "get_input_names", i, out, cap);
}
PD_CAPI int PD_GetOutputName(void *pred, int i, char *out, int cap) {
  return name_at(pred, "get_output_names", i, out, cap);
}

// -- run ---------------------------------------------------------------------

// float32 input tensor by name
PD_CAPI int PD_SetInputFloat(void *pred, const char *name, const float *data,
                             const int64_t *shape, int ndim) {
  Gil gil;
  PyObject *copied = np_array_from(data, shape, ndim, "float32",
                                   sizeof(float));
  if (!copied) {
    capture_error();
    return -1;
  }

  PyObject *handle =
      PyObject_CallMethod((PyObject *)pred, "get_input_handle", "s", name);
  if (!handle) {
    Py_DECREF(copied);
    capture_error();
    return -1;
  }
  PyObject *r = PyObject_CallMethod(handle, "copy_from_cpu", "O", copied);
  Py_DECREF(copied);
  Py_DECREF(handle);
  if (!r) {
    capture_error();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

PD_CAPI int PD_PredictorRun(void *pred) {
  Gil gil;
  PyObject *r = PyObject_CallMethod((PyObject *)pred, "zero_copy_run", nullptr);
  if (!r) {
    fprintf(stderr, "PD_PredictorRun: %s\n", capture_error());
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

// copy a float32 output into caller storage; returns numel (or -1).
// shape_out (cap ndim_cap) receives the dims, *ndim_out the rank.
PD_CAPI int64_t PD_GetOutputFloat(void *pred, const char *name, float *out,
                                  int64_t capacity, int64_t *shape_out,
                                  int ndim_cap, int *ndim_out) {
  Gil gil;
  PyObject *handle =
      PyObject_CallMethod((PyObject *)pred, "get_output_handle", "s", name);
  if (!handle) {
    capture_error();
    return -1;
  }
  PyObject *arr = PyObject_CallMethod(handle, "copy_to_cpu", nullptr);
  Py_DECREF(handle);
  if (!arr) {
    capture_error();
    return -1;
  }
  PyObject *f32 = PyObject_CallMethod(arr, "astype", "s", "float32");
  Py_DECREF(arr);
  if (!f32) {
    capture_error();
    return -1;
  }
  PyObject *flat = PyObject_CallMethod(f32, "ravel", nullptr);
  PyObject *shape = PyObject_GetAttrString(f32, "shape");
  if (!flat || !shape) {
    Py_XDECREF(flat);
    Py_XDECREF(shape);
    Py_DECREF(f32);
    capture_error();
    return -1;
  }
  int nd = (int)PyTuple_Size(shape);
  if (ndim_out) *ndim_out = nd;
  for (int i = 0; i < nd && i < ndim_cap; ++i)
    shape_out[i] = PyLong_AsLongLong(PyTuple_GetItem(shape, i));
  Py_DECREF(shape);

  // single memcpy out of the contiguous float32 buffer — no per-
  // element Python boxing on the serving hot path
  PyObject *contig =
      PyObject_CallMethod(g_np_mod, "ascontiguousarray", "O", flat);
  Py_DECREF(flat);
  Py_DECREF(f32);
  if (!contig) {
    capture_error();
    return -1;
  }
  Py_buffer view;
  if (PyObject_GetBuffer(contig, &view, PyBUF_SIMPLE) != 0) {
    Py_DECREF(contig);
    capture_error();
    return -1;
  }
  int64_t n = (int64_t)(view.len / sizeof(float));
  int64_t ncopy = n < capacity ? n : capacity;
  memcpy(out, view.buf, (size_t)ncopy * sizeof(float));
  PyBuffer_Release(&view);
  Py_DECREF(contig);
  return n;
}

PD_CAPI void PD_Finalize() {
  // embedding hosts usually skip finalization (jax atexit handlers);
  // provided for completeness.
}

// -- native trainer ----------------------------------------------------------
// Reference: paddle/fluid/train/demo/demo_trainer.cc — load a
// serialized program pair (saved by a python authoring script) and run
// train steps from native code with no Python driver in the loop. The
// programs travel as the Program JSON serialization; the python side
// is paddle_tpu/capi/trainer.py (CTrainer).

namespace {

PyObject *g_trainer_mod = nullptr;

int trainer_set_input(void *t, const char *name, const void *data,
                      const int64_t *shape, int ndim, const char *dtype,
                      size_t elem) {
  Gil gil;
  PyObject *arr = np_array_from(data, shape, ndim, dtype, elem);
  if (!arr) {
    capture_error();
    return -1;
  }
  PyObject *r =
      PyObject_CallMethod((PyObject *)t, "set_input", "sO", name, arr);
  Py_DECREF(arr);
  if (!r) {
    capture_error();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

}  // namespace

PD_CAPI void *PD_TrainerNew(const char *main_json_path,
                            const char *startup_json_path) {
  Gil gil;
  if (!g_trainer_mod) {
    g_trainer_mod = PyImport_ImportModule("paddle_tpu.capi.trainer");
    if (!g_trainer_mod) {
      fprintf(stderr, "PD_TrainerNew(import): %s\n", capture_error());
      return nullptr;
    }
  }
  PyObject *t = PyObject_CallMethod(g_trainer_mod, "new_trainer", "ss",
                                    main_json_path, startup_json_path);
  if (!t) fprintf(stderr, "PD_TrainerNew: %s\n", capture_error());
  return t;
}

PD_CAPI void PD_TrainerDelete(void *t) {
  Gil gil;
  Py_XDECREF((PyObject *)t);
}

PD_CAPI int PD_TrainerSetInputFloat(void *t, const char *name,
                                    const float *data, const int64_t *shape,
                                    int ndim) {
  return trainer_set_input(t, name, data, shape, ndim, "float32",
                           sizeof(float));
}

PD_CAPI int PD_TrainerSetInputInt64(void *t, const char *name,
                                    const int64_t *data, const int64_t *shape,
                                    int ndim) {
  return trainer_set_input(t, name, data, shape, ndim, "int64",
                           sizeof(int64_t));
}

// one train step; *loss_out receives the scalar fetch (e.g. the loss)
PD_CAPI int PD_TrainerRunStep(void *t, const char *fetch_name,
                              double *loss_out) {
  Gil gil;
  PyObject *r =
      PyObject_CallMethod((PyObject *)t, "run_step", "s", fetch_name);
  if (!r) {
    fprintf(stderr, "PD_TrainerRunStep: %s\n", capture_error());
    return -1;
  }
  double v = PyFloat_AsDouble(r);
  Py_DECREF(r);
  if (PyErr_Occurred()) {
    capture_error();
    return -1;
  }
  if (loss_out) *loss_out = v;
  return 0;
}

PD_CAPI int PD_TrainerSavePersistables(void *t, const char *dirname) {
  Gil gil;
  PyObject *r = PyObject_CallMethod((PyObject *)t, "save_persistables", "s",
                                    dirname);
  if (!r) {
    fprintf(stderr, "PD_TrainerSavePersistables: %s\n", capture_error());
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

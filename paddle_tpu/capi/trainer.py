"""Python-side object the native trainer C API drives.

Reference: paddle/fluid/train/demo/demo_trainer.cc — a C++ binary
loads a SERIALIZED program (saved by a python model-authoring script),
runs the startup program once, then loops train steps with no Python
driver in the loop. Here the C layer (paddle_capi.cpp PD_Trainer*)
embeds CPython and drives this class; the programs travel as the
Program JSON serialization (core/framework.py to_json/from_json — the
ProgramDesc-protobuf equivalent)."""

from __future__ import annotations

import numpy as np

from ..core.executor import Executor, Scope, scope_guard
from ..core.framework import Program
from ..core.places import TPUPlace


class CTrainer:
    def __init__(self, main_path: str, startup_path: str):
        with open(main_path) as f:
            self.main = Program.from_json(f.read())
        with open(startup_path) as f:
            self.startup = Program.from_json(f.read())
        self.scope = Scope()
        self.exe = Executor(TPUPlace())
        self.exe.run(self.startup, scope=self.scope)
        self.feed = {}

    def set_input(self, name: str, arr) -> None:
        self.feed[name] = np.asarray(arr)

    def run_step(self, fetch_name: str) -> float:
        (out,) = self.exe.run(self.main, feed=self.feed,
                              fetch_list=[fetch_name], scope=self.scope)
        return float(np.asarray(out).reshape(-1)[0])

    def save_persistables(self, dirname: str) -> None:
        from .. import io

        with scope_guard(self.scope):
            io.save_persistables(self.exe, dirname, self.main)


def new_trainer(main_path: str, startup_path: str) -> CTrainer:
    return CTrainer(main_path, startup_path)

"""Stdlib-only HTTP front end over the ServingEngine.

Reference: the reference's inference-server demo exposed the
AnalysisPredictor over an RPC front end; here it is `http.server`
(zero new dependencies — the container bakes nothing extra) with the
three endpoints a serving deployment actually needs:

    POST /v1/predict   {"inputs": {name: nested-list} | [..], "deadline_ms": n}
                       -> 200 {"outputs": {name: nested-list}}
                          503 overloaded (shed load, retry with backoff)
                          504 deadline exceeded
                          400 malformed request
    POST /v1/generate  {"tokens": [..], "max_new_tokens": n, "eos_id": id,
                        "deadline_ms": n, "stream": true,
                        "adapter"/"model": "summarize-v3"}
                       -> 200 chunked application/x-ndjson: one
                          {"index": i, "token": t} line per token AS IT
                          IS SAMPLED (first line lands at
                          time-to-first-token, long before the
                          generation completes), then a final
                          {"done": true, "finish_reason": ..,
                          "usage": {prompt/completion/verified/
                          accepted_draft token counts}} line — the
                          usage fragment makes speculative-decoding
                          behavior visible per request.
                          stream=false buffers into one JSON object
                          (same usage fragment).
                          Requires a GenerationEngine
                          (ServingServer(..., generation_engine=)).
                          Multi-model serving: ``adapter`` (alias
                          ``model``, or the ``X-Adapter`` header) routes
                          the request through a resident LoRA adapter —
                          mixed-adapter rows share the SAME continuous
                          batch (paddle_tpu.adapters). A non-resident
                          adapter is a 404 (503 shed kind "adapter"
                          through the traffic tier).
    POST /v1/admin/adapters        {"adapter_id": id, "alpha": a,
                        "tenant": t, "factors": {target: {"a": [[..]],
                        "b": [[..]]}}} -> 200 residency row. Uploads a
                        LoRA adapter into the device pool (409 in-use
                        on re-upload of a pinned id, 429 over tenant
                        quota, 503 pool full).
    POST /v1/admin/adapters/evict  {"adapter_id": id, "force": false}
                        -> 200 freed row; 404 not resident; 409 pinned
                        by in-flight rows unless force.
    GET  /healthz      -> 200 while serving, 503 once closed (a load
                          balancer drains on this flip); with a traffic
                          controller attached, also per-class queue
                          depths + drain state + deadline-miss ratio —
                          the one endpoint a router/autoscaler needs
    GET  /metrics      -> Prometheus text: serving counters/quantiles +
                          aggregated predictor bucket stats
    GET  /metrics/fleet -> the MERGED fleet exposition (every known
                          worker scraped and re-labeled
                          {worker=,phase=,rank=} + paddle_slo_* burn
                          gauges); requires ServingServer(...,
                          fleet=FleetAggregator(...))
    GET  /v1/admin/trace/<id> -> this process's completed spans for
                          one trace id (from the flight ring),
                          pid-stamped; observability.assemble_trace
                          merges these across the fleet and
                          tools/timeline.py renders process lanes
    POST /v1/admin/flight/dump -> dump the local flight ring now
                          (the SLO sustained-burn trigger calls this
                          on every worker)

Correlation: every request adopts the client's ``X-Request-Id`` (or
mints one) and extracts ``traceparent``/``X-Trace``
(observability/propagate.py) so handler spans join the caller's
trace; replies echo both ids as headers, error bodies carry
``request_id``/``trace_id`` fields, and a streamed /v1/generate
stamps them on the first NDJSON fragment and the done tail.

With ``ServingServer(engine, traffic=TrafficController(...))`` both
POST endpoints route through the traffic tier: tenant and priority
class come from the ``X-Tenant`` / ``X-Priority`` headers (or payload
fields ``tenant`` / ``priority``), and every shed maps to 503 (429
for tenant-quota sheds) carrying a ``Retry-After`` header computed
from the measured queue-drain rate. Without a controller, bare-engine
``Overloaded`` 503s still carry a coarse Retry-After estimate.

Slow clients: a streamed ``/v1/generate`` whose client stops reading
hits the socket write timeout (``traffic_stream_write_timeout_s``),
which CANCELS the sequence — its KV pages free at the next step
boundary and the handler thread is reaped, instead of the writer
blocking forever while the engine decodes tokens nobody will read.

Each request handler thread just blocks in `engine.predict` — the
coalescing into dense TPU batches happens in the engine's batcher, so
N concurrent HTTP callers become ~N/max_batch predictor calls.
Requests are wrapped in `profiler.record_event` spans, so a profiling
session shows `serving/http_predict` ranges in `tools/timeline.py`
traces right next to the executor's compile/step events.
"""

from __future__ import annotations

import json
import math
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from .engine import DeadlineExceeded, EngineClosed, Overloaded, ServingEngine


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")


def _retry_after_header(seconds: float) -> str:
    # Retry-After is integer seconds on the wire; the JSON body keeps
    # the sub-second value for clients that can use it
    return str(max(1, int(math.ceil(seconds))))


class _Handler(BaseHTTPRequestHandler):
    engine: ServingEngine = None  # set by the subclass ServingServer makes
    gen_engine = None             # generation.GenerationEngine (optional)
    traffic = None                # traffic.TrafficController (optional)
    fleet = None                  # observability.FleetAggregator (optional)
    phase = None                  # disagg worker phase (optional)
    started_at: float = 0.0       # time.monotonic() at server start
    stream_timeout_s: float = 0.0  # /v1/generate write stall budget
    sndbuf: int = 0               # test hook: shrink SO_SNDBUF
    active = None                 # {"n": int} shared with ServingServer
    active_lock = None
    server_version = "paddle_tpu_serving/1.0"
    protocol_version = "HTTP/1.1"
    # per-request correlation state (set by _begin_request)
    _rid = None
    _ctx = None
    _trace_id = None

    # -- plumbing ------------------------------------------------------------
    def log_message(self, fmt, *args):  # noqa: A003 — quiet by default
        pass

    def setup(self):
        super().setup()
        if self.sndbuf:
            self.connection.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDBUF, int(self.sndbuf))

    def _begin_request(self):
        """Correlation ids, once per request: adopt the client's
        ``X-Request-Id`` (or mint one) and extract the incoming trace
        context (``traceparent``/``X-Trace``) so every span in this
        handler joins the caller's trace and every reply echoes the
        ids back."""
        from ..observability import propagate

        self._rid = (self.headers.get(propagate.REQUEST_ID_HEADER)
                     or propagate.new_request_id())
        self._ctx = propagate.extract(self.headers)
        self._trace_id = (self._ctx.trace_id
                          if self._ctx is not None else None)

    def _reply(self, code: int, body: bytes, ctype: str, headers=None):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if self._rid:
            self.send_header("X-Request-Id", self._rid)
        if self._trace_id:
            self.send_header("X-Trace", self._trace_id)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, code: int, obj, headers=None):
        if code >= 400 and isinstance(obj, dict):
            # every error body is log-correlatable: shed storms,
            # deadline 504s and adapter 404s all name the request and
            # (when the caller sent one) the trace they belong to
            if self._rid:
                obj.setdefault("request_id", self._rid)
            if self._trace_id:
                obj.setdefault("trace_id", self._trace_id)
        self._reply(code, json.dumps(obj, default=_json_default).encode(),
                    "application/json", headers=headers)

    def _reply_shed(self, e) -> None:
        """A traffic-layer shed: 503 (429 for quota) + Retry-After
        from the measured drain rate / token-bucket refill."""
        code = 429 if e.kind == "quota" else 503
        self._reply_json(code, {
            "error": str(e), "kind": f"shed:{e.kind}",
            "retry_after_s": round(e.retry_after_s, 3),
        }, headers={"Retry-After": _retry_after_header(e.retry_after_s)})

    def _meta(self, payload) -> tuple:
        """(tenant, priority, adapter) from headers first, payload
        second — a proxy can stamp headers without touching the body.
        ``model`` is an alias for ``adapter`` (the OpenAI-style field
        name); ``base`` / the engine's base version mean no adapter."""
        tenant = self.headers.get("X-Tenant") or payload.get("tenant")
        priority = self.headers.get("X-Priority") or payload.get("priority")
        adapter = (self.headers.get("X-Adapter") or payload.get("adapter")
                   or payload.get("model"))
        if adapter is not None:
            adapter = str(adapter)
            base = getattr(self.gen_engine, "model_version", "base")
            if adapter in ("", "base", base):
                adapter = None
        return tenant, priority, adapter

    # -- endpoints -----------------------------------------------------------
    def do_GET(self):  # noqa: N802 — http.server contract
        self._begin_request()
        if self.path == "/healthz":
            from .. import version

            draining = self.engine.closed or (
                self.traffic is not None and self.traffic.draining)
            body = {
                "status": "draining" if draining else "ok",
                # uptime + build info: a load balancer's drain check and
                # a fleet-rollout "which build is this" probe share one
                # endpoint
                "uptime_s": round(time.monotonic() - self.started_at, 3),
                "version": version.full_version,
                "tpu": version.tpu(),
            }
            if self.phase:
                # disaggregated serving: the router needs to know which
                # phase this worker serves ("prefill"/"decode"/"both")
                # from the SAME probe it already polls for drain state
                body["phase"] = self.phase
            gen = self.gen_engine
            if gen is not None and hasattr(gen, "phase_health"):
                try:
                    body["phases"] = gen.phase_health()
                except Exception:  # noqa: BLE001 — a closing service
                    pass
            if gen is not None and hasattr(gen, "models_fragment"):
                # multi-model serving: base fingerprint/version + the
                # resident adapter set — a router places adapter
                # traffic by residency from the probe it already polls
                try:
                    body["models"] = gen.models_fragment()
                except Exception:  # noqa: BLE001 — a closing service
                    pass
            if self.traffic is not None:
                # per-class queue depths + drain state + miss ratio:
                # the router/autoscaler decides from THIS endpoint,
                # not from scraping and joining three metric families
                body["traffic"] = self.traffic.health()
            self._reply_json(503 if draining else 200, body)
        elif self.path == "/metrics":
            # the UNIFIED registry: serving counters (this engine and
            # any sibling, labeled), dispatch/compile caches, executor,
            # supervisor, reader and step families in ONE scrape
            from .. import observability

            text = observability.to_prometheus_text()
            self._reply(200, text.encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
        elif self.path == "/metrics/fleet":
            # the FLEET exposition: every known worker's registry
            # scraped and merged with {worker=,phase=,rank=} labels +
            # paddle_slo_* burn gauges (observability/fleet.py)
            if self.fleet is None:
                self._reply_json(404, {
                    "error": "no FleetAggregator attached — construct "
                             "ServingServer(..., fleet=FleetAggregator())"})
                return
            try:
                text = self.fleet.to_prometheus_text()
            except Exception as e:  # noqa: BLE001 — a scrape must not 500 loop
                self._reply_json(500, {"error": repr(e)})
                return
            self._reply(200, text.encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
        elif self.path.startswith("/v1/admin/trace/"):
            # this process's slice of one trace (spans still in the
            # flight ring), pid-stamped for process-lane rendering;
            # fleet.assemble_trace merges these across workers
            from ..observability import propagate

            tid = self.path.rsplit("/", 1)[-1].strip().lower()
            payload = propagate.local_trace(tid, phase=self.phase)
            self._reply_json(200 if payload["spans"] else 404, payload)
        else:
            self._reply_json(404, {"error": f"no such endpoint {self.path}"})

    def do_POST(self):  # noqa: N802
        self._begin_request()
        # in-flight accounting: the rolling-restart drain waits for
        # this to hit zero before the process exits, so no accepted
        # request ever dies with its response half-written
        with self.active_lock:
            self.active["n"] += 1
        try:
            if self.path == "/v1/generate":
                self._generate()
            elif self.path == "/v1/predict":
                self._predict()
            elif self.path == "/v1/admin/adapters/evict":
                self._adapter_admin(evict=True)
            elif self.path == "/v1/admin/adapters":
                self._adapter_admin(evict=False)
            elif self.path == "/v1/admin/flight/dump":
                self._flight_dump()
            else:
                self._reply_json(404,
                                 {"error": f"no such endpoint {self.path}"})
        finally:
            with self.active_lock:
                self.active["n"] -= 1

    def _flight_dump(self):
        """Dump this process's flight ring on demand — what the SLO
        monitor's sustained-burn trigger POSTs to every worker so the
        whole fleet's last-N-events land on disk at the same moment."""
        from ..observability import flight

        try:
            path = flight.dump(f"admin:{self._rid}")
            self._reply_json(200, {"path": path, "request_id": self._rid})
        except Exception as e:  # noqa: BLE001 — the server must survive
            self._reply_json(500, {"error": repr(e)})

    def _predict(self):
        from ..observability import tracing

        try:
            length = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
            inputs = payload["inputs"]
            deadline_ms = payload.get("deadline_ms")
            timeout = payload.get("timeout_s")
        except (ValueError, KeyError, TypeError) as e:
            self._reply_json(400, {"error": f"malformed request: {e!r}"})
            return
        for name, v in (("deadline_ms", deadline_ms), ("timeout_s", timeout)):
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, (int, float))):
                # client-input errors are 400s, never 500s: a string
                # deadline would otherwise surface as a TypeError deep
                # in the engine and read as a server fault
                self._reply_json(
                    400, {"error": f"{name} must be a number, got {v!r}"})
                return
        from ..traffic import TrafficShed, engine_retry_after

        try:
            # span (record_event when tracing is off): the HTTP handler
            # thread is the trace root — or, when the client sent a
            # traceparent, a child of the caller's span (the router ->
            # worker hop joins one trace)
            with tracing.attach(self._ctx), \
                 tracing.span("serving/http_predict",
                              {"request_id": self._rid}) as _sctx:
                if _sctx is not None:
                    self._trace_id = _sctx.trace_id
                if self.traffic is not None:
                    tenant, priority, _ = self._meta(payload)
                    outs = self.traffic.predict(
                        inputs, tenant=tenant, priority=priority,
                        deadline_ms=deadline_ms, timeout=timeout)
                else:
                    outs = self.engine.predict(inputs,
                                               deadline_ms=deadline_ms,
                                               timeout=timeout)
        except TrafficShed as e:
            self._reply_shed(e)
        except Overloaded as e:
            ra = engine_retry_after(self.engine)
            self._reply_json(
                503, {"error": str(e), "kind": "overloaded",
                      "retry_after_s": round(ra, 3)},
                headers={"Retry-After": _retry_after_header(ra)})
        except (DeadlineExceeded, TimeoutError) as e:
            self._reply_json(504, {"error": str(e), "kind": "deadline"})
        except EngineClosed as e:
            self._reply_json(503, {"error": str(e), "kind": "closed"})
        except (ValueError, KeyError) as e:
            self._reply_json(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — the server must survive any request
            self._reply_json(500, {"error": repr(e)})
        else:
            names = self.engine._fetch_names
            self._reply_json(200, {"outputs": {
                n: np.asarray(o) for n, o in zip(names, outs)}})

    # -- adapter lifecycle (admin) -------------------------------------------
    def _adapter_admin(self, evict: bool):
        """Upload / evict LoRA adapters against the GenerationEngine's
        AdapterStore. The factor payload is plain JSON nested lists —
        an operator can curl a small adapter in; bulk paths should go
        through ``store.upload`` in-process."""
        store = getattr(self.gen_engine, "adapter_store", None)
        if store is None:
            self._reply_json(404, {
                "error": "no AdapterStore attached — construct the "
                         "GenerationEngine with adapter_store= or set "
                         "the adapter_pool_max_bytes flag"})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
            adapter_id = str(payload["adapter_id"])
        except (ValueError, KeyError, TypeError) as e:
            self._reply_json(400, {"error": f"malformed request: {e!r}"})
            return
        from ..adapters import (AdapterError, AdapterInUse, AdapterMissing,
                                AdapterPoolFull, AdapterQuotaExceeded)

        try:
            if evict:
                row = store.evict(adapter_id,
                                  force=bool(payload.get("force", False)))
                self._reply_json(200, {"evicted": row})
                return
            raw = payload["factors"]
            if not isinstance(raw, dict) or not raw:
                raise ValueError("factors must be a non-empty object "
                                 "{target: {'a': [[..]], 'b': [[..]]}}")
            factors = {}
            for t, ab in raw.items():
                if isinstance(ab, dict):
                    a, b = ab["a"], ab["b"]
                else:
                    a, b = ab
                factors[str(t)] = (np.asarray(a, np.float32),
                                   np.asarray(b, np.float32))
            alpha = payload.get("alpha")
            row = store.upload(
                factors=factors, adapter_id=adapter_id,
                alpha=float(alpha) if alpha is not None else None,
                tenant=payload.get("tenant"))
            self._reply_json(200, {"uploaded": row})
        except AdapterQuotaExceeded as e:
            self._reply_json(429, {"error": str(e), "kind": "quota"})
        except AdapterPoolFull as e:
            self._reply_json(503, {"error": str(e), "kind": "pool_full"})
        except AdapterInUse as e:
            self._reply_json(409, {"error": str(e), "kind": "in_use"})
        except AdapterMissing as e:
            self._reply_json(404, {"error": str(e), "kind": "missing"})
        except (AdapterError, ValueError, KeyError, TypeError) as e:
            self._reply_json(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — the server must survive
            self._reply_json(500, {"error": repr(e)})

    # -- autoregressive generation (streamed) -------------------------------
    def _write_chunk(self, data: bytes):
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _generate(self):
        from ..observability import tracing

        if self.gen_engine is None:
            self._reply_json(404, {
                "error": "no GenerationEngine attached — construct "
                         "ServingServer(engine, generation_engine=...)"})
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
            tokens = payload["tokens"]
            if (not isinstance(tokens, list) or not tokens
                    or not all(isinstance(t, int) for t in tokens)):
                raise ValueError("tokens must be a non-empty int list")
            max_new = payload.get("max_new_tokens")
            eos_id = payload.get("eos_id")
            deadline_ms = payload.get("deadline_ms")
            do_stream = bool(payload.get("stream", True))
            for name, v in (("max_new_tokens", max_new),
                            ("eos_id", eos_id),
                            ("deadline_ms", deadline_ms)):
                if v is not None and (isinstance(v, bool)
                                      or not isinstance(v, (int, float))):
                    raise ValueError(f"{name} must be a number, got {v!r}")
        except (ValueError, KeyError, TypeError) as e:
            self._reply_json(400, {"error": f"malformed request: {e!r}"})
            return
        from .engine import DeadlineExceeded as _DE
        from .engine import EngineClosed as _EC
        from .engine import Overloaded as _OV
        from ..traffic import TrafficShed, generation_retry_after

        from ..adapters import AdapterError, AdapterMissing

        ticket = None
        tenant, priority, adapter = self._meta(payload)
        try:
            with tracing.attach(self._ctx), \
                 tracing.span("serving/http_generate",
                              {"request_id": self._rid}) as _sctx:
                if _sctx is not None:
                    self._trace_id = _sctx.trace_id
                if self.traffic is not None:
                    ticket = self.traffic.submit_generation(
                        tokens, tenant=tenant, priority=priority,
                        deadline_ms=deadline_ms, max_new_tokens=max_new,
                        eos_id=eos_id if eos_id is not None else "default",
                        adapter=adapter)
                    # blocks until the dispatcher admits the prompt
                    # into the continuous batch (or sheds it)
                    stream = ticket.stream(
                        timeout=(deadline_ms / 1e3 + 5.0
                                 if deadline_ms is not None else 600.0))
                else:
                    # adapter rides only when named: engine ducks that
                    # don't host adapters (e.g. disagg.DisaggService)
                    # keep working behind the same endpoint
                    kw = {"adapter": adapter} if adapter is not None else {}
                    stream = self.gen_engine.submit(
                        tokens, max_new_tokens=max_new,
                        eos_id=eos_id if eos_id is not None else "default",
                        deadline_ms=deadline_ms, **kw)
        except AdapterMissing as e:
            # the adapter is simply not resident: a 404 tells the
            # router to upload (or place the request elsewhere), where
            # a 503 would read as "back off and retry the same worker"
            self._reply_json(404, {"error": str(e), "kind": "adapter"})
            return
        except AdapterError as e:
            self._reply_json(409, {"error": str(e), "kind": "adapter"})
            return
        except TrafficShed as e:
            self._reply_shed(e)
            return
        except _OV as e:
            ra = generation_retry_after(self.gen_engine)
            self._reply_json(
                503, {"error": str(e), "kind": "overloaded",
                      "retry_after_s": round(ra, 3)},
                headers={"Retry-After": _retry_after_header(ra)})
            return
        except _EC as e:
            self._reply_json(503, {"error": str(e), "kind": "closed"})
            return
        except (_DE, TimeoutError) as e:
            if ticket is not None:
                # the client is gone after this 504: withdraw the
                # still-queued request so it never spends decode lanes
                # and KV pages on a stream nobody will read
                ticket.cancel()
            self._reply_json(504, {"error": str(e), "kind": "deadline"})
            return
        except ValueError as e:
            self._reply_json(400, {"error": str(e)})
            return
        def usage_fragment():
            # per-request spec-decode visibility: how many tokens the
            # draft proposed AND the target accepted vs the total the
            # target verified — an operator can see speculative
            # behavior per response, not just in fleet-wide gauges
            u = stream.usage()
            u["prompt_tokens"] = len(tokens)
            return u

        if not do_stream:
            try:
                out = stream.result()
            except (_DE, TimeoutError) as e:
                self._reply_json(504, {"error": str(e), "kind": "deadline"})
                return
            except Exception as e:  # noqa: BLE001
                self._reply_json(500, {"error": repr(e)})
                return
            self._reply_json(200, {"tokens": out,
                                   "finish_reason": stream.finish_reason,
                                   "usage": usage_fragment()})
            return
        # streamed: chunked NDJSON, one line per token the moment the
        # engine samples it — the whole point of continuous batching is
        # that this first line does NOT wait for the generation to end
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        if self._rid:
            self.send_header("X-Request-Id", self._rid)
        if self._trace_id:
            self.send_header("X-Trace", self._trace_id)
        self.end_headers()
        # slow-client budget: a client that stops READING eventually
        # fills the socket buffers and blocks our next write; the
        # timeout turns that permanent stall into a cancel — the
        # sequence retires at the next step boundary (KV pages freed),
        # the engine stops decoding tokens nobody will read, and this
        # handler thread is reaped instead of leaking. Routine
        # hangups (RST/EPIPE) take the same path.
        if self.stream_timeout_s and self.stream_timeout_s > 0:
            self.connection.settimeout(float(self.stream_timeout_s))
        n = 0
        try:
            for tok in stream:
                line = {"index": n, "token": int(tok)}
                if n == 0:
                    # the trace/request ids ride the FIRST fragment (at
                    # time-to-first-token) so a client can correlate a
                    # stream it later abandons; the tail repeats them
                    if self._trace_id:
                        line["trace_id"] = self._trace_id
                    if self._rid:
                        line["request_id"] = self._rid
                self._write_chunk(json.dumps(line).encode() + b"\n")
                n += 1
            tail = {"done": True, "finish_reason": stream.finish_reason,
                    "n_tokens": n, "usage": usage_fragment()}
        except OSError:   # stalled (socket.timeout) or hung-up client
            stream.cancel()
            self.close_connection = True
            return
        except Exception as e:  # noqa: BLE001 — deadline/cancel mid-stream
            tail = {"done": True, "finish_reason": stream.finish_reason
                    or "error", "n_tokens": n, "error": str(e),
                    "usage": usage_fragment()}
        if self._trace_id:
            tail.setdefault("trace_id", self._trace_id)
        if self._rid:
            tail.setdefault("request_id", self._rid)
        try:
            self._write_chunk(json.dumps(tail).encode() + b"\n")
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except OSError:
            stream.cancel()   # client hung up: stop wasting decode lanes
            self.close_connection = True


class _QuietThreadingServer(ThreadingHTTPServer):
    daemon_threads = True

    def handle_error(self, request, client_address):
        import sys

        et = sys.exc_info()[0]
        if et is not None and issubclass(et, (ConnectionError, TimeoutError)):
            return  # client hung up mid-request: routine, not a server bug
        super().handle_error(request, client_address)


class _ReuseportThreadingServer(_QuietThreadingServer):
    """SO_REUSEPORT listener: N worker PROCESSES bind the same
    host:port and the kernel load-balances new connections across
    them — the traffic.WorkerPool scale-out front."""

    def server_bind(self):
        if not hasattr(socket, "SO_REUSEPORT"):
            raise OSError(
                "SO_REUSEPORT is not supported on this platform; use "
                "traffic.ThinRouter / WorkerPool(use_reuseport=False)")
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


class ServingServer:
    """Own the HTTP listener; the engine's lifecycle stays the
    caller's. `port=0` binds an ephemeral port (tests, examples);
    `.port` reports the bound one.

    ``traffic=`` routes both POST endpoints through a
    ``traffic.TrafficController`` (priority/tenant admission, deadline
    sheds with Retry-After). ``reuse_port=True`` binds with
    SO_REUSEPORT so sibling worker processes share the port.
    ``stream_write_timeout_s`` overrides the
    ``traffic_stream_write_timeout_s`` flag (slow-reader cancel);
    ``sndbuf`` shrinks the per-connection send buffer (test hook for
    the slow-client regression test)."""

    def __init__(self, engine: ServingEngine, host: str = "127.0.0.1",
                 port: int = 0, start: bool = True, generation_engine=None,
                 traffic=None, reuse_port: bool = False,
                 stream_write_timeout_s: Optional[float] = None,
                 sndbuf: int = 0, phase: Optional[str] = None,
                 fleet=None):
        from ..flags import flag

        self.engine = engine
        self.generation_engine = generation_engine
        self.traffic = traffic
        self.fleet = fleet
        if phase is None:
            phase = getattr(generation_engine, "phase", None)
        self.phase = str(phase) if phase else None
        if stream_write_timeout_s is None:
            stream_write_timeout_s = float(
                flag("traffic_stream_write_timeout_s"))
        self._active = {"n": 0}
        self._active_lock = threading.Lock()
        handler = type("_BoundHandler", (_Handler,),
                       {"engine": engine, "gen_engine": generation_engine,
                        "traffic": traffic, "fleet": fleet,
                        "phase": self.phase,
                        "stream_timeout_s": float(stream_write_timeout_s),
                        "sndbuf": int(sndbuf),
                        "active": self._active,
                        "active_lock": self._active_lock,
                        "started_at": time.monotonic()})
        server_cls = (_ReuseportThreadingServer if reuse_port
                      else _QuietThreadingServer)
        self._httpd = server_cls((host, port), handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    def active_requests(self) -> int:
        """POST requests currently inside a handler (the drain
        protocol's exit condition)."""
        with self._active_lock:
            return self._active["n"]

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServingServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="pt-serving-http", daemon=True)
            self._thread.start()
        return self

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(10)
            self._thread = None

    def __enter__(self) -> "ServingServer":
        return self

    def __exit__(self, *exc):
        self.close()

"""Inference serving: dynamic micro-batching over the Predictor.

Reference: paddle/fluid/inference/ ended at a clone-per-thread
predictor; the server layer above it — request coalescing, admission
control, deadlines, metrics — is what this subsystem adds, TPU-native:
concurrent single requests become dense bucketed batches (one XLA
executable per bucket, batch assembled up to `serving_max_batch_size`
rows or `serving_batch_timeout_ms`, whichever first), dispatched over
a pool of Predictor clones that share compiled executables through the
runtime dispatch cache.

    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.serving import ServingEngine, ServingServer

    cfg = Config(model_dir); cfg.enable_shape_bucketing()
    engine = ServingEngine(create_predictor(cfg))
    outs = engine.predict({"ids": ids, "mask": mask}, deadline_ms=50)
    srv = ServingServer(engine, port=8500)   # /v1/predict /healthz /metrics

Stateful autoregressive decode (streamed ``POST /v1/generate``) lives
in paddle_tpu.generation; pass its engine via
``ServingServer(engine, generation_engine=...)``.
"""

from .engine import (
    DeadlineExceeded,
    EngineClosed,
    Overloaded,
    RequestCancelled,
    ServingEngine,
    ServingError,
    ServingFuture,
)
from .metrics import ServingMetrics, StreamingHistogram
from .server import ServingServer

__all__ = [
    "ServingEngine",
    "ServingServer",
    "ServingMetrics",
    "StreamingHistogram",
    "ServingFuture",
    "ServingError",
    "Overloaded",
    "DeadlineExceeded",
    "EngineClosed",
    "RequestCancelled",
]

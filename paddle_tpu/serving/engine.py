"""ServingEngine: dynamic micro-batching over the Predictor.

Reference: paddle/fluid/inference/ shipped a server story around the
AnalysisPredictor (clone-per-thread, each caller holding its own IO
handles). That leaves batching to the caller — and on TPU an unbatched
request stream is the worst case: XLA executables are compiled per
shape, and a batch-1 call wastes the systolic array. Modern TPU
serving (Ragged Paged Attention etc., PAPERS.md) assumes a layer that
coalesces concurrent requests into dense batches; this module is that
layer.

Shape of the machine:

    submit() ──> bounded admission queue ──> batcher thread
                                               │  coalesce up to
                                               │  max_batch_size rows or
                                               │  batch_timeout_ms,
                                               │  whichever first
                                               ▼
                              batch queue ──> N worker threads, each
                                              holding a Predictor.clone()

* Admission control: the queue is bounded (`queue_capacity`); a full
  queue rejects with `Overloaded` at submit time instead of growing
  unboundedly — the caller sheds load explicitly, it is never queued
  into a latency cliff.
* Coalescing: requests group by a compatibility key — identical
  non-batch dims, except sequence dims (the predictor's declared
  dynamic feeds) which group by their shape *bucket* when bucketing is
  enabled, reusing `Config.enable_shape_bucketing`'s ladder so padding
  waste stays accounted in one place. Within a group the engine pads
  each request's sequence dim up to the group bucket and concatenates
  along the batch dim; outputs are split back by row offsets.
* Deadlines: `submit(..., deadline_ms=)` — a request whose deadline
  passes while still queued is completed with `DeadlineExceeded` and
  never batched. `ServingFuture.cancel()` does the same on demand.
  Once batched, a request runs to completion (a TPU batch in flight
  cannot be recalled).
* Workers: `num_workers` Predictor clones. Clones share weights
  (scope) and compiled executables through the runtime dispatch cache
  (runtime/dispatch.py shared compiled-block cache), so N workers cost
  N python threads, not N XLA compiles.
* Drain: `close(drain=True)` stops admission, lets the batcher flush
  everything already queued (without waiting out batch timeouts), and
  joins the workers. `close(drain=False)` fails queued requests with
  `EngineClosed`.

Defaults come from the live flags `serving_max_batch_size`,
`serving_batch_timeout_ms`, `serving_queue_capacity`,
`serving_num_workers` (flags.py), overridable per engine.
"""

from __future__ import annotations

import collections
import contextlib
import queue as _queue_mod
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .metrics import ServingMetrics


class ServingError(RuntimeError):
    """Base class for serving-layer failures."""


class Overloaded(ServingError):
    """Admission queue full: the request was rejected, not queued."""


class DeadlineExceeded(ServingError):
    """The request's deadline passed before it was batched."""


class EngineClosed(ServingError):
    """submit() after close(), or queued work failed by a hard close."""


class RequestCancelled(ServingError):
    """The caller cancelled the request before it was batched."""


class ServingFuture:
    """Completion handle for one submitted request. `result()` returns
    the per-fetch output list (predictor order) or raises the serving
    error the request was completed with."""

    __slots__ = ("_ev", "_lock", "_result", "_error", "_engine",
                 "_callbacks")

    def __init__(self, engine: "ServingEngine"):
        self._ev = threading.Event()
        self._lock = threading.Lock()
        self._result: Optional[List[np.ndarray]] = None
        self._error: Optional[BaseException] = None
        self._engine = engine
        self._callbacks: List = []

    def _complete(self, result=None, error=None) -> bool:
        """First completion wins (batcher expiry vs caller cancel vs
        worker result race); returns whether THIS call won."""
        with self._lock:
            if self._ev.is_set():
                return False
            self._result, self._error = result, error
            self._ev.set()
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — a bad callback is the caller's bug
                pass
        return True

    def add_done_callback(self, fn) -> None:
        """``fn(self)`` on completion (whichever thread completes it);
        immediately if already done. The traffic layer's completion
        accounting rides this instead of burning a waiter thread per
        request."""
        with self._lock:
            if not self._ev.is_set():
                self._callbacks.append(fn)
                return
        try:
            fn(self)
        except Exception:  # noqa: BLE001
            pass

    def cancel(self) -> bool:
        """Cancel if not yet completed/batched. True if the request
        will never run; False if it already completed (or is past the
        point of no return and its result/error will arrive)."""
        won = self._complete(error=RequestCancelled(
            "request cancelled before batching"))
        if won:
            self._engine.metrics.inc("cancelled_total")
        return won

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        if not self._ev.wait(timeout):
            raise TimeoutError(
                f"serving result not ready within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result

    def exception(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError(
                f"serving result not ready within {timeout}s")
        return self._error


class _Request:
    __slots__ = ("arrays", "n_rows", "key", "deadline", "enqueue_t",
                 "future", "ctx")

    def __init__(self, arrays, n_rows, key, deadline, future, ctx=None):
        self.arrays = arrays        # per-feed, predictor feed order
        self.n_rows = n_rows
        self.key = key              # batch-compatibility key (None: solo)
        self.deadline = deadline    # absolute time.monotonic() or None
        self.enqueue_t = time.monotonic()
        self.future = future
        self.ctx = ctx              # tracing.SpanContext of the submit span


class ServingEngine:
    """Dynamic-batching front end over a `Predictor`.

    In-process API:

        engine = ServingEngine(predictor)            # flags defaults
        fut = engine.submit({"x": arr}, deadline_ms=50)
        outs = fut.result(timeout=1.0)               # per-fetch list
        outs = engine.predict({"x": arr})            # submit+result
        engine.metrics.snapshot()                    # serving metrics
        engine.predictor_stats()                     # bucket stats, all clones
        engine.close(drain=True)

    `server.ServingServer` wraps this with the HTTP front end.
    """

    def __init__(self, predictor, max_batch_size: Optional[int] = None,
                 batch_timeout_ms: Optional[float] = None,
                 queue_capacity: Optional[int] = None,
                 num_workers: Optional[int] = None, start: bool = True):
        from ..flags import flag

        # autotune seam: a profile recorded for this model pre-tunes
        # the serving_* knobs BEFORE they are read below (explicit
        # user-set flags / ctor args still win)
        from ..runtime.dispatch import autotune_for_program

        autotune_for_program(getattr(predictor, "_program", None))

        self._predictor = predictor
        self._feed_names: List[str] = list(predictor.get_input_names())
        self._fetch_names: List[str] = list(predictor.get_output_names())
        cfg = predictor._config
        self._bucketing = bool(getattr(cfg, "_bucketing", False))
        self._seq_buckets = tuple(getattr(cfg, "_seq_buckets", ()) or ())
        self._seq_feeds = set(getattr(predictor, "_seq_feed_names", ()))

        self.max_batch_size = int(max_batch_size if max_batch_size is not None
                                  else flag("serving_max_batch_size"))
        self.batch_timeout_s = float(
            batch_timeout_ms if batch_timeout_ms is not None
            else flag("serving_batch_timeout_ms")) / 1e3
        self.queue_capacity = int(queue_capacity if queue_capacity is not None
                                  else flag("serving_queue_capacity"))
        self.num_workers = max(1, int(num_workers if num_workers is not None
                                      else flag("serving_num_workers")))
        if self.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if self.queue_capacity < 1:
            raise ValueError("queue_capacity must be >= 1")

        self.metrics = ServingMetrics()
        # unified registry: aggregated predictor bucket stats join the
        # scrape as paddle_serving_predictor_*{engine=...} gauges.
        # Share the metrics object's registry id so paddle_serving_*
        # and paddle_serving_predictor_* series for THIS engine carry
        # the same engine= label and dashboards can join on it.
        from ..observability import watch_engine

        self._obs_id = self.metrics._obs_id
        watch_engine(self)
        self._cond = threading.Condition()
        self._pending: "collections.deque[_Request]" = collections.deque()
        self._closed = False      # admission stopped
        self._stop = False        # batcher should flush and exit
        # depth num_workers: natural backpressure — when every worker
        # is busy the batcher blocks here and requests accumulate in
        # the (bounded) admission queue until Overloaded fires
        self._batch_q: "_queue_mod.Queue" = _queue_mod.Queue(
            maxsize=self.num_workers)
        # every worker is a clone — sharing scope + compiled
        # executables via the dispatch cache, so the pool still binds
        # each bucket once. The caller's predictor is left untouched
        # (its direct runs keep their own bind_tag); the clones are
        # re-tagged so executables bound by this pool report as
        # serving's in trace spans and the donation/host-sync audit
        self._worker_preds = [predictor.clone()
                              for _ in range(self.num_workers)]
        for p in self._worker_preds:
            p.bind_tag = "serving/predict"
        self._batcher: Optional[threading.Thread] = None
        self._workers: List[threading.Thread] = []
        self._started = False
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ServingEngine":
        """Idempotent: spawn the batcher + worker threads."""
        with self._cond:
            if self._started:
                return self
            if self._closed:
                raise EngineClosed("engine already closed")
            self._started = True
        self._batcher = threading.Thread(
            target=self._batcher_loop, name="pt-serving-batcher", daemon=True)
        self._batcher.start()
        for i, pred in enumerate(self._worker_preds):
            t = threading.Thread(target=self._worker_loop, args=(pred,),
                                 name=f"pt-serving-worker-{i}", daemon=True)
            t.start()
            self._workers.append(t)
        return self

    def close(self, drain: bool = True, timeout: Optional[float] = 30.0):
        """Stop admission; drain (default) or fail queued requests;
        join the batcher and workers. Safe to call twice."""
        with self._cond:
            already = self._closed and self._stop
            self._closed = True
            if not drain:
                while self._pending:
                    req = self._pending.popleft()
                    req.future._complete(error=EngineClosed(
                        "engine closed before the request was batched"))
                self.metrics.set_queue_depth(0)
            self._stop = True
            self._cond.notify_all()
        if already:
            return
        if self._started:
            # the batcher emits the worker-stop sentinels itself when
            # its flush completes, so a join timeout here just returns
            # early — in-flight work still finishes, nothing strands
            self._batcher.join(timeout)
            for t in self._workers:
                t.join(timeout)
        else:
            # never started: nothing will ever serve the queue
            with self._cond:
                while self._pending:
                    self._pending.popleft().future._complete(
                        error=EngineClosed("engine closed before start()"))
                self.metrics.set_queue_depth(0)

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc):
        self.close(drain=exc[0] is None)

    @property
    def closed(self) -> bool:
        return self._closed

    # -- submission ----------------------------------------------------------
    def submit(self, feed: Union[Dict[str, Any], Sequence[Any]],
               deadline_ms: Optional[float] = None) -> ServingFuture:
        """Admit one request (dict name->array, or sequence in feed
        order). Raises `Overloaded` when the queue is full and
        `EngineClosed` after close() — both BEFORE any work is queued."""
        from ..observability import tracing

        arrays = self._normalize_feed(feed)
        n_rows = self._request_rows(arrays)
        key = self._group_key(arrays)
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        fut = ServingFuture(self)
        # root span of the request's trace: admission happens inside
        # it, and the context rides on the request so the worker's
        # batch-execute span (another thread) can parent/flow to it.
        # Gated on the flag (unlike the _execute span, submit had NO
        # profiler call before this PR — tracing off must stay free)
        with (tracing.span("serving/submit", {"rows": n_rows})
              if tracing.enabled() else contextlib.nullcontext()) as ctx:
            req = _Request(arrays, n_rows, key, deadline, fut, ctx=ctx)
            with self._cond:
                if self._closed:
                    raise EngineClosed("ServingEngine is closed")
                if len(self._pending) >= self.queue_capacity:
                    self.metrics.inc("rejected_total")
                    raise Overloaded(
                        f"serving queue full ({self.queue_capacity} pending);"
                        " retry with backoff or raise serving_queue_capacity")
                self._pending.append(req)
                self.metrics.inc("requests_total")
                self.metrics.set_queue_depth(len(self._pending))
                self._cond.notify_all()
        return fut

    def predict(self, feed, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None) -> List[np.ndarray]:
        """Synchronous submit + result."""
        return self.submit(feed, deadline_ms=deadline_ms).result(timeout)

    # -- introspection -------------------------------------------------------
    def predictor_stats(self) -> Dict[str, Any]:
        """`Predictor.bucket_stats()` aggregated across every worker
        clone: summed runs, exact padding waste from the raw element
        counters, distinct compiled buckets from the union of the
        per-bucket hit histograms — the device-side companion to
        `metrics.snapshot()`'s queue-side view. `request_shapes` is a
        lower bound (per-clone signatures are counted, not exposed, so
        overlaps across clones can't be deduplicated)."""
        runs = real = padded = 0
        hits: Dict[str, int] = {}
        request_shapes = 0
        for p in self._worker_preds:
            st = p.bucket_stats()
            runs += st["runs"]
            real += st["real_elements"]
            padded += st["padded_elements"]
            request_shapes = max(request_shapes, st["request_shapes"])
            for k, v in st.get("bucket_hits", {}).items():
                hits[k] = hits.get(k, 0) + v
        out = {
            "runs": runs,
            "padding_waste": (round(1.0 - real / padded, 4)
                              if padded else 0.0),
            "request_shapes": request_shapes,
            "compiled_shapes": len(hits),
            "bucket_hits": hits,
        }
        # distlint findings from the partitioned load (predictor.py runs
        # the dist passes warn-mode when a mesh is resolved) — clones
        # share the source predictor's report, so read it once
        lint = getattr(self._worker_preds[0], "lint_report", None)

        if lint is not None:
            out["distlint"] = {"errors": len(lint.errors),
                               "warnings": len(lint.warnings),
                               "codes": sorted({d.code for d in
                                                lint.errors + lint.warnings})}
        return out

    def predictor_stats_numeric(self) -> Dict[str, Any]:
        """The registry collector's view: predictor_stats() with the
        per-bucket histogram reduced to its size (labels are the
        registry's job, nested dicts are not)."""
        st = self.predictor_stats()
        st.pop("bucket_hits", None)
        return st

    def stats(self) -> Dict[str, Any]:
        """Serving metrics + aggregated predictor bucket stats in one
        JSON-serializable dict (what /metrics renders)."""
        return {"serving": self.metrics.snapshot(),
                "predictor": self.predictor_stats()}

    # -- request shaping -----------------------------------------------------
    def _normalize_feed(self, feed) -> List[np.ndarray]:
        if isinstance(feed, dict):
            missing = [n for n in self._feed_names if n not in feed]
            extra = [n for n in feed if n not in self._feed_names]
            if missing or extra:
                raise ValueError(
                    f"feed names mismatch: missing {missing}, "
                    f"unexpected {extra}; expected {self._feed_names}")
            ordered = [feed[n] for n in self._feed_names]
        else:
            ordered = list(feed)
            if len(ordered) != len(self._feed_names):
                raise ValueError(
                    f"expected {len(self._feed_names)} feeds "
                    f"({self._feed_names}), got {len(ordered)}")
        return [np.asarray(a) for a in ordered]

    def _request_rows(self, arrays: List[np.ndarray]) -> int:
        rows = {int(a.shape[0]) for a in arrays if a.ndim >= 1}
        if len(rows) > 1:
            raise ValueError(
                f"inconsistent batch dims across feeds: {sorted(rows)}")
        return rows.pop() if rows else 1

    def _group_key(self, arrays: List[np.ndarray]):
        """Two requests batch together iff their keys are equal: same
        dtypes, same non-batch dims — except sequence dims, which
        compare by shape bucket when bucketing is on (the predictor
        pads them up anyway, so requests of length 7 and 21 share a
        32-bucket batch). Scalar feeds can't concatenate: key None
        means the request is always served alone."""
        key = []
        for name, a in zip(self._feed_names, arrays):
            if a.ndim == 0:
                return None
            dims = list(a.shape[1:])
            if (self._bucketing and name in self._seq_feeds
                    and a.ndim >= 2 and self._seq_buckets):
                dims[0] = self._predictor._bucket_of(
                    int(a.shape[1]), self._seq_buckets)
            key.append((name, a.dtype.str, tuple(dims)))
        return tuple(key)

    # -- batcher -------------------------------------------------------------
    def _pop_next_live_locked(self) -> Optional[_Request]:
        """Pop the oldest request that is still worth serving;
        complete+drop expired/cancelled ones on the way."""
        now = time.monotonic()
        while self._pending:
            req = self._pending.popleft()
            if req.future.done():            # cancelled by the caller
                continue
            if req.deadline is not None and now > req.deadline:
                if req.future._complete(error=DeadlineExceeded(
                        f"deadline passed after "
                        f"{(now - req.enqueue_t) * 1e3:.1f}ms in queue")):
                    self.metrics.inc("expired_total")
                continue
            return req
        return None

    def _pop_compatible_locked(self, key, max_rows: int) -> Optional[_Request]:
        """Pop the oldest queued request that fits the open batch
        (same key, <= max_rows rows); expired/cancelled requests are
        completed and dropped regardless of compatibility."""
        now = time.monotonic()
        i = 0
        while i < len(self._pending):
            req = self._pending[i]
            if req.future.done():
                del self._pending[i]
                continue
            if req.deadline is not None and now > req.deadline:
                del self._pending[i]
                if req.future._complete(error=DeadlineExceeded(
                        f"deadline passed after "
                        f"{(now - req.enqueue_t) * 1e3:.1f}ms in queue")):
                    self.metrics.inc("expired_total")
                continue
            if key is not None and req.key == key and req.n_rows <= max_rows:
                del self._pending[i]
                return req
            i += 1
        return None

    def _collect_batch(self) -> Optional[List[_Request]]:
        """Block until a batch is ready (first request + compatible
        followers up to max_batch_size rows or batch_timeout, whichever
        first; no timeout wait while draining). None = shut down."""
        with self._cond:
            while True:
                first = self._pop_next_live_locked()
                if first is not None:
                    break
                if self._stop:
                    self.metrics.set_queue_depth(len(self._pending))
                    return None
                self._cond.wait(0.1)
            batch = [first]
            rows = first.n_rows
            t_close = time.monotonic() + self.batch_timeout_s
            while rows < self.max_batch_size and first.key is not None:
                nxt = self._pop_compatible_locked(
                    first.key, self.max_batch_size - rows)
                if nxt is not None:
                    batch.append(nxt)
                    rows += nxt.n_rows
                    continue
                if self._stop:
                    break            # draining: never wait for traffic
                remaining = t_close - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, 0.05))
            self.metrics.set_queue_depth(len(self._pending))
        return batch

    def _batcher_loop(self):
        try:
            while True:
                batch = self._collect_batch()
                if batch is None:
                    return
                rows = sum(r.n_rows for r in batch)
                self.metrics.observe_batch(len(batch), rows,
                                           self.max_batch_size)
                now = time.monotonic()
                for r in batch:
                    self.metrics.observe_queue_wait(
                        (now - r.enqueue_t) * 1e3)
                self._batch_q.put(batch)
        finally:
            # the batcher owns the end of the stream: worker-stop
            # sentinels go in HERE, strictly after the last batch —
            # close() putting them could race ahead of batches still
            # being flushed (FIFO would hand workers the sentinel
            # first and strand those requests' futures forever)
            for _ in range(self.num_workers):
                self._batch_q.put(None)

    # -- workers -------------------------------------------------------------
    def _worker_loop(self, pred):
        while True:
            batch = self._batch_q.get()
            if batch is None:
                return
            self._execute(pred, batch)

    def _assemble(self, batch: List[_Request]):
        """Concatenate member requests along the batch dim, padding
        sequence dims up to the group bucket first (group key fixes the
        target, so members always align). Engine-level padding elements
        feed the metrics' padding-waste gauge; the predictor's own
        bucket padding is accounted by bucket_stats. Returns
        (feeds, padded_any) — padded_any flags that member outputs may
        come back at the padded seq length and need true-shape
        slicing."""
        feeds = []
        real = total = 0
        padded_any = False
        for fi, name in enumerate(self._feed_names):
            parts = []
            target = None
            if len(batch) > 1 and batch[0].key is not None:
                target = batch[0].key[fi][2]  # non-batch dims, bucketed
            for req in batch:
                a = req.arrays[fi]
                if target is not None and a.ndim >= 2 \
                        and tuple(a.shape[1:]) != target:
                    pads = [(0, 0)] + [
                        (0, t - s) for t, s in zip(target, a.shape[1:])]
                    a = np.pad(a, pads)
                    padded_any = True
                real += int(req.arrays[fi].size)
                total += int(a.size)
                parts.append(a)
            feeds.append(np.concatenate(parts, axis=0)
                         if len(parts) > 1 else parts[0])
        if total:
            self.metrics.record_padding(real, total)
        return feeds, padded_any

    def _true_shapes_for(self, pred, req: _Request):
        """Per-fetch output shapes for the request at its TRUE feed
        shapes (the predictor's own eval_shape machinery, cached per
        signature). Needed when the engine seq-padded the request into
        a co-batch: the predictor then only sees the padded feed, so
        per-token outputs come back at the bucket length — a request
        must get the same output shape whether it was served solo or
        coalesced."""
        feed = dict(zip(self._feed_names, req.arrays))
        with pred._lock:
            return pred._true_fetch_shapes(feed)

    def _execute(self, pred, batch: List[_Request]):
        from ..observability import tracing

        t_exec = time.monotonic()
        try:
            feeds, padded_any = self._assemble(batch)
            # the batch-execute span parents to the first member's
            # submit span and carries flow_from for every OTHER member
            # — tools_timeline renders the cross-thread handoff arrows
            # submit(caller thread) -> execute(worker thread); nested
            # spans below (predictor run -> executor/step) parent to
            # this one via the worker thread's ambient context
            first_ctx = batch[0].ctx
            flow = [r.ctx.span_id for r in batch[1:] if r.ctx is not None]
            with tracing.span(
                    f"serving/batch_execute[n={len(batch)}]",
                    {"rows": sum(r.n_rows for r in batch),
                     **({"flow_from": flow} if flow else {})},
                    parent=first_ctx):
                outs = pred.run(feeds)
            true_shapes = ([self._true_shapes_for(pred, r) for r in batch]
                           if padded_any else None)
            done = self._split_and_complete(batch, outs, true_shapes)
            now = time.monotonic()
            for req in batch:
                self.metrics.observe_latency((now - req.enqueue_t) * 1e3)
            self.metrics.inc("responses_total", done)
        except Exception as e:  # noqa: BLE001 — a bad batch must not kill the worker
            n = 0
            for req in batch:
                if req.future._complete(error=ServingError(
                        f"predictor execution failed: {e!r}")):
                    n += 1
            self.metrics.inc("errors_total", n)

    def _split_and_complete(self, batch: List[_Request],
                            outs: Sequence[np.ndarray],
                            true_shapes=None) -> int:
        """Row-split the batched outputs back per request (and, when
        the engine seq-padded the batch, slice each member's outputs
        down to its true shapes); returns how many futures this call
        actually completed (a concurrent cancel() can win the race and
        keep its error)."""
        total_rows = sum(r.n_rows for r in batch)
        offset = 0
        won = 0
        for i, req in enumerate(batch):
            sliced = []
            for j, o in enumerate(outs):
                o = np.asarray(o)
                if o.ndim >= 1 and o.shape[0] == total_rows:
                    o = o[offset:offset + req.n_rows]
                    if true_shapes is not None:
                        # e.g. a per-token [rows, seq, H] output padded
                        # to the bucket seq: back to the true length
                        ts = tuple(true_shapes[i][j])
                        if o.shape != ts:
                            o = o[tuple(slice(0, s) for s in ts)]
                # else: batch-invariant output (a scalar metric, a
                # table) — every member gets the whole thing
                sliced.append(o)
            offset += req.n_rows
            if req.future._complete(result=sliced):
                won += 1
        return won

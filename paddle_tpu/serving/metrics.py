"""Serving metrics: lock-protected registry + streaming histogram.

Reference: the reference's inference-server story shipped QPS/latency
accounting next to the predictor (paddle/fluid/inference/). Here the
registry is deliberately stdlib-only and O(1) per observation: the
serving hot path (admission, batching, completion) touches it under
one lock, and readers get a consistent point-in-time snapshot — the
same contract Scope/Executor counters follow elsewhere in the repo.

Latency quantiles use a fixed log-spaced streaming histogram (the
Prometheus classic-histogram shape): constant memory, no per-request
sample retention, p50/p95/p99 read by bucket interpolation. At the
default 8%-wide buckets the quantile error is bounded by the bucket
width — plenty for capacity planning, and it never degrades under
millions of requests.
"""

from __future__ import annotations

import bisect
import threading
from typing import Any, Dict, Optional


class StreamingHistogram:
    """Fixed log-spaced buckets over (0, hi]; O(1) record, O(buckets)
    quantile. Values below `lo` land in the first bucket, above `hi`
    in the overflow bucket (reported as >= hi)."""

    def __init__(self, lo: float = 0.05, hi: float = 300_000.0,
                 factor: float = 1.08):
        bounds = []
        b = float(lo)
        while b < hi:
            bounds.append(b)
            b *= factor
        self._bounds = bounds          # upper edges, ascending
        self._counts = [0] * (len(bounds) + 1)  # +1 overflow
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max = 0.0

    def record(self, v: float) -> None:
        v = float(v)
        self._counts[bisect.bisect_left(self._bounds, v)] += 1
        self.count += 1
        self.sum += v
        self.max = v if v > self.max else self.max
        self.min = v if self.min is None or v < self.min else self.min

    def quantile(self, q: float) -> float:
        """Approximate q-quantile: the geometric midpoint of the bucket
        holding the q*count-th observation (0.0 when empty)."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        acc = 0
        for i, c in enumerate(self._counts):
            acc += c
            if acc >= rank and c:
                if i >= len(self._bounds):          # overflow bucket
                    return self._bounds[-1] if self._bounds else 0.0
                lo = self._bounds[i - 1] if i else self._bounds[i] / 2
                return (lo * self._bounds[i]) ** 0.5
        return self.max

    def snapshot(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "sum": round(self.sum, 3),
            "mean": round(self.sum / self.count, 3) if self.count else 0.0,
            "min": round(self.min, 3) if self.min is not None else 0.0,
            "max": round(self.max, 3),
            "p50": round(self.quantile(0.50), 3),
            "p95": round(self.quantile(0.95), 3),
            "p99": round(self.quantile(0.99), 3),
        }


_COUNTERS = (
    "requests_total",          # admitted into the queue
    "responses_total",         # completed with a result
    "rejected_total",          # refused at admission (queue full)
    "expired_total",           # deadline passed before batching
    "cancelled_total",         # future.cancel() before batching
    "errors_total",            # predictor raised during execution
    "batches_total",           # predictor calls dispatched
    "batched_requests_total",  # requests across all dispatched batches
)


class ServingMetrics:
    """The engine-wide registry. Every mutator and `snapshot()` take
    the one internal lock, so concurrent serving workers can neither
    corrupt counters nor observe a torn read."""

    def __init__(self):
        self._lock = threading.Lock()
        # unified telemetry: every live ServingMetrics is a labeled
        # series group (paddle_serving_*{engine="N"}) in the one
        # process-wide registry — /metrics on ANY server shows every
        # engine. Weakly held: a closed engine drops out of the scrape.
        from ..observability import watch_serving

        watch_serving(self)
        self._c: Dict[str, int] = {k: 0 for k in _COUNTERS}
        self._latency_ms = StreamingHistogram()
        self._queue_wait_ms = StreamingHistogram()
        self._queue_depth = 0
        self._occupancy_max = 0          # requests in the fullest batch
        self._rows_sum = 0               # samples actually batched
        self._rows_capacity_sum = 0      # max_batch_size per batch
        self._pad_real = 0               # engine-level seq-padding waste
        self._pad_total = 0

    # -- mutators (hot path) ------------------------------------------------
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._c[name] += n

    def observe_latency(self, ms: float) -> None:
        with self._lock:
            self._latency_ms.record(ms)

    def observe_queue_wait(self, ms: float) -> None:
        with self._lock:
            self._queue_wait_ms.record(ms)

    def observe_batch(self, n_requests: int, n_rows: int,
                      capacity: int) -> None:
        with self._lock:
            self._c["batches_total"] += 1
            self._c["batched_requests_total"] += n_requests
            if n_requests > self._occupancy_max:
                self._occupancy_max = n_requests
            self._rows_sum += n_rows
            self._rows_capacity_sum += capacity

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = depth

    def record_padding(self, real_elements: int, total_elements: int) -> None:
        with self._lock:
            self._pad_real += int(real_elements)
            self._pad_total += int(total_elements)

    # -- readers -------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """One consistent, JSON-serializable point-in-time view."""
        with self._lock:
            batches = self._c["batches_total"]
            out: Dict[str, Any] = dict(self._c)
            out["queue_depth"] = self._queue_depth
            out["latency_ms"] = self._latency_ms.snapshot()
            out["queue_wait_ms"] = self._queue_wait_ms.snapshot()
            out["batch_occupancy"] = {
                "mean": (round(self._c["batched_requests_total"] / batches, 3)
                         if batches else 0.0),
                "max": self._occupancy_max,
            }
            out["batch_fill"] = (
                round(self._rows_sum / self._rows_capacity_sum, 4)
                if self._rows_capacity_sum else 0.0)
            out["padding_waste"] = (
                round(1.0 - self._pad_real / self._pad_total, 4)
                if self._pad_total else 0.0)
            return out

    def to_prometheus_text(self,
                           extra: Optional[Dict[str, Any]] = None) -> str:
        """Prometheus exposition format (counters, gauges, quantile
        summaries). `extra` adds flat name->number gauges (the server
        passes the aggregated predictor bucket stats)."""
        snap = self.snapshot()
        lines = []

        def emit(name, kind, value, labels=""):
            lines.append(f"# TYPE paddle_serving_{name} {kind}")
            lines.append(f"paddle_serving_{name}{labels} {value}")

        for k in _COUNTERS:
            emit(k, "counter", snap[k])
        emit("queue_depth", "gauge", snap["queue_depth"])
        emit("batch_occupancy_mean", "gauge", snap["batch_occupancy"]["mean"])
        emit("batch_occupancy_max", "gauge", snap["batch_occupancy"]["max"])
        emit("batch_fill", "gauge", snap["batch_fill"])
        emit("padding_waste", "gauge", snap["padding_waste"])
        for hist_name in ("latency_ms", "queue_wait_ms"):
            h = snap[hist_name]
            lines.append(f"# TYPE paddle_serving_{hist_name} summary")
            for q, k in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
                lines.append(
                    f'paddle_serving_{hist_name}{{quantile="{q}"}} {h[k]}')
            lines.append(f"paddle_serving_{hist_name}_sum {h['sum']}")
            lines.append(f"paddle_serving_{hist_name}_count {h['count']}")
        for k, v in (extra or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                emit(k, "gauge", v)
        return "\n".join(lines) + "\n"

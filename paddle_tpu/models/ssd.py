"""SSD single-shot detector: multi-box heads, matching loss, NMS
inference — the reference's detection model family assembled from the
detection op set.

Reference analogue: python/paddle/fluid/layers/detection.py
(multi_box_head, ssd_loss, detection_output) over
operators/detection/* — used by the SSD/MobileNet-SSD models.

TPU-native: matching/mining run as dense static-shape ops inside the
compiled step (iou_similarity -> per-prior argmax match -> hard
negative mining via top-k), no host round-trips.
"""

from __future__ import annotations

import numpy as np


def multi_box_head(feats, image, num_classes, min_sizes, max_sizes=None,
                   aspect_ratios=None):
    """Conv loc/conf heads + priors per feature map.

    feats: list of [B, C, H, W] Variables; image: the input image var
    (prior_box reads its spatial extent). Returns (loc [B, P, 4],
    conf [B, P, num_classes], priors [P, 4], prior_vars [P, 4]).
    """
    import paddle_tpu as fluid
    from paddle_tpu import layers

    aspect_ratios = aspect_ratios or [[2.0]] * len(feats)
    locs, confs, priors, pvars = [], [], [], []
    for i, feat in enumerate(feats):
        # priors/cell: min + (geometric-mean max) + one per non-1
        # aspect ratio incl. flipped (mirrors the prior_box lowering)
        full_ars = []
        for a in aspect_ratios[i]:
            full_ars.append(a)
            if a != 1.0:
                full_ars.append(1.0 / a)
        n_priors = 1 + (1 if max_sizes else 0) + sum(
            1 for a in full_ars if a != 1.0)
        loc = layers.conv2d(feat, n_priors * 4, 3, padding=1)
        conf = layers.conv2d(feat, n_priors * num_classes, 3, padding=1)
        # [B, A*4, H, W] -> [B, H*W*A, 4]
        loc = layers.transpose(loc, [0, 2, 3, 1])
        loc = layers.reshape(loc, [0, -1, 4])
        conf = layers.transpose(conf, [0, 2, 3, 1])
        conf = layers.reshape(conf, [0, -1, num_classes])
        box, var = layers.prior_box(
            feat, image,
            min_sizes=[min_sizes[i]],
            max_sizes=[max_sizes[i]] if max_sizes else None,
            aspect_ratios=aspect_ratios[i],
            flip=True, clip=True,
        )
        box = layers.reshape(box, [-1, 4])
        var = layers.reshape(var, [-1, 4])
        locs.append(loc)
        confs.append(conf)
        priors.append(box)
        pvars.append(var)
    loc = layers.concat(locs, axis=1)
    conf = layers.concat(confs, axis=1)
    prior = layers.concat(priors, axis=0)
    pvar = layers.concat(pvars, axis=0)
    return loc, conf, prior, pvar


def _register_ssd_loss_op():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.registry import register_op, has_op

    if not has_op("ssd_loss_dense"):
        @register_op("ssd_loss_dense",
                     inputs=("Loc", "Conf", "GtBox", "GtLabel", "Prior",
                             "PVar"),
                     outputs=("Loss",),
                     no_grad=("GtBox", "GtLabel", "Prior", "PVar"))
        def _ssd_loss_dense(ctx, op, ins):
            loc_p = ins["Loc"][0]       # [B, P, 4]
            conf_p = ins["Conf"][0]     # [B, P, C]
            gtb = ins["GtBox"][0]       # [B, G, 4]
            gtl = ins["GtLabel"][0]     # [B, G]
            prior_ = ins["Prior"][0]    # [P, 4]
            pvar_ = ins["PVar"][0]      # [P, 4]
            thr = float(op.attrs.get("overlap_threshold", 0.5))
            ratio = float(op.attrs.get("neg_pos_ratio", 3.0))
            lw = float(op.attrs.get("loc_weight", 1.0))
            cw = float(op.attrs.get("conf_weight", 1.0))
            B, P, C = conf_p.shape

            from paddle_tpu.ops.detection import _pairwise_iou

            def encode(gt, pr, pv):
                pw = jnp.maximum(pr[:, 2] - pr[:, 0], 1e-6)
                ph = jnp.maximum(pr[:, 3] - pr[:, 1], 1e-6)
                pcx = pr[:, 0] + pw * 0.5
                pcy = pr[:, 1] + ph * 0.5
                gw = jnp.maximum(gt[:, 2] - gt[:, 0], 1e-6)
                gh = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-6)
                gcx = gt[:, 0] + gw * 0.5
                gcy = gt[:, 1] + gh * 0.5
                t = jnp.stack([(gcx - pcx) / pw, (gcy - pcy) / ph,
                               jnp.log(gw / pw), jnp.log(gh / ph)], 1)
                return t / pv

            def one(loc_b, conf_b, gtb_b, gtl_b):
                valid_g = gtl_b > 0
                ious = _pairwise_iou(prior_, gtb_b)  # [P, G]
                ious = jnp.where(valid_g[None, :], ious, -1.0)
                best_gt = jnp.argmax(ious, 1)
                best_iou = jnp.max(ious, 1)
                pos = best_iou >= thr                      # [P]
                tgt_label = jnp.where(pos, gtl_b[best_gt], 0)
                tgt_loc = encode(gtb_b[best_gt], prior_, pvar_)

                logp = jax.nn.log_softmax(conf_b, -1)
                conf_loss = -jnp.take_along_axis(
                    logp, tgt_label[:, None].astype(jnp.int32), 1)[:, 0]
                n_pos = jnp.sum(pos)
                n_neg = jnp.minimum(
                    (ratio * n_pos).astype(jnp.int32), P - 1)
                neg_score = jnp.where(pos, -jnp.inf, conf_loss)
                order = jnp.argsort(-neg_score)
                rank = jnp.argsort(order)
                hard_neg = (~pos) & (rank < n_neg)

                diff = loc_b - tgt_loc
                absd = jnp.abs(diff)
                smooth = jnp.where(absd < 1.0, 0.5 * diff * diff,
                                   absd - 0.5)
                loc_loss = jnp.sum(
                    smooth.sum(-1) * pos.astype(smooth.dtype))
                conf_total = jnp.sum(
                    conf_loss * (pos | hard_neg).astype(conf_loss.dtype))
                denom = jnp.maximum(n_pos.astype(jnp.float32), 1.0)
                return (lw * loc_loss + cw * conf_total) / denom

            losses = jax.vmap(one)(loc_p, conf_p, gtb, gtl)
            return {"Loss": [jnp.mean(losses).reshape(1)]}


_register_ssd_loss_op()


def ssd_loss(loc, conf, gt_box, gt_label, prior, pvar,
             overlap_threshold=0.5, neg_pos_ratio=3.0, loc_weight=1.0,
             conf_weight=1.0):
    """Matching + mined SSD loss (reference layers/detection.py
    ssd_loss). gt_box [B, G, 4] (corner form, zero rows = padding),
    gt_label [B, G] int (0 = background/pad). Dense per-prior matching:
    a prior is positive iff its best gt IoU >= overlap_threshold; hard
    negative mining keeps the top (neg_pos_ratio * #pos) background
    priors by confidence loss (the ssd_loss_dense op above)."""
    import paddle_tpu as fluid

    helper = fluid.layer_helper.LayerHelper("ssd_loss")
    out = helper.create_variable_for_type_inference(shape=(1,))
    helper.append_op(
        type="ssd_loss_dense",
        inputs={"Loc": [loc], "Conf": [conf], "GtBox": [gt_box],
                "GtLabel": [gt_label], "Prior": [prior], "PVar": [pvar]},
        outputs={"Loss": [out]},
        attrs={"overlap_threshold": overlap_threshold,
               "neg_pos_ratio": neg_pos_ratio, "loc_weight": loc_weight,
               "conf_weight": conf_weight},
    )
    return out


def detection_output(loc, conf, prior, pvar, nms_threshold=0.45,
                     score_threshold=0.01, keep_top_k=20,
                     background_label=0):
    """Decode + NMS (reference layers/detection.py detection_output):
    box_coder decode_center_size then multiclass_nms."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    decoded = layers.box_coder(
        prior_box=prior, prior_box_var=pvar, target_box=loc,
        code_type="decode_center_size", box_normalized=True, axis=0)
    helper = fluid.layer_helper.LayerHelper("detection_output")
    scores = layers.softmax(conf)
    scores = layers.transpose(scores, [0, 2, 1])  # [B, C, P]
    out = helper.create_variable_for_type_inference()
    nums = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="multiclass_nms",
        inputs={"BBoxes": [decoded], "Scores": [scores]},
        outputs={"Out": [out], "NmsRoisNum": [nums]},
        attrs={"nms_threshold": nms_threshold,
               "score_threshold": score_threshold,
               "keep_top_k": keep_top_k,
               "background_label": background_label},
    )
    return out, nums


def build_ssd(image_size=32, num_classes=4, optimizer=None, max_gt=4):
    """Tiny SSD over a 2-scale conv backbone. Returns
    (main, startup, feeds, fetches)."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        img = layers.data("image", [3, image_size, image_size])
        gt_box = layers.data("gt_box", [max_gt, 4])
        gt_label = layers.data("gt_label", [max_gt], dtype="int64")

        c1 = layers.conv2d(img, 8, 3, stride=2, padding=1, act="relu")
        c2 = layers.conv2d(c1, 16, 3, stride=2, padding=1, act="relu")
        c3 = layers.conv2d(c2, 16, 3, stride=2, padding=1, act="relu")

        loc, conf, prior, pvar = multi_box_head(
            [c2, c3], img, num_classes,
            min_sizes=[image_size * 0.2, image_size * 0.4],
            max_sizes=[image_size * 0.5, image_size * 0.8],
        )
        loss = ssd_loss(loc, conf, gt_box, gt_label, prior, pvar)
        loss = layers.reduce_sum(loss)
        if optimizer is not None:
            optimizer.minimize(loss)
        nmsed, nums = detection_output(loc, conf, prior, pvar)
    return main, startup, {"image": "image", "gt_box": "gt_box",
                           "gt_label": "gt_label"}, {
        "loss": loss, "detections": nmsed, "det_nums": nums}


def synthetic_det_batch(rng: np.random.RandomState, batch, image_size=32,
                        num_classes=4, max_gt=4):
    img = rng.rand(batch, 3, image_size, image_size).astype("float32")
    boxes = np.zeros((batch, max_gt, 4), "float32")
    labels = np.zeros((batch, max_gt), "int64")
    for b in range(batch):
        n = rng.randint(1, max_gt + 1)
        for g in range(n):
            cx, cy = rng.rand(2) * 0.6 + 0.2
            w, h = rng.rand(2) * 0.3 + 0.15
            boxes[b, g] = [max(cx - w / 2, 0), max(cy - h / 2, 0),
                           min(cx + w / 2, 1), min(cy + h / 2, 1)]
            labels[b, g] = rng.randint(1, num_classes)
    return {"image": img, "gt_box": boxes, "gt_label": labels}

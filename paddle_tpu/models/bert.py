"""BERT/ERNIE-style transformer encoder pretraining graph (flagship
model — BASELINE configs 3/4).

Reference: the fused-attention capability surface
(operators/fused/multihead_matmul_op.cu is inference-only in the
reference; training-side attention there is composed op-by-op, which is
what this builder emits). On TPU the whole encoder compiles to one XLA
program; paddle_tpu.kernels provides Pallas flash-attention used when
config.use_flash_attention (bypassing the materialized [B,H,S,S]
attention matrix).

Megatron-style tensor parallelism (beyond the reference, SURVEY §2f
P-row "TP absent") comes from param sharding annotations consumed by
the executor's GSPMD path: column-parallel QKV/FFN-in, row-parallel
proj/FFN-out.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import numpy as np

from .. import layers, nets, optimizer as optim
from ..core.framework import Program, program_guard
from ..initializer import NormalInitializer, ConstantInitializer
from ..param_attr import ParamAttr


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    initializer_range: float = 0.02
    use_flash_attention: bool = False

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def large():
        return BertConfig(hidden_size=1024, num_layers=24, num_heads=16, ffn_size=4096)

    @staticmethod
    def tiny():
        return BertConfig(
            vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
            ffn_size=128, max_position=128,
        )


def _attr(name, std):
    return ParamAttr(name=name, initializer=NormalInitializer(0.0, std))


def _encoder_layer(x, cfg: BertConfig, idx: int, is_test=False,
                   input_mask=None):
    h = cfg.hidden_size
    std = cfg.initializer_range
    pre = f"enc{idx}"
    # self-attention: fused QKV projection (column-parallel under mp)
    qkv = layers.fc(
        x, 3 * h, num_flatten_dims=2,
        param_attr=_attr(f"{pre}_qkv.w", std), bias_attr=ParamAttr(name=f"{pre}_qkv.b"),
    )
    q, k, v = layers.split(qkv, 3, dim=2)
    if cfg.use_flash_attention:
        from ..kernels import flash_attention_layer

        ctx = flash_attention_layer(q, k, v, cfg.num_heads,
                                    mask_var=input_mask)
    else:
        ctx = nets.scaled_dot_product_attention(
            q, k, v, num_heads=cfg.num_heads,
            dropout_rate=0.0 if is_test else cfg.attention_dropout,
            padding_mask=input_mask,
        )
    proj = layers.fc(
        ctx, h, num_flatten_dims=2,
        param_attr=_attr(f"{pre}_proj.w", std), bias_attr=ParamAttr(name=f"{pre}_proj.b"),
    )
    if not is_test and cfg.hidden_dropout:
        proj = layers.dropout(proj, cfg.hidden_dropout,
                              dropout_implementation="upscale_in_train")
    x = layers.layer_norm(
        layers.elementwise_add(x, proj), begin_norm_axis=2,
        param_attr=ParamAttr(name=f"{pre}_ln1.scale"),
        bias_attr=ParamAttr(name=f"{pre}_ln1.bias"),
    )
    # FFN (column- then row-parallel under mp)
    ffn1 = layers.fc(
        x, cfg.ffn_size, num_flatten_dims=2, act="gelu",
        param_attr=_attr(f"{pre}_ffn1.w", std), bias_attr=ParamAttr(name=f"{pre}_ffn1.b"),
    )
    ffn2 = layers.fc(
        ffn1, h, num_flatten_dims=2,
        param_attr=_attr(f"{pre}_ffn2.w", std), bias_attr=ParamAttr(name=f"{pre}_ffn2.b"),
    )
    if not is_test and cfg.hidden_dropout:
        ffn2 = layers.dropout(ffn2, cfg.hidden_dropout,
                              dropout_implementation="upscale_in_train")
    x = layers.layer_norm(
        layers.elementwise_add(x, ffn2), begin_norm_axis=2,
        param_attr=ParamAttr(name=f"{pre}_ln2.scale"),
        bias_attr=ParamAttr(name=f"{pre}_ln2.bias"),
    )
    return x


def build_bert_pretrain(
    cfg: BertConfig,
    seq_len: int,
    optimizer: Optional[object] = None,
    is_test: bool = False,
    dtype: str = "float32",
):
    """Returns (main_program, startup_program, feeds dict, fetch dict).

    Feeds: src_ids [B,S] int64, pos_ids [B,S] int64, labels [B,S] int64,
    input_mask [B,S] float32 (1 = real token, 0 = padding — the
    reference's BiasQK padding-mask capability,
    fused/multihead_matmul_op.cu:441, expressed as the cheap [B,S]
    key-mask form).
    Loss: full-softmax LM cross-entropy, masked mean over real tokens.
    """
    main, startup = Program(), Program()
    std = cfg.initializer_range
    with program_guard(main, startup):
        src = layers.data("src_ids", [seq_len], dtype="int64")
        pos = layers.data("pos_ids", [seq_len], dtype="int64")
        labels = layers.data("labels", [seq_len], dtype="int64")
        input_mask = layers.data("input_mask", [seq_len], dtype="float32")
        word_emb = layers.embedding(
            src, [cfg.vocab_size, cfg.hidden_size],
            param_attr=_attr("word_embedding", std),
        )
        pos_emb = layers.embedding(
            pos, [cfg.max_position, cfg.hidden_size],
            param_attr=_attr("pos_embedding", std),
        )
        x = layers.elementwise_add(word_emb, pos_emb)
        x = layers.layer_norm(
            x, begin_norm_axis=2,
            param_attr=ParamAttr(name="emb_ln.scale"),
            bias_attr=ParamAttr(name="emb_ln.bias"),
        )
        if not is_test and cfg.hidden_dropout:
            x = layers.dropout(x, cfg.hidden_dropout,
                               dropout_implementation="upscale_in_train")
        # per-layer outputs double as PipelineOptimizer cut points
        # (reference PipelineOptimizer cuts its program at user-chosen
        # vars, optimizer.py:3414); every boundary is the same
        # [B, S, H] activation, which the SPMD pipeline requires
        encoder_outputs = []
        for i in range(cfg.num_layers):
            x = _encoder_layer(x, cfg, i, is_test, input_mask=input_mask)
            encoder_outputs.append(x)
        logits = layers.fc(
            x, cfg.vocab_size, num_flatten_dims=2,
            param_attr=_attr("lm_head.w", std), bias_attr=ParamAttr(name="lm_head.b"),
        )
        lbl = layers.unsqueeze(labels, [2])
        ce = layers.softmax_with_cross_entropy(logits, lbl)  # [B, S, 1]
        ce = layers.elementwise_mul(layers.squeeze(ce, [2]), input_mask)
        # masked mean over real tokens only
        loss = layers.elementwise_div(
            layers.reduce_sum(ce), layers.reduce_sum(input_mask))
        if optimizer is not None and not is_test:
            optimizer.minimize(loss)
    return main, startup, {"src_ids": src, "pos_ids": pos,
                           "labels": labels, "input_mask": input_mask}, {
        "loss": loss, "logits": logits,
        "encoder_outputs": encoder_outputs,
    }


def apply_megatron_sharding(program: Program, mp_axis: str = "mp", dp_axis: str = "dp"):
    """Annotate params with PartitionSpecs: column-parallel QKV/FFN-in
    (shard output dim), row-parallel proj/FFN-out (shard input dim),
    vocab-parallel embedding + LM head. GSPMD inserts the collectives
    megatron does by hand."""
    gb = program.global_block()
    for name, var in gb.vars.items():
        if not getattr(var, "persistable", False) or var.shape is None:
            continue
        if name.endswith("_qkv.w") or name.endswith("_ffn1.w"):
            var.sharding = (None, mp_axis)
        elif name.endswith("_qkv.b") or name.endswith("_ffn1.b"):
            var.sharding = (mp_axis,)
        elif name.endswith("_proj.w") or name.endswith("_ffn2.w"):
            var.sharding = (mp_axis, None)
        elif name in ("word_embedding", "lm_head.w"):
            # vocab dim for the table, hidden->vocab for the head
            var.sharding = (mp_axis, None) if name == "word_embedding" else (None, mp_axis)
        # optimizer accumulators inherit their param's sharding
    for name, var in gb.vars.items():
        owner = getattr(var, "accumulator_owner", None)
        if owner and owner in gb.vars:
            base = gb.vars[owner]
            if base.sharding is not None and var.shape == base.shape:
                var.sharding = base.sharding
    return program


def synthetic_batch(rng: np.random.RandomState, batch: int, seq_len: int,
                    vocab: int, min_len: Optional[int] = None):
    """min_len=None: full-length rows (throughput benchmarking).
    min_len=k: per-row lengths uniform in [k, seq_len] — a realistic
    padded batch exercising the attention mask."""
    src = rng.randint(0, vocab, (batch, seq_len)).astype("int64")
    pos = np.tile(np.arange(seq_len, dtype="int64"), (batch, 1))
    labels = np.roll(src, -1, axis=1)
    if min_len is None:
        mask = np.ones((batch, seq_len), "float32")
    else:
        lengths = rng.randint(min_len, seq_len + 1, batch)
        mask = (np.arange(seq_len)[None, :] < lengths[:, None]).astype("float32")
    return {"src_ids": src, "pos_ids": pos, "labels": labels,
            "input_mask": mask}

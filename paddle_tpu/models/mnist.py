"""LeNet for MNIST (BASELINE config 1). Reference:
tests/book/test_recognize_digits.py."""

from __future__ import annotations

from .. import layers, nets
from ..core.framework import Program, program_guard


def build_lenet(optimizer=None):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("img", [1, 28, 28])
        label = layers.data("label", [1], dtype="int64")
        c1 = nets.simple_img_conv_pool(img, 6, 5, 2, 2, conv_padding=2, act="relu")
        c2 = nets.simple_img_conv_pool(c1, 16, 5, 2, 2, act="relu")
        f1 = layers.fc(c2, 120, act="relu")
        f2 = layers.fc(f1, 84, act="relu")
        logits = layers.fc(f2, 10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        if optimizer is not None:
            optimizer.minimize(loss)
    return main, startup, {"img": img, "label": label}, {"loss": loss, "acc": acc}

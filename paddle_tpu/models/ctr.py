"""CTR models: DeepFM and wide&deep over sparse id features.

Reference analogue: the fleet CTR models
(tests/unittests/test_dist_fleet_ctr.py's dist_fleet_ctr.py,
incubate/fleet demos) — the parameter-server workload family the
reference was built around: huge sparse embedding tables + a small
dense tower.

TPU-native: embeddings use ``is_sparse=True`` so gradients flow as
SelectedRows (rows touched this batch only) into the sparse optimizer
kernels and the PS sparse push path — the update cost scales with
batch ids, not vocab (core/selected_rows.py).
"""

from __future__ import annotations

import numpy as np


def build_deepfm(num_fields=8, vocab_size=1000, embed_dim=8,
                 dense_dim=4, hidden=(32, 16), optimizer=None,
                 is_sparse=True):
    """DeepFM: first-order weights + FM second-order interactions +
    a deep MLP tower, all over one shared embedding table.

    Returns (main, startup, feeds, fetches): feed slots are
    ``sparse_ids`` [B, num_fields] int64, ``dense_x`` [B, dense_dim],
    ``label`` [B, 1]; fetches: loss, auc-ready prediction.
    """
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        ids = layers.data("sparse_ids", [num_fields], dtype="int64")
        dense = layers.data("dense_x", [dense_dim])
        label = layers.data("label", [1])

        # first-order: per-id scalar weight
        w1 = layers.embedding(ids, size=[vocab_size, 1],
                              is_sparse=is_sparse,
                              param_attr=fluid.ParamAttr(name="fm_w1"))
        first_order = layers.reduce_sum(w1, dim=[1])  # [B, 1]

        # second-order: 0.5 * ((sum v)^2 - sum v^2)
        emb = layers.embedding(ids, size=[vocab_size, embed_dim],
                               is_sparse=is_sparse,
                               param_attr=fluid.ParamAttr(name="fm_v"))
        sum_v = layers.reduce_sum(emb, dim=[1])           # [B, D]
        sum_v_sq = layers.square(sum_v)
        sq_v = layers.square(emb)
        sum_sq_v = layers.reduce_sum(sq_v, dim=[1])
        second_order = layers.scale(
            layers.reduce_sum(sum_v_sq - sum_sq_v, dim=[1], keep_dim=True),
            scale=0.5)                                     # [B, 1]

        # deep tower over [flattened embeddings ++ dense]
        deep_in = layers.concat(
            [layers.reshape(emb, [-1, num_fields * embed_dim]), dense],
            axis=1)
        h = deep_in
        for width in hidden:
            h = layers.fc(h, width, act="relu")
        deep_out = layers.fc(h, 1)

        logit = first_order + second_order + deep_out
        pred = layers.sigmoid(logit)
        loss = layers.mean(
            layers.sigmoid_cross_entropy_with_logits(logit, label))
        if optimizer is not None:
            optimizer.minimize(loss)
    return main, startup, {"ids": "sparse_ids", "dense": "dense_x",
                           "label": "label"}, {"loss": loss, "pred": pred}


def build_wide_deep(num_fields=8, vocab_size=1000, embed_dim=8,
                    hidden=(32, 16), optimizer=None, is_sparse=True):
    """wide & deep: linear (wide) memorization + MLP (deep)
    generalization over the same sparse ids."""
    import paddle_tpu as fluid
    from paddle_tpu import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup), fluid.unique_name.guard():
        ids = layers.data("sparse_ids", [num_fields], dtype="int64")
        label = layers.data("label", [1])

        wide = layers.embedding(ids, size=[vocab_size, 1],
                                is_sparse=is_sparse,
                                param_attr=fluid.ParamAttr(name="wide_w"))
        wide_out = layers.reduce_sum(wide, dim=[1])

        emb = layers.embedding(ids, size=[vocab_size, embed_dim],
                               is_sparse=is_sparse,
                               param_attr=fluid.ParamAttr(name="deep_emb"))
        h = layers.reshape(emb, [-1, num_fields * embed_dim])
        for width in hidden:
            h = layers.fc(h, width, act="relu")
        deep_out = layers.fc(h, 1)

        logit = wide_out + deep_out
        pred = layers.sigmoid(logit)
        loss = layers.mean(
            layers.sigmoid_cross_entropy_with_logits(logit, label))
        if optimizer is not None:
            optimizer.minimize(loss)
    return main, startup, {"ids": "sparse_ids", "label": "label"}, {
        "loss": loss, "pred": pred}


def synthetic_ctr_batch(rng: np.random.RandomState, batch, num_fields=8,
                        vocab_size=1000, dense_dim=4):
    """Clickable synthetic data: label correlates with a few 'magic'
    ids so training visibly reduces loss."""
    ids = rng.randint(0, vocab_size, (batch, num_fields)).astype("int64")
    dense = rng.rand(batch, dense_dim).astype("float32")
    magic = (ids % 7 == 0).sum(1) + dense.sum(1)
    label = (magic > np.median(magic)).astype("float32").reshape(-1, 1)
    return {"sparse_ids": ids, "dense_x": dense, "label": label}

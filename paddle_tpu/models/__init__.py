"""Model zoo built on the layers API.

Reference analogue: the "book"/dist test model definitions
(tests/book/, tests/unittests/dist_mnist.py, dist_se_resnext.py,
dist_transformer.py) — canonical models exercising the stack, also used
by bench.py and __graft_entry__.py.
"""

from .bert import BertConfig, build_bert_pretrain, apply_megatron_sharding
from .resnet import build_resnet50
from .mnist import build_lenet

"""Model zoo built on the layers API.

Reference analogue: the "book"/dist test model definitions
(tests/book/, tests/unittests/dist_mnist.py, dist_se_resnext.py,
dist_transformer.py) — canonical models exercising the stack, also used
by bench.py and __graft_entry__.py.
"""

from .bert import BertConfig, build_bert_pretrain, apply_megatron_sharding
from .resnet import build_resnet50
from .mnist import build_lenet
from .gpt import (
    GPTConfig,
    build_gpt_lm,
    apply_gpt_megatron_sharding,
    synthetic_lm_batch,
)
from .seq2seq import build_seq2seq, beam_search_infer
from .ctr import build_deepfm, build_wide_deep, synthetic_ctr_batch
from .vision import build_vgg, build_se_resnext
from .ssd import build_ssd, multi_box_head, ssd_loss, detection_output

"""VGG + SE-ResNeXt — the other two conv families the reference's
book/dist tests train (book/test_image_classification.py vgg16;
tests/unittests/dist_se_resnext.py SE-ResNeXt-50).

Both support data_format="NHWC" (TPU-native layout) like resnet.py;
the feed contract stays NCHW with one input transpose.
"""

from __future__ import annotations

from .. import layers
from ..core.framework import Program, program_guard
from ..param_attr import ParamAttr
from .resnet import _conv_bn


def _ch(x, fmt):
    return x.shape[1] if fmt == "NCHW" else x.shape[3]


def build_vgg(num_classes=10, image_size=32, optimizer=None, depth=11,
              data_format="NCHW"):
    """VGG-{11,13,16,19} with batch norm (reference book
    test_image_classification.py `vgg16_bn_drop`)."""
    cfgs = {
        11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
        13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
             512, 512, "M"],
        16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
             512, 512, 512, "M", 512, 512, 512, "M"],
        19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
             512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
    }
    fmt = data_format
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("image", [3, image_size, image_size])
        label = layers.data("label", [1], dtype="int64")
        x = img
        if fmt == "NHWC":
            x = layers.transpose(x, [0, 2, 3, 1])
        i = 0
        for v in cfgs[depth]:
            if v == "M":
                x = layers.pool2d(x, 2, "max", pool_stride=2,
                                  data_format=fmt)
                continue
            x = layers.conv2d(
                x, v, 3, padding=1, bias_attr=False,
                param_attr=ParamAttr(name=f"vgg.c{i}.w"), data_format=fmt)
            x = layers.batch_norm(
                x, act="relu", data_layout=fmt,
                param_attr=ParamAttr(name=f"vgg.bn{i}.s"),
                bias_attr=ParamAttr(name=f"vgg.bn{i}.b"),
                moving_mean_name=f"vgg.bn{i}.m",
                moving_variance_name=f"vgg.bn{i}.v")
            i += 1
        x = layers.dropout(x, 0.5)
        h = layers.fc(x, 512, act="relu", param_attr=ParamAttr(name="fc1.w"))
        h = layers.dropout(h, 0.5)
        logits = layers.fc(h, num_classes, param_attr=ParamAttr(name="fc2.w"))
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        if optimizer is not None:
            optimizer.minimize(loss)
    return main, startup, {"image": img, "label": label}, {"loss": loss,
                                                           "acc": acc}


def _squeeze_excite(x, reduction, name, fmt):
    c = _ch(x, fmt)
    pool = layers.pool2d(x, 1, "avg", global_pooling=True, data_format=fmt)
    sq = layers.fc(pool, max(c // reduction, 4), act="relu",
                   param_attr=ParamAttr(name=f"{name}.sq.w"))
    ex = layers.fc(sq, c, act="sigmoid",
                   param_attr=ParamAttr(name=f"{name}.ex.w"))
    # [B, C] gate reshaped to rank 4 at the layout's channel position
    ex4 = layers.reshape(ex, [-1, c, 1, 1] if fmt == "NCHW"
                         else [-1, 1, 1, c])
    return layers.elementwise_mul(x, ex4, axis=0)


def _sex_block(x, nf, stride, cardinality, reduction, name, fmt):
    """SE-ResNeXt bottleneck: grouped 3x3 + squeeze-excite + shortcut
    (reference dist_se_resnext.py bottleneck_block)."""
    conv0 = _conv_bn(x, nf, 1, 1, "relu", f"{name}.c0", fmt)
    conv1 = _conv_bn(conv0, nf, 3, stride, "relu", f"{name}.c1", fmt,
                     groups=cardinality)
    conv2 = _conv_bn(conv1, nf * 2, 1, 1, None, f"{name}.c2", fmt)
    scaled = _squeeze_excite(conv2, reduction, f"{name}.se", fmt)
    if stride != 1 or _ch(x, fmt) != nf * 2:
        short = _conv_bn(x, nf * 2, 1, stride, None, f"{name}.sc", fmt)
    else:
        short = x
    return layers.relu(layers.elementwise_add(short, scaled))


def build_se_resnext(num_classes=10, image_size=32, optimizer=None,
                     depth=(1, 1, 1), filters=(64, 128, 256),
                     cardinality=8, reduction=16, data_format="NCHW"):
    """SE-ResNeXt; default depth is the CI-sized variant (the reference
    dist test also shrinks it — full 50-layer = depth (3,4,6,3))."""
    fmt = data_format
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("image", [3, image_size, image_size])
        label = layers.data("label", [1], dtype="int64")
        x = img
        if fmt == "NHWC":
            x = layers.transpose(x, [0, 2, 3, 1])
        x = _conv_bn(x, 64, 3, 1, "relu", "stem", fmt)
        for stage, (d, f) in enumerate(zip(depth, filters)):
            for blk in range(d):
                stride = 2 if blk == 0 and stage > 0 else 1
                x = _sex_block(x, f, stride, cardinality, reduction,
                               f"s{stage}b{blk}", fmt)
        pool = layers.pool2d(x, 1, "avg", global_pooling=True,
                             data_format=fmt)
        logits = layers.fc(pool, num_classes,
                           param_attr=ParamAttr(name="head.w"))
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        if optimizer is not None:
            optimizer.minimize(loss)
    return main, startup, {"image": img, "label": label}, {"loss": loss,
                                                           "acc": acc}

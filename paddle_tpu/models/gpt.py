"""Decoder-only causal transformer LM (GPT family).

Reference analogue: tests/unittests/dist_transformer.py +
book/test_machine_translation.py scale models — the canonical
"transformer trained via the Program API" exercise. TPU-first choices:
fused QKV (one MXU matmul), causal flash attention (Pallas,
kernels/flash_attention.py) on TPU, megatron column/row sharding
annotations on the same `mp` axis convention as models/bert.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from .. import layers, nets
from ..core.framework import Program, default_main_program, program_guard
from ..param_attr import ParamAttr
from ..initializer import NormalInitializer


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_size: int = 3072
    max_position: int = 1024
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    initializer_range: float = 0.02
    use_flash_attention: bool = False
    # MoE (beyond-reference, SURVEY §2f EP axis): every `moe_every`-th
    # decoder swaps its dense FFN for a switch-MoE layer (0 = dense).
    # Train with CompiledProgram.with_expert_parallel to shard experts.
    moe_every: int = 0
    moe_experts: int = 8
    moe_capacity: float = 1.25
    moe_aux_coeff: float = 0.01

    @staticmethod
    def small():
        return GPTConfig()

    @staticmethod
    def tiny():
        return GPTConfig(vocab_size=1000, hidden_size=64, num_layers=2,
                         num_heads=4, ffn_size=256, max_position=128,
                         hidden_dropout=0.0, attention_dropout=0.0)

    @staticmethod
    def gpt3_1p3b():
        """GPT-3 XL shape (paper table 2.1): 24 layers, d_model 2048,
        16 heads x 128; ~1.3B params (BASELINE config 5)."""
        return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                         ffn_size=8192, max_position=1024)


def _attr(name, std, axes=None):
    # logical_axes: what each weight dim MEANS — the partition
    # subsystem's rules table maps them to mesh axes per compile
    # (partition/), so this one tagging makes GPT tensor-parallel
    # ready on any mesh with zero further model edits
    return ParamAttr(name=name, initializer=NormalInitializer(0.0, std),
                     logical_axes=axes)


def _decoder_layer(x, cfg: GPTConfig, idx: int, is_test=False,
                   aux_losses=None):
    h = cfg.hidden_size
    std = cfg.initializer_range
    pre = f"dec{idx}"
    ln1 = layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=ParamAttr(name=f"{pre}_ln1.scale"),
        bias_attr=ParamAttr(name=f"{pre}_ln1.bias"),
    )
    qkv = layers.fc(
        ln1, 3 * h, num_flatten_dims=2,
        param_attr=_attr(f"{pre}_qkv.w", std, axes=("embed", "heads")),
        bias_attr=ParamAttr(name=f"{pre}_qkv.b",
                            logical_axes=("heads",)),
    )
    q, k, v = layers.split(qkv, 3, dim=2)
    if cfg.use_flash_attention:
        from ..kernels import flash_attention_layer

        ctx = flash_attention_layer(q, k, v, cfg.num_heads, causal=True)
    else:
        ctx = nets.scaled_dot_product_attention(
            q, k, v, num_heads=cfg.num_heads, causal=True,
            dropout_rate=0.0 if is_test else cfg.attention_dropout,
        )
    proj = layers.fc(
        ctx, h, num_flatten_dims=2,
        param_attr=_attr(f"{pre}_proj.w", std, axes=("heads", "embed")),
        bias_attr=ParamAttr(name=f"{pre}_proj.b"),
    )
    if not is_test and cfg.hidden_dropout:
        proj = layers.dropout(proj, cfg.hidden_dropout,
                              dropout_implementation="upscale_in_train")
    x = layers.elementwise_add(x, proj)
    ln2 = layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=ParamAttr(name=f"{pre}_ln2.scale"),
        bias_attr=ParamAttr(name=f"{pre}_ln2.bias"),
    )
    if cfg.moe_every and (idx + 1) % cfg.moe_every == 0:
        ffn2, aux = layers.switch_moe(
            ln2, cfg.moe_experts, cfg.ffn_size,
            capacity_factor=cfg.moe_capacity,
            param_attr=ParamAttr(name=f"{pre}_moe"),
            bias_attr=ParamAttr(name=f"{pre}_moe_b"))
        if aux_losses is not None:
            aux_losses.append(aux)
    else:
        ffn1 = layers.fc(
            ln2, cfg.ffn_size, num_flatten_dims=2, act="gelu",
            param_attr=_attr(f"{pre}_ffn1.w", std,
                             axes=("embed", "mlp")),
            bias_attr=ParamAttr(name=f"{pre}_ffn1.b",
                                logical_axes=("mlp",)),
        )
        ffn2 = layers.fc(
            ffn1, h, num_flatten_dims=2,
            param_attr=_attr(f"{pre}_ffn2.w", std,
                             axes=("mlp", "embed")),
            bias_attr=ParamAttr(name=f"{pre}_ffn2.b"),
        )
    if not is_test and cfg.hidden_dropout:
        ffn2 = layers.dropout(ffn2, cfg.hidden_dropout,
                              dropout_implementation="upscale_in_train")
    return layers.elementwise_add(x, ffn2)


def build_gpt_lm(cfg: GPTConfig, seq_len: int, optimizer=None, is_test=False):
    """Next-token LM: returns (main, startup, feeds, fetches).
    tokens [B, S] int64 -> loss (shifted CE) + logits."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        tokens = layers.data("tokens", [seq_len], dtype="int64")
        labels = layers.data("labels", [seq_len], dtype="int64")
        emb = layers.embedding(
            tokens, size=[cfg.vocab_size, cfg.hidden_size],
            param_attr=_attr("gpt_tok_emb", cfg.initializer_range,
                             axes=("vocab", "embed")),
        )
        pos = layers.embedding(
            layers.assign(np.arange(seq_len, dtype="int64")[None, :]),
            size=[cfg.max_position, cfg.hidden_size],
            param_attr=_attr("gpt_pos_emb", cfg.initializer_range,
                             axes=("seq", "embed")),
        )
        x = layers.elementwise_add(emb, pos)
        aux_losses = []
        for i in range(cfg.num_layers):
            x = _decoder_layer(x, cfg, i, is_test=is_test,
                               aux_losses=aux_losses)
        x = layers.layer_norm(
            x, begin_norm_axis=2,
            param_attr=ParamAttr(name="gpt_lnf.scale"),
            bias_attr=ParamAttr(name="gpt_lnf.bias"),
        )
        logits = layers.fc(
            x, cfg.vocab_size, num_flatten_dims=2,
            param_attr=_attr("gpt_head.w", cfg.initializer_range,
                             axes=("embed", "vocab")),
            bias_attr=ParamAttr(name="gpt_head.b",
                                logical_axes=("vocab",)),
        )
        loss = layers.mean(
            layers.softmax_with_cross_entropy(
                logits, layers.unsqueeze(labels, [2])
            )
        )
        if aux_losses and not is_test:
            # switch-MoE load-balance term (mean over MoE layers) —
            # train-only: eval loss/perplexity stays the pure LM
            # objective
            total_aux = aux_losses[0]
            for a in aux_losses[1:]:
                total_aux = layers.elementwise_add(total_aux, a)
            loss = layers.elementwise_add(
                layers.reshape(loss, [1]),
                layers.scale(total_aux,
                             scale=cfg.moe_aux_coeff / len(aux_losses)))
            loss = layers.mean(loss)
        if optimizer is not None:
            optimizer.minimize(loss)
    return main, startup, {"tokens": tokens, "labels": labels}, {
        "loss": loss, "logits": logits,
    }


def apply_gpt_megatron_sharding(program: Program, mp_axis: str = "mp"):
    """Column-parallel qkv/ffn1, row-parallel proj/ffn2, vocab-parallel
    embeddings — same annotation scheme as models/bert.py
    apply_megatron_sharding."""
    block = program.global_block()
    for name, v in block.vars.items():
        if v.sharding is not None or not getattr(v, "persistable", False):
            continue
        # suffix match, not substring: optimizer accumulators are named
        # <param>_<acc>_0, so '"_qkv.w" in name' also tagged
        # dec0_qkv.w_beta1_pow_acc_0 — a [1]-shaped scalar — with a
        # rank-2 spec (distlint PTL060/PTL062 caught this; accumulators
        # get their spec below via structural inheritance instead)
        if name.endswith("_qkv.w") or name.endswith("_ffn1.w"):
            v.sharding = (None, mp_axis)
        elif name.endswith("_qkv.b") or name.endswith("_ffn1.b"):
            v.sharding = (mp_axis,)
        elif name.endswith("_proj.w") or name.endswith("_ffn2.w"):
            v.sharding = (mp_axis, None)
        elif name in ("gpt_tok_emb", "gpt_head.w"):
            v.sharding = (None, mp_axis) if name == "gpt_head.w" else (mp_axis, None)
    # optimizer accumulators inherit their param's sharding only when
    # the shapes line up (moment buffers yes; scalar beta-pow stays
    # replicated) — same scheme as models/bert.py
    for name, v in block.vars.items():
        owner = getattr(v, "accumulator_owner", None)
        if owner and owner in block.vars:
            base = block.vars[owner]
            if base.sharding is not None and v.shape == base.shape:
                v.sharding = base.sharding
    program._bump()


def synthetic_lm_batch(rng: np.random.RandomState, batch: int, seq_len: int,
                       vocab: int):
    """Learnable synthetic corpus: next token = (3*cur + 7) % vocab with
    occasional noise."""
    toks = rng.randint(0, vocab, (batch, seq_len)).astype("int64")
    for t in range(1, seq_len):
        toks[:, t] = (3 * toks[:, t - 1] + 7) % vocab
    labels = np.concatenate(
        [toks[:, 1:], ((3 * toks[:, -1:] + 7) % vocab)], axis=1
    ).astype("int64")
    return {"tokens": toks, "labels": labels}

"""Attention seq2seq (machine-translation book pattern).

Reference: python/paddle/fluid/tests/book/test_machine_translation.py —
GRU encoder, attention decoder, trained with teacher forcing and
decoded with beam search (beam_search/beam_search_decode ops). The
training graph here uses the dense recurrent op (ops/rnn.py) and the
inference path drives the SAME decoder-step program through the beam
ops, one Executor.run per step (the reference's While-block decode,
unrolled host-side)."""

from __future__ import annotations

from typing import Dict

import numpy as np

from .. import layers
from ..core.framework import Program, program_guard
from ..param_attr import ParamAttr


def _gru_step(x_and_prev, hidden_size, prefix):
    """One GRU cell step out of fc ops (shared by train scan and the
    inference step program via identical param names). Inputs are
    pre-concatenated so one named weight serves the whole cell."""
    x, prev = x_and_prev
    xp = layers.concat([x, prev], axis=1)
    gates = layers.fc(
        xp, 2 * hidden_size, act="sigmoid",
        param_attr=ParamAttr(name=f"{prefix}_gates.w"),
        bias_attr=ParamAttr(name=f"{prefix}_gates.b"),
    )
    r, z = layers.split(gates, 2, dim=1)
    cand = layers.fc(
        layers.concat([x, layers.elementwise_mul(r, prev)], axis=1),
        hidden_size, act="tanh",
        param_attr=ParamAttr(name=f"{prefix}_cand.w"),
        bias_attr=ParamAttr(name=f"{prefix}_cand.b"),
    )
    one_minus_z = layers.scale(z, scale=-1.0, bias=1.0)
    return layers.elementwise_add(
        layers.elementwise_mul(one_minus_z, prev),
        layers.elementwise_mul(z, cand),
    )


def build_seq2seq(src_vocab: int, tgt_vocab: int, seq_len: int,
                  emb_dim: int = 32, hidden: int = 64, optimizer=None):
    """Teacher-forced training graph. Returns (main, startup, feeds,
    fetches)."""
    main, startup = Program(), Program()
    with program_guard(main, startup):
        src = layers.data("src", [seq_len], dtype="int64")
        tgt_in = layers.data("tgt_in", [seq_len], dtype="int64")
        tgt_out = layers.data("tgt_out", [seq_len], dtype="int64")

        src_emb = layers.embedding(
            src, size=[src_vocab, emb_dim],
            param_attr=ParamAttr(name="s2s_src_emb"),
        )  # [B, S, E]
        # encoder: bidirectional-ish = fused GRU over the sequence
        enc = layers.dynamic_gru_dense(src_emb, hidden) if hasattr(
            layers, "dynamic_gru_dense") else None
        if enc is None:
            from ..layers.control_flow import StaticRNN

            src_t = layers.transpose(src_emb, [1, 0, 2])  # [S, B, E]
            rnn = StaticRNN()
            with rnn.step():
                word = rnn.step_input(src_t)
                prev = rnn.memory(shape=[-1, hidden], batch_ref=word,
                                  ref_batch_dim_idx=0)
                h = _gru_step((word, prev), hidden, "s2s_enc")
                rnn.update_memory(prev, h)
                rnn.step_output(h)
            enc_states = rnn()  # [S, B, H]
            enc = layers.transpose(enc_states, [1, 0, 2])  # [B, S, H]

        tgt_emb = layers.embedding(
            tgt_in, size=[tgt_vocab, emb_dim],
            param_attr=ParamAttr(name="s2s_tgt_emb"),
        )
        from ..layers.control_flow import StaticRNN

        tgt_t = layers.transpose(tgt_emb, [1, 0, 2])
        dec = StaticRNN()
        with dec.step():
            word = dec.step_input(tgt_t)
            prev = dec.memory(shape=[-1, hidden], batch_ref=word,
                              ref_batch_dim_idx=0)
            ctx = _attention(prev, enc, hidden)
            inp = layers.concat([word, ctx], axis=1)
            h = _gru_step((inp, prev), hidden, "s2s_dec")
            dec.update_memory(prev, h)
            dec.step_output(h)
        dec_states = layers.transpose(dec(), [1, 0, 2])  # [B, S, H]
        logits = layers.fc(
            dec_states, tgt_vocab, num_flatten_dims=2,
            param_attr=ParamAttr(name="s2s_head.w"),
            bias_attr=ParamAttr(name="s2s_head.b"),
        )
        loss = layers.mean(
            layers.softmax_with_cross_entropy(
                logits, layers.unsqueeze(tgt_out, [2])
            )
        )
        if optimizer is not None:
            optimizer.minimize(loss)
    return main, startup, {"src": src, "tgt_in": tgt_in, "tgt_out": tgt_out}, {
        "loss": loss, "logits": logits, "encoder": enc,
    }


def _attention(query, enc, hidden):
    """Additive attention: scores = v' tanh(W [h; enc_t])."""
    q_proj = layers.fc(
        query, hidden, bias_attr=False,
        param_attr=ParamAttr(name="s2s_att_q.w"),
    )  # [B, H]
    e_proj = layers.fc(
        enc, hidden, num_flatten_dims=2, bias_attr=False,
        param_attr=ParamAttr(name="s2s_att_e.w"),
    )  # [B, S, H]
    mix = layers.tanh(
        layers.elementwise_add(e_proj, layers.unsqueeze(q_proj, [1]))
    )
    scores = layers.fc(
        mix, 1, num_flatten_dims=2, bias_attr=False,
        param_attr=ParamAttr(name="s2s_att_v.w"),
    )  # [B, S, 1]
    w = layers.softmax(layers.squeeze(scores, [2]))  # [B, S]
    return layers.squeeze(
        layers.matmul(layers.unsqueeze(w, [1]), enc), [1]
    )  # [B, H]


def build_decoder_step(src_vocab: int, tgt_vocab: int, seq_len: int,
                       emb_dim: int = 32, hidden: int = 64):
    """One decoder step + beam expansion as its own program (the
    reference's While-block body). Feeds: enc [B, S, H] (from the
    training/encoder program), prev_hidden [B*beam, H], pre_ids,
    pre_scores [B, beam]. Startup shares param NAMES with the training
    program, so load the trained scope."""
    main = Program()
    startup = Program()
    with program_guard(main, startup):
        enc = layers.data("enc", [seq_len, hidden], dtype="float32")
        prev_h = layers.data("prev_h", [hidden], dtype="float32")
        cur_ids = layers.data("cur_ids", [1], dtype="int64")
        # embedding over [B*beam, 1] ids flattens to [B*beam, E]
        word = layers.embedding(
            cur_ids, size=[tgt_vocab, emb_dim],
            param_attr=ParamAttr(name="s2s_tgt_emb"),
        )
        ctx = _attention(prev_h, enc, hidden)
        inp = layers.concat([word, ctx], axis=1)
        h = _gru_step((inp, prev_h), hidden, "s2s_dec")
        logits = layers.fc(
            h, tgt_vocab,
            param_attr=ParamAttr(name="s2s_head.w"),
            bias_attr=ParamAttr(name="s2s_head.b"),
        )
        logp = layers.log_softmax(logits) if hasattr(layers, "log_softmax") \
            else layers.log(layers.softmax(logits))
    return main, startup, {
        "enc": enc, "prev_h": prev_h, "cur_ids": cur_ids,
    }, {"logp": logp, "h": h}


def beam_search_infer(exe, scope, enc_value, step_prog,
                      step_fetches, beam_size, bos_id, eos_id, max_len,
                      hidden):
    """Host-driven beam decode over the step program (reference's
    While + beam_search ops): each iteration runs the decoder step for
    all B*beam hypotheses, expands with the beam_search op, reorders
    hidden states by parent_idx, and finally backtracks with
    beam_search_decode."""
    B = enc_value.shape[0]
    cur = np.full((B, beam_size), bos_id, "int64")
    scores = np.zeros((B, beam_size), "float32")
    scores[:, 1:] = -1e9  # first step: one live hypothesis
    h = np.zeros((B * beam_size, hidden), "float32")
    enc_tiled = np.repeat(enc_value, beam_size, axis=0)
    all_ids, all_parents = [], []
    for _ in range(max_len):
        logp, h_new = exe.run(
            step_prog,
            feed={"enc": enc_tiled, "prev_h": h,
                  "cur_ids": cur.reshape(-1, 1)},
            fetch_list=[step_fetches["logp"], step_fetches["h"]],
            scope=scope,
        )
        V = logp.shape[-1]
        acc = scores[..., None] + logp.reshape(B, beam_size, V)
        sel_ids, sel_scores, parents = _beam_step(
            exe, cur, scores, acc, beam_size, eos_id
        )
        all_ids.append(sel_ids)
        all_parents.append(parents)
        # reorder hidden by parent beam
        h = h_new.reshape(B, beam_size, hidden)[
            np.arange(B)[:, None], parents
        ].reshape(B * beam_size, hidden)
        cur, scores = sel_ids.astype("int64"), sel_scores
    return _beam_decode(exe, np.stack(all_ids).astype("int32"),
                        np.stack(all_parents).astype("int32"),
                        scores, beam_size, eos_id)


_BEAM_PROG_CACHE = {}


def _beam_step(exe, pre_ids, pre_scores, acc, beam_size, eos_id):
    # one program per (shape, beam, eos): rebuilt programs would force a
    # fresh lowering every decode step
    ck = ("step", pre_ids.shape, acc.shape, beam_size, eos_id)
    if ck in _BEAM_PROG_CACHE:
        main, outs = _BEAM_PROG_CACHE[ck]
        return tuple(exe.run(main, feed={
            "bs_pre_ids": pre_ids.astype("int32"),
            "bs_pre_scores": pre_scores, "bs_scores": acc,
        }, fetch_list=outs))
    main, startup = Program(), Program()
    with program_guard(main, startup):
        blk = main.global_block()
        mk = lambda n, a: (blk.create_var(name=n, shape=a.shape,
                                          dtype=str(a.dtype), is_data=True))
        pi = mk("bs_pre_ids", pre_ids.astype("int32"))
        ps = mk("bs_pre_scores", pre_scores)
        sc = mk("bs_scores", acc)
        outs = [blk.create_var(name=f"bs_o{i}") for i in range(3)]
        blk.append_op(
            type="beam_search",
            inputs={"pre_ids": [pi], "pre_scores": [ps], "scores": [sc]},
            outputs={"selected_ids": [outs[0]], "selected_scores": [outs[1]],
                     "parent_idx": [outs[2]]},
            attrs={"beam_size": beam_size, "end_id": eos_id,
                   "is_accumulated": True},
        )
    _BEAM_PROG_CACHE[ck] = (main, outs)
    return tuple(exe.run(main, feed={
        "bs_pre_ids": pre_ids.astype("int32"),
        "bs_pre_scores": pre_scores, "bs_scores": acc,
    }, fetch_list=outs))


def _beam_decode(exe, ids, parents, final_scores, beam_size, eos_id):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        blk = main.global_block()
        mk = lambda n, a: blk.create_var(name=n, shape=a.shape,
                                         dtype=str(a.dtype), is_data=True)
        iv = mk("bd_ids", ids)
        pv = mk("bd_parents", parents)
        sv = mk("bd_scores", final_scores)
        s_out = blk.create_var(name="bd_sent")
        sc_out = blk.create_var(name="bd_sent_scores")
        blk.append_op(
            type="beam_search_decode",
            inputs={"Ids": [iv], "Parents": [pv], "Scores": [sv]},
            outputs={"SentenceIds": [s_out], "SentenceScores": [sc_out]},
            attrs={"beam_size": beam_size, "end_id": eos_id},
        )
    return tuple(exe.run(main, feed={
        "bd_ids": ids, "bd_parents": parents, "bd_scores": final_scores,
    }, fetch_list=[s_out, sc_out]))

"""ResNet-50 (BASELINE config 2). Reference model shape:
tests/unittests/dist_se_resnext.py + book image-classification tests.

data_format="NHWC" runs every conv/bn/pool in the TPU-native layout
(trailing channels tile onto vector lanes without relayouts); the feed
contract stays NCHW — the one transpose happens on the input image."""

from __future__ import annotations

from .. import layers
from ..core.framework import Program, program_guard
from ..param_attr import ParamAttr


def _conv_bn(x, num_filters, filter_size, stride=1, act="relu", name="",
             fmt="NCHW", groups=1):
    """conv(no bias) + batch_norm, layout-aware. Shared by the resnet /
    vgg / se_resnext builders (models/vision.py imports it)."""
    conv = layers.conv2d(
        x, num_filters, filter_size, stride=stride,
        padding=(filter_size - 1) // 2, bias_attr=False, groups=groups,
        param_attr=ParamAttr(name=f"{name}.conv.w"),
        data_format=fmt,
    )
    return layers.batch_norm(
        conv, act=act,
        param_attr=ParamAttr(name=f"{name}.bn.scale"),
        bias_attr=ParamAttr(name=f"{name}.bn.bias"),
        moving_mean_name=f"{name}.bn.mean",
        moving_variance_name=f"{name}.bn.var",
        data_layout=fmt,
    )


def _bottleneck(x, num_filters, stride, name, fmt="NCHW"):
    ch_axis = 1 if fmt == "NCHW" else 3
    conv0 = _conv_bn(x, num_filters, 1, act="relu", name=f"{name}.b0",
                     fmt=fmt)
    conv1 = _conv_bn(conv0, num_filters, 3, stride=stride, act="relu",
                     name=f"{name}.b1", fmt=fmt)
    conv2 = _conv_bn(conv1, num_filters * 4, 1, act=None, name=f"{name}.b2",
                     fmt=fmt)
    if stride != 1 or x.shape[ch_axis] != num_filters * 4:
        short = _conv_bn(x, num_filters * 4, 1, stride=stride, act=None,
                         name=f"{name}.sc", fmt=fmt)
    else:
        short = x
    return layers.relu(layers.elementwise_add(short, conv2))


def build_resnet50(num_classes=1000, image_size=224, optimizer=None,
                   data_format="NCHW"):
    fmt = data_format
    main, startup = Program(), Program()
    with program_guard(main, startup):
        img = layers.data("image", [3, image_size, image_size])
        label = layers.data("label", [1], dtype="int64")
        x = img
        if fmt == "NHWC":
            x = layers.transpose(x, [0, 2, 3, 1])
        x = _conv_bn(x, 64, 7, stride=2, name="stem", fmt=fmt)
        x = layers.pool2d(x, 3, "max", pool_stride=2, pool_padding=1,
                          data_format=fmt)
        depth = [3, 4, 6, 3]
        filters = [64, 128, 256, 512]
        for stage, (d, f) in enumerate(zip(depth, filters)):
            for blk in range(d):
                stride = 2 if blk == 0 and stage > 0 else 1
                x = _bottleneck(x, f, stride, name=f"s{stage}b{blk}",
                                fmt=fmt)
        pool = layers.pool2d(x, 7, "avg", global_pooling=True,
                             data_format=fmt)
        logits = layers.fc(pool, num_classes, param_attr=ParamAttr(name="head.w"))
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        if optimizer is not None:
            optimizer.minimize(loss)
    return main, startup, {"image": img, "label": label}, {"loss": loss, "acc": acc}

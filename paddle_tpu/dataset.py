"""Dataset API: high-throughput file-based ingest.

Reference: python/paddle/fluid/dataset.py:22-47 (DatasetFactory,
QueueDataset, InMemoryDataset) wrapping the C++ MultiSlotDataFeed
(framework/data_feed.h:61, data_feed.proto) — multi-threaded
file->channel parsing with global shuffle via fleet RPC
(framework/data_set.cc).

TPU-native: parsing runs in the native C++ datafeed library
(native/datafeed.cpp, loaded via ctypes) with python-thread fallback;
batches flow to the device through the DataLoader prefetch path.
Global shuffle uses a local shard shuffle (single-host) — multi-host
global shuffle exchanges shard lists through the coordination service.
"""

from __future__ import annotations

import os
import random
import threading
import queue as _queue
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np


class DatasetFactory:
    """Reference dataset.py DatasetFactory.create_dataset."""

    def create_dataset(self, datafeed_class: str = "QueueDataset"):
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        raise ValueError(f"unknown dataset class {datafeed_class!r}")


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._thread_num = 1
        self._filelist: List[str] = []
        self._use_var_names: List[str] = []
        self._var_shapes: Dict[str, tuple] = {}
        self._var_dtypes: Dict[str, str] = {}
        self._pipe_command = None

    # -- reference API --------------------------------------------------------
    def set_batch_size(self, batch_size: int):
        self._batch_size = batch_size

    def set_thread(self, thread_num: int):
        self._thread_num = thread_num

    def set_filelist(self, filelist: Sequence[str]):
        self._filelist = list(filelist)

    def set_use_var(self, var_list):
        self._use_var_names = [v.name for v in var_list]
        for v in var_list:
            self._var_shapes[v.name] = tuple(
                d for d in (v.shape or ()) if d is not None and d > 0
            )
            self._var_dtypes[v.name] = v.dtype

    def set_pipe_command(self, cmd: str):
        self._pipe_command = cmd

    def get_filelist(self):
        return self._filelist

    # -- parsing --------------------------------------------------------------
    def _parse_file(self, path: str) -> Iterator[List[np.ndarray]]:
        """MultiSlot text format (reference MultiSlotDataFeed): each
        line = for each slot: <n> v1 ... vn. Uses the native parser
        when available."""
        from .native import datafeed as native_feed

        dtypes = [self._var_dtypes[n] for n in self._use_var_names]
        if native_feed.available():
            yield from native_feed.parse_file(path, len(self._use_var_names), dtypes)
            return
        with open(path) as f:
            for line in f:
                parts = line.split()
                i = 0
                sample = []
                for slot_i in range(len(self._use_var_names)):
                    n = int(parts[i])
                    i += 1
                    vals = parts[i : i + n]
                    i += n
                    dt = dtypes[slot_i]
                    arr = np.array(vals, dtype=np.float32 if "float" in dt else np.int64)
                    sample.append(arr)
                yield sample

    def _iter_samples(self) -> Iterator[List[np.ndarray]]:
        for path in self._filelist:
            yield from self._parse_file(path)

    def _iter_batches(self) -> Iterator[Dict[str, np.ndarray]]:
        """Multi-threaded file parsing feeding a bounded channel
        (reference data_feed channels), batched for the executor."""
        chan: "_queue.Queue" = _queue.Queue(maxsize=4 * self._thread_num * self._batch_size)
        stop = object()
        files = list(self._filelist)

        def worker(paths):
            for p in paths:
                for s in self._parse_file(p):
                    chan.put(s)
            chan.put(stop)

        nthreads = max(1, min(self._thread_num, len(files) or 1))
        shards = [files[i::nthreads] for i in range(nthreads)]
        for sh in shards:
            threading.Thread(target=worker, args=(sh,), daemon=True).start()

        done = 0
        buf: List[List[np.ndarray]] = []
        while done < nthreads:
            item = chan.get()
            if item is stop:
                done += 1
                continue
            buf.append(item)
            if len(buf) == self._batch_size:
                yield self._collate(buf)
                buf = []
        if buf:
            yield self._collate(buf)

    def _collate(self, rows: List[List[np.ndarray]]) -> Dict[str, np.ndarray]:
        out = {}
        for i, name in enumerate(self._use_var_names):
            cols = [r[i] for r in rows]
            arr = np.stack(cols, axis=0)
            shp = self._var_shapes.get(name)
            if shp:
                arr = arr.reshape((arr.shape[0],) + shp)
            want = self._var_dtypes[name]
            if "int" in want:
                arr = arr.astype(np.int64)
            out[name] = arr
        return out


class QueueDataset(DatasetBase):
    """Streaming dataset (reference QueueDataset): files parsed on the
    fly, no global shuffle."""


class InMemoryDataset(DatasetBase):
    """Reference InMemoryDataset: load_into_memory + local/global
    shuffle + merge."""

    def __init__(self):
        super().__init__()
        self._samples: List[List[np.ndarray]] = []

    def load_into_memory(self):
        self._samples = list(self._iter_samples())

    def local_shuffle(self, seed: Optional[int] = None):
        random.Random(seed).shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num: int = 12, seed: Optional[int] = None):
        """Shuffle across ALL trainers (reference data_set.cc
        GlobalShuffle ships samples between workers over fleet RPC).

        TPU-native: every rank loads the same source and applies one
        seed-synchronized permutation, then keeps its rank's slice —
        the same resulting partition as the reference's exchange with
        zero cross-worker traffic. Rank/world come from `fleet` when
        given, else the launcher env contract."""
        import os

        if fleet is not None:
            rank, world = fleet.worker_index(), max(fleet.worker_num(), 1)
        else:
            rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
            world = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        # always partition from the FULL load: calling global_shuffle
        # once per epoch must re-deal the same deck, not slice the
        # rank's previous slice to nothing
        if not hasattr(self, "_full_samples"):
            self._full_samples = list(self._samples)
        self._shuffle_epoch = getattr(self, "_shuffle_epoch", 0) + 1
        if seed is None:
            # must agree across ranks; vary per epoch deterministically
            seed = self._shuffle_epoch
        rng = random.Random(seed)
        order = list(range(len(self._full_samples)))
        rng.shuffle(order)
        self._samples = [self._full_samples[i] for i in order[rank::world]]

    def release_memory(self):
        self._samples = []

    def get_memory_data_size(self, fleet=None):
        return len(self._samples)

    def _iter_batches(self):
        buf = []
        for s in self._samples:
            buf.append(s)
            if len(buf) == self._batch_size:
                yield self._collate(buf)
                buf = []
        if buf:
            yield self._collate(buf)

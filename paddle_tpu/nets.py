"""Composite networks. Reference: python/paddle/fluid/nets.py
(simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention)."""

from __future__ import annotations

from . import layers

__all__ = [
    "simple_img_conv_pool",
    "img_conv_group",
    "glu",
    "scaled_dot_product_attention",
]


def simple_img_conv_pool(
    input,
    num_filters,
    filter_size,
    pool_size,
    pool_stride,
    pool_padding=0,
    pool_type="max",
    global_pooling=False,
    conv_stride=1,
    conv_padding=0,
    conv_dilation=1,
    conv_groups=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    use_cudnn=True,
):
    conv_out = layers.conv2d(
        input=input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=conv_stride,
        padding=conv_padding,
        dilation=conv_dilation,
        groups=conv_groups,
        param_attr=param_attr,
        bias_attr=bias_attr,
        act=act,
    )
    return layers.pool2d(
        input=conv_out,
        pool_size=pool_size,
        pool_type=pool_type,
        pool_stride=pool_stride,
        pool_padding=pool_padding,
        global_pooling=global_pooling,
    )


def img_conv_group(
    input,
    conv_num_filter,
    pool_size,
    conv_padding=1,
    conv_filter_size=3,
    conv_act=None,
    param_attr=None,
    conv_with_batchnorm=False,
    conv_batchnorm_drop_rate=0.0,
    pool_stride=1,
    pool_type="max",
    use_cudnn=True,
):
    tmp = input
    if not isinstance(conv_padding, list):
        conv_padding = [conv_padding] * len(conv_num_filter)
    if not isinstance(conv_with_batchnorm, list):
        conv_with_batchnorm = [conv_with_batchnorm] * len(conv_num_filter)
    if not isinstance(conv_batchnorm_drop_rate, list):
        conv_batchnorm_drop_rate = [conv_batchnorm_drop_rate] * len(conv_num_filter)
    for i, nf in enumerate(conv_num_filter):
        local_act = None if conv_with_batchnorm[i] else conv_act
        tmp = layers.conv2d(
            input=tmp,
            num_filters=nf,
            filter_size=conv_filter_size,
            padding=conv_padding[i],
            param_attr=param_attr,
            act=local_act,
        )
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i]:
                tmp = layers.dropout(tmp, conv_batchnorm_drop_rate[i])
    return layers.pool2d(
        input=tmp, pool_size=pool_size, pool_type=pool_type, pool_stride=pool_stride
    )


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(
    queries, keys, values, num_heads=1, dropout_rate=0.0, causal=False,
    padding_mask=None,
):
    """Multi-head attention from program-level ops (reference nets.py).
    The fused Pallas path is paddle_tpu.kernels.flash_attention, used by
    the transformer models; this version keeps op-graph parity.
    padding_mask: [B, S] float (1 = real token, 0 = padding) — keys at
    padded positions get -1e9 added to their logits."""
    d_key = queries.shape[-1] // num_heads

    def _split_heads(x):
        b, t, d = x.shape
        y = layers.reshape(x, [0, 0, num_heads, d // num_heads])
        return layers.transpose(y, [0, 2, 1, 3])

    def _merge_heads(x):
        b, h, t, d = x.shape
        y = layers.transpose(x, [0, 2, 1, 3])
        return layers.reshape(y, [0, 0, h * d])

    q = _split_heads(queries)
    k = _split_heads(keys)
    v = _split_heads(values)
    scaled = layers.scale(q, scale=d_key**-0.5)
    logits = layers.matmul(scaled, k, transpose_y=True)
    if padding_mask is not None:
        # (1 - mask) * -1e9 broadcast over [B, H, S_q, S_k]'s key dim
        neg = layers.scale(padding_mask, scale=1e9, bias=-1e9)  # 0 / -1e9
        neg = layers.unsqueeze(neg, [1, 2])  # [B, 1, 1, S]
        logits = layers.elementwise_add(logits, neg)
    if causal:
        import numpy as _np

        T = int(logits.shape[-1])
        mask = layers.assign(
            _np.triu(_np.full((T, T), -1e9, "float32"), k=1)[None, None]
        )
        logits = layers.elementwise_add(logits, mask)
    weights = layers.softmax(logits)
    if dropout_rate:
        weights = layers.dropout(
            weights, dropout_rate, dropout_implementation="upscale_in_train"
        )
    ctx = layers.matmul(weights, v)
    return _merge_heads(ctx)

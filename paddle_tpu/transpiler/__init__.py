"""Distribution transpilers (API-parity layer).

Reference: python/paddle/fluid/transpiler/ — DistributeTranspiler
(distribute_transpiler.py:254,540) rewrites programs for pserver /
nccl2 / collective modes; collective.py:36-377 inserts c_gen_nccl_id /
c_comm_init / c_allreduce ops; geo_sgd_transpiler.py for geo-async.

TPU-native: graph rewriting for collectives is unnecessary (GSPMD
inserts them from shardings), so the transpile step's real output is a
*mesh execution plan* attached to the program. The op-insertion
entry points still exist and emit real collective ops (lowered via
named-axis lax collectives) so reference-style user code keeps working.
"""

from .distribute_transpiler import (
    DistributeTranspiler,
    DistributeTranspilerConfig,
)
from .collective import GradAllReduce, LocalSGD, SingleProcessMultiThread
from .geo_sgd_transpiler import GeoSgdTranspiler

__all__ = [
    "DistributeTranspiler",
    "DistributeTranspilerConfig",
    "GradAllReduce",
    "LocalSGD",
    "SingleProcessMultiThread",
    "GeoSgdTranspiler",
]

from .layout import auto_nhwc  # noqa: F401,E402

"""DistributeTranspiler.

Reference: transpiler/distribute_transpiler.py:254 (config :141,
transpile :540; nccl2 path :598-640; pserver program construction
:640ff with slice_var_up param splitting).

Modes here:
  * "collective"/"nccl2": mark the program for mesh data-parallel
    execution (CompiledProgram.with_data_parallel does the real work;
    rendezvous = jax.distributed, replacing gen_nccl_id RPC).
  * "pserver"/"geo": build trainer/pserver programs against the
    host parameter-server runtime (paddle_tpu/ps/) which replaces the
    reference's gRPC listen_and_serv stack for sparse/host-resident
    tables. Dense training on TPU prefers fully-sharded params; the PS
    path exists for embedding-dominated CTR-style workloads.
"""

from __future__ import annotations

from typing import List, Optional

from ..core import framework


class DistributeTranspilerConfig:
    """Reference distribute_transpiler.py:141."""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    enable_dc_asgd = False
    mode = "pserver"
    print_log = False
    wait_port = True
    runtime_split_send_recv = False
    sync_mode = True
    geo_sgd_mode = False
    geo_sgd_need_push_nums = 100
    collective_mode: Optional[str] = None
    nccl_comm_num = 1
    use_hierarchical_allreduce = False
    hierarchical_allreduce_inter_nranks = 0


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._mode = None
        self._trainer_id = 0
        self._trainers = 1
        self._origin_program = None
        self._pserver_endpoints: List[str] = []

    def transpile(
        self,
        trainer_id: int,
        program=None,
        pservers: str = "127.0.0.1:6174",
        trainers: int = 1,
        sync_mode: bool = True,
        startup_program=None,
        current_endpoint: str = "127.0.0.1:6174",
    ):
        program = program or framework.default_main_program()
        self._origin_program = program
        self._trainer_id = trainer_id
        self._pserver_endpoints = [e for e in str(pservers).split(",") if e]
        if isinstance(trainers, str):
            # nccl2 mode passes trainer endpoints string (reference :598)
            self._trainer_endpoints = trainers.split(",")
            self._trainers = len(self._trainer_endpoints)
        else:
            self._trainers = int(trainers)
        self._sync_mode = sync_mode

        mode = self.config.mode
        if self.config.collective_mode or mode in ("nccl2", "collective"):
            # collective DP: attach mesh plan; grads allreduced by GSPMD
            self._mode = "collective"
            program._dist_plan = {
                "mode": "collective",
                "trainer_id": trainer_id,
                "trainers": self._trainers,
            }
            return
        self._mode = "pserver"
        from ..ps.transpile import build_ps_programs

        self._ps_artifacts = build_ps_programs(
            program,
            startup_program or framework.default_startup_program(),
            self._pserver_endpoints,
            trainer_id,
            self._trainers,
            sync_mode,
            slice_var_up=self.config.slice_var_up,
            min_block_size=self.config.min_block_size,
        )

    # -- reference getters ----------------------------------------------------
    def get_trainer_program(self, wait_port=True):
        if self._mode == "collective":
            return self._origin_program
        return self._ps_artifacts.trainer_program

    def get_pserver_program(self, endpoint: str):
        assert self._mode == "pserver", "no pserver program in collective mode"
        return self._ps_artifacts.pserver_programs[endpoint]

    def get_pserver_programs(self, endpoint: str):
        return self.get_pserver_program(endpoint), self.get_startup_program(endpoint)

    def get_startup_program(self, endpoint: str, pserver_program=None, startup_program=None):
        return self._ps_artifacts.pserver_startups[endpoint]

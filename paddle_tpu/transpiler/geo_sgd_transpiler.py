"""Geo-SGD transpiler. Reference: transpiler/geo_sgd_transpiler.py —
local SGD on trainers; every K steps push param deltas to pservers and
pull the merged result (GeoSgdCommunicator)."""

from __future__ import annotations

from ..core import framework
from .distribute_transpiler import DistributeTranspiler, DistributeTranspilerConfig


class GeoSgdTranspiler(DistributeTranspiler):
    def __init__(self, config=None):
        config = config or DistributeTranspilerConfig()
        config.geo_sgd_mode = True
        config.sync_mode = False
        super().__init__(config)

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=False, startup_program=None,
                  current_endpoint="127.0.0.1:6174"):
        super().transpile(trainer_id, program, pservers, trainers, False,
                          startup_program, current_endpoint)
        if self._mode == "pserver":
            # geo: trainers run the FULL program locally (incl. optimizer
            # ops) and only sync deltas; the pserver applies deltas with
            # lr=1 (reference geo_sgd semantics)
            self._ps_artifacts.trainer_program = self._origin_program
            for k in self._ps_artifacts.optimizer_specs:
                self._ps_artifacts.optimizer_specs[k] = {"type": "sgd", "lr": 1.0}

    def get_communicator(self, scope, need_push_nums=100):
        from ..ps.communicator import Communicator

        return Communicator(self._ps_artifacts, scope, mode="geo",
                            geo_need_push_nums=need_push_nums)

"""Automatic NCHW -> NHWC layout conversion pass.

The reference converts layouts with IR passes + a data-layout-transfer
runtime (framework/data_layout_transform.cc, the mkldnn layout passes);
here the same idea is a program-rewriting pass targeting the TPU-native
channels-last layout: users keep NCHW model code, `auto_nhwc(program)`
flips every conv/pool/batch_norm region to NHWC and inserts transposes
only at region boundaries (feeds, fc/matmul anchors, fetches of 4D
intermediates come back channels-last — scalar losses are unchanged).

Contract: run on the FORWARD program, before append_backward/minimize
(grad ops copy forward attrs at creation; the registry auto-vjp then
differentiates the flipped forward, so gradients follow for free).
"""

from __future__ import annotations

from ..core.framework import OpRole, Program, unique_name

# op type -> layout attr name
_FLIPPABLE = {
    "conv2d": ("data_format", "Input", "Output"),
    "depthwise_conv2d": ("data_format", "Input", "Output"),
    "conv2d_transpose": ("data_format", "Input", "Output"),
    "pool2d": ("data_format", "X", "Out"),
    "batch_norm": ("data_layout", "X", "Y"),
    "sync_batch_norm": ("data_layout", "X", "Y"),
    "group_norm": ("data_layout", "X", "Y"),
}

# elementwise/unary ops that are layout-agnostic when all 4D operands
# share the region layout
_UNARY_PASS = {
    "relu", "relu6", "gelu", "sigmoid", "tanh", "leaky_relu", "elu",
    "swish", "hard_swish", "hard_sigmoid", "softplus", "dropout",
    "scale", "cast", "sqrt", "square", "abs", "exp", "pow", "clip",
}
_EW_PASS = {
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
}

_TO_NHWC = [0, 2, 3, 1]
_TO_NCHW = [0, 3, 1, 2]


def _is4d(block, name):
    v = block.vars.get(name)
    return v is not None and v.shape is not None and len(v.shape) == 4


def auto_nhwc(program: Program) -> int:
    """Rewrite in place; returns the number of ops flipped to NHWC.
    Raises if the program already has backward/optimize ops."""
    block = program.global_block()
    for op in block.ops:
        if int(op.attrs.get("op_role", 0)) & (OpRole.Backward | OpRole.Optimize):
            raise ValueError(
                "auto_nhwc must run on the forward program, before "
                "append_backward/minimize (grad ops copy forward attrs)")

    nhwc = set()        # var names currently holding NHWC values
    new_ops = []
    flipped = 0
    trans_cache = {}    # (name, to_nhwc) -> transposed var name

    def _permute_meta(name):
        v = block.vars.get(name)
        if v is not None and v.shape is not None and len(v.shape) == 4:
            s = list(v.shape)
            v.shape = (s[0], s[2], s[3], s[1])

    def _transpose(name, to_nhwc):
        """Emit a transpose2 of `name`; returns the new var name."""
        perm = _TO_NHWC if to_nhwc else _TO_NCHW
        src = block.vars.get(name)
        shp = None
        if src is not None and src.shape is not None and len(src.shape) == 4:
            shp = tuple(src.shape[p] for p in perm)
        suffix = "nhwc" if to_nhwc else "nchw"
        out = block.create_var(
            name=unique_name.generate(f"{name}.{suffix}"), shape=shp,
            dtype=getattr(src, "dtype", "float32"))
        xshape = block.create_var(
            name=unique_name.generate(f"{name}.{suffix}.xshape"),
            shape=(0,), dtype=getattr(src, "dtype", "float32"),
            stop_gradient=True)
        from ..core.framework import Operator

        top = Operator(block, "transpose2",
                       attrs={"axis": list(perm)})
        top.inputs = {"X": [name]}
        top.outputs = {"Out": [out.name], "XShape": [xshape.name]}
        new_ops.append(top)
        return out.name

    def _ensure(name, want_nhwc):
        """Return a var name holding `name`'s value in the wanted
        layout, inserting (and memoizing) a transpose when needed."""
        if (name in nhwc) == want_nhwc:
            return name
        key = (name, want_nhwc)
        if key not in trans_cache:
            trans_cache[key] = _transpose(name, to_nhwc=want_nhwc)
        return trans_cache[key]

    for op in block.ops:
        t = op.type
        if t in _FLIPPABLE:
            attr_name, in_slot, out_slot = _FLIPPABLE[t]
            xname = op.inputs.get(in_slot, [None])[0]
            cur = op.attrs.get(attr_name, "NCHW")
            if cur != "NCHW" or xname is None or not (
                    _is4d(block, xname) or xname in nhwc):
                new_ops.append(op)
                continue
            op.inputs[in_slot] = [_ensure(xname, True)] + \
                op.inputs[in_slot][1:]
            op.attrs[attr_name] = "NHWC"
            flipped += 1
            for oname in op.outputs.get(out_slot, []):
                nhwc.add(oname)
                _permute_meta(oname)
            new_ops.append(op)
        elif t in _UNARY_PASS:
            xname = op.inputs.get("X", [None])[0]
            if xname in nhwc:
                # unary ops preserve shape, so a channels-last input
                # makes EVERY output channels-last at runtime — mark
                # them even when shape metadata is missing (shape None
                # left an unmarked-NHWC var that downstream anchors
                # consumed as NCHW; round-4 advisor finding)
                for names in op.outputs.values():
                    for oname in names:
                        nhwc.add(oname)
                        _permute_meta(oname)
            new_ops.append(op)
        elif t in _EW_PASS:
            xs = op.inputs.get("X", [])
            ys = op.inputs.get("Y", [])
            four_d = [n for n in xs + ys
                      if _is4d(block, n) or n in nhwc]

            def _rank(n):
                v = block.vars.get(n)
                return (len(v.shape) if v is not None and v.shape is not None
                        else None)

            # only two broadcast shapes are relayout-safe: both
            # operands 4D (same layout flip) or a [C] Y at axis=1
            # (channel axis moves 1 -> 3). Anything else — [C,H,W] at
            # axis=1, [H,W] at axis=2, unknown ranks — falls through
            # to the anchor path below (restore NCHW) instead of
            # silently miscompiling the broadcast.
            y_ok = (not ys or ys[0] in four_d
                    or (_rank(ys[0]) == 1
                        and int(op.attrs.get("axis", -1)) == 1))
            if any(n in nhwc for n in four_d) and y_ok:
                op.inputs["X"] = [
                    _ensure(n, True) if (n in four_d or n in nhwc) else n
                    for n in xs]
                op.inputs["Y"] = [
                    _ensure(n, True) if (n in four_d or n in nhwc) else n
                    for n in ys]
                # [C] bias broadcast into the channel axis moves 1 -> 3
                if int(op.attrs.get("axis", -1)) == 1 and ys and \
                        not _is4d(block, ys[0]) and ys[0] not in nhwc:
                    op.attrs["axis"] = 3
                for names in op.outputs.values():
                    for oname in names:
                        nhwc.add(oname)
                        _permute_meta(oname)
                new_ops.append(op)
            else:
                for slot, names in op.inputs.items():
                    op.inputs[slot] = [
                        _ensure(n, False) if n in nhwc else n
                        for n in names]
                new_ops.append(op)
        else:
            # anchor op: restore NCHW for any region input it consumes
            for slot, names in op.inputs.items():
                op.inputs[slot] = [
                    _ensure(n, False) if n in nhwc else n for n in names]
            new_ops.append(op)

    block.ops = new_ops
    program.version += 1
    return flipped

"""Collective transpilers.

Reference: transpiler/collective.py:36 (Collective base), :178
(GradAllReduce — insert c_allreduce_sum after each grad), :270
(LocalSGD — local steps + periodic param averaging), :377
(SingleProcessMultiThread).

TPU-native: the op insertion is kept (ops lower to named-axis lax
collectives / identity under GSPMD), but the heavy lifting — actually
averaging gradients across devices — is done by the mesh sharding the
program runs under, so these transpilers mainly annotate.
"""

from __future__ import annotations

from ..core.framework import OpRole, Program


class Collective:
    def __init__(self, nrings: int = 1):
        self.nrings = nrings

    def transpile(self, startup_program, main_program, rank, endpoints,
                  current_endpoint, wait_port=True):
        self.rank = rank
        self.endpoints = endpoints if isinstance(endpoints, list) else endpoints.split(",")
        self.nranks = len(self.endpoints)
        self.startup_program = startup_program or Program()
        self.main_program = main_program or Program()
        self._transpile_startup_program()
        self._transpile_main_program()
        # nrings is part of the plan: the collective-safety analysis
        # pass (PTL072) checks every collective's ring_id against the
        # rings the plan actually initializes
        self.main_program._dist_plan = {
            "mode": "collective", "trainer_id": rank, "trainers": self.nranks,
            "nrings": self.nrings,
        }

    def _transpile_startup_program(self):
        # reference inserts c_gen_nccl_id + c_comm_init per ring
        # (collective.py:99-131); both lower to no-ops (rendezvous is
        # jax.distributed) but are kept for program-dump parity
        block = self.startup_program.global_block()
        for ring_id in range(self.nrings):
            block.append_op(
                type="c_comm_init",
                attrs={"ring_id": ring_id, "nranks": self.nranks, "rank": self.rank},
            )
        self.startup_program._bump()

    def _transpile_main_program(self):
        pass


class GradAllReduce(Collective):
    """Reference collective.py:178."""

    def _transpile_main_program(self):
        from ..core.framework import Parameter

        block = self.main_program.global_block()

        def is_param_grad(n):
            # ONLY parameter grads are averaged (reference keys on
            # op_role_var param/grad pairs, collective.py:196);
            # averaging intermediate activation grads would corrupt
            # the earlier layers' chain rule
            if not n.endswith("@GRAD"):
                return False
            base = n[: -len("@GRAD")]
            v = block.vars.get(base)
            return isinstance(v, Parameter) and v.trainable

        # param grads that a later Backward `sum` op re-produces (the
        # rename-and-sum scheme for multi-consumer params): allreduce
        # only after the final sum, not after every partial
        summed_later = {
            n
            for op in block.ops
            if op.type == "sum" and int(op.attrs.get("op_role", 0)) & OpRole.Backward
            for names in op.outputs.values()
            for n in names
            if n.endswith("@GRAD")
        }
        new_ops = []
        ring = 0
        for op in block.ops:
            new_ops.append(op)
            # "sum" included: multi-consumer params get their final
            # @GRAD from the rename-and-sum op, not a *_grad op
            if int(op.attrs.get("op_role", 0)) & OpRole.Backward and (
                op.type.endswith("_grad") or op.type == "sum"
            ):
                for names in op.outputs.values():
                    for n in names:
                        if not is_param_grad(n):
                            continue
                        if op.type != "sum" and n in summed_later:
                            continue
                        ar = type(op)(
                            block, "c_allreduce_sum",
                            inputs={"X": [n]}, outputs={"Out": [n]},
                            attrs={"ring_id": ring % self.nrings,
                                   "op_role": OpRole.Backward},
                        )
                        new_ops.append(ar)
                        sc = type(op)(
                            block, "scale",
                            inputs={"X": [n]}, outputs={"Out": [n]},
                            attrs={"scale": 1.0 / self.nranks,
                                   "op_role": OpRole.Backward},
                        )
                        new_ops.append(sc)
                        ring += 1
        block.ops = new_ops
        self.main_program._bump()


class LocalSGD(Collective):
    """Reference collective.py:270 — periodic cross-replica parameter
    averaging instead of per-step grad allreduce."""

    def __init__(self, nrings: int = 1, local_steps: int = 4):
        super().__init__(nrings)
        self.local_steps = local_steps

    def _transpile_main_program(self):
        from ..layers.tensor import create_global_var
        from ..core.framework import program_guard, unique_name

        block = self.main_program.global_block()
        with program_guard(self.main_program, self.startup_program):
            step = create_global_var([1], 0, "float32", persistable=True,
                                     name=unique_name.generate("local_sgd_step"))
        block.append_op(type="increment", inputs={"X": [step.name]},
                        outputs={"Out": [step.name]},
                        attrs={"step": 1.0, "op_role": OpRole.Optimize})
        # every local_steps: param = pmean(param). The averaging is
        # emitted unconditionally (static graph) and SELECTED by a
        # where on (step mod local_steps == 0) — real gating, not just
        # a recorded attr.
        k = float(max(self.local_steps, 1))
        for p in self.main_program.all_parameters():
            avg = block.create_var(
                name=unique_name.generate(f"{p.name}.lsgd_avg"),
                shape=p.shape, dtype=p.dtype, stop_gradient=True,
            )
            block.append_op(
                type="c_allreduce_sum", inputs={"X": [p.name]},
                outputs={"Out": [avg.name]},
                attrs={"ring_id": 0, "op_role": OpRole.Optimize},
            )
            block.append_op(
                type="scale", inputs={"X": [avg.name]}, outputs={"Out": [avg.name]},
                attrs={"scale": 1.0 / self.nranks, "op_role": OpRole.Optimize},
            )
            block.append_op(
                type="local_sgd_select",
                inputs={"Step": [step.name], "Avg": [avg.name], "Param": [p.name]},
                outputs={"Out": [p.name]},
                attrs={"every": k, "op_role": OpRole.Optimize},
            )
        self.main_program._bump()


class SingleProcessMultiThread(GradAllReduce):
    """Reference collective.py:377 — single process driving all local
    devices: exactly the pjit/mesh default, so only the annotation
    remains."""

    def _transpile_startup_program(self):
        pass

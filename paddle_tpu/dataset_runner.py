"""Dataset-path training driver (reference Executor.train_from_dataset
-> MultiTrainer/HogwildWorker, framework/multi_trainer.cc:157,
framework/hogwild_worker.cc).

thread <= 1: batches funnel through the single compiled step — device
parallelism comes from the mesh, not host threads. thread > 1: real
HogwildWorker semantics — N host threads pull batches from one channel
and run the SAME compiled step against the SHARED scope without
synchronization (lock-free updates; last writer wins per step, exactly
the reference's trade). Buffer donation is disabled on this path: two
in-flight steps would otherwise alias-donate the same param buffers.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Optional

# status lines keep off stdout — a serving process or pipe-reading tool
# shares this process's stdout (observability PR: library paths log)
_log = logging.getLogger("paddle_tpu.dataset")


def run_from_dataset(
    executor,
    program,
    dataset,
    scope,
    fetch_list=None,
    fetch_info=None,
    print_period=100,
    train=True,
    thread=0,
):
    if dataset is None:
        raise ValueError("dataset is required")
    fetch_list = fetch_list or []
    fetch_info = fetch_info or [v.name if hasattr(v, "name") else str(v) for v in fetch_list]
    if thread and thread > 1:
        return _run_hogwild(
            executor, program, dataset, scope, fetch_list, fetch_info,
            print_period, int(thread),
        )
    step = 0
    results = None
    for batch in dataset._iter_batches():
        results = executor.run(
            program=program,
            feed=batch,
            fetch_list=fetch_list,
            scope=scope,
        )
        if fetch_list and step % print_period == 0:
            msgs = ", ".join(
                f"{n}={float(r.reshape(-1)[0]):.6f}" for n, r in zip(fetch_info, results)
            )
            _log.info("[dataset] step %d: %s", step, msgs)
        step += 1
    return results


def _run_hogwild(executor, program, dataset, scope, fetch_list, fetch_info,
                 print_period, n_threads):
    from .core.executor import Executor

    # dedicated executor with donation off (shared params, concurrent
    # steps) — cached on the caller so repeated epochs reuse compiled
    # steps instead of recompiling per call
    exe = getattr(executor, "_hogwild_exe", None)
    if exe is None:
        exe = Executor(executor.place)
        exe.disable_donation = True
        executor._hogwild_exe = exe

    channel: "queue.Queue" = queue.Queue(maxsize=2 * n_threads)
    stop = object()
    errors = []
    last = [None]
    counter = [0]
    lock = threading.Lock()

    def worker(tid):
        try:
            while True:
                b = channel.get()
                if b is stop:
                    return
                r = exe.run(program=program, feed=b, fetch_list=fetch_list,
                            scope=scope)
                with lock:
                    counter[0] += 1
                    last[0] = r
                    step = counter[0]
                if fetch_list and step % print_period == 0:
                    msgs = ", ".join(
                        f"{n}={float(v.reshape(-1)[0]):.6f}"
                        for n, v in zip(fetch_info, r)
                    )
                    _log.info("[dataset hogwild t%d] step %d: %s",
                              tid, step, msgs)
        except BaseException as e:
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    try:
        for batch in dataset._iter_batches():
            # timed put + liveness check: if every worker died on an
            # error the bounded queue would otherwise block us forever
            while True:
                if errors or not any(t.is_alive() for t in threads):
                    break
                try:
                    channel.put(batch, timeout=1.0)
                    break
                except queue.Full:
                    continue
            if errors or not any(t.is_alive() for t in threads):
                break
    finally:
        # always deliver ALL sentinels, even when the dataset iterator
        # raises — a worker left without one blocks on channel.get
        # forever and keeps mutating the shared scope. Queued REAL
        # batches are only dropped on the error path (workers dead or
        # wedged); on a normal epoch end we wait for them to drain.
        for _ in threads:
            attempts = 0
            while True:
                try:
                    channel.put(stop, timeout=1.0)
                    break
                except queue.Full:
                    attempts += 1
                    # drop queued batches when workers are dead, erroring,
                    # or wedged past a deadline — never hang forever
                    if (errors or attempts > 120
                            or not any(t.is_alive() for t in threads)):
                        try:
                            channel.get_nowait()  # make room: abandon run
                        except queue.Empty:
                            pass
        for t in threads:
            t.join(timeout=120.0)
    if errors:
        raise errors[0]
    return last[0]

"""Dataset-path training driver (reference Executor.train_from_dataset
-> MultiTrainer/HogwildWorker, framework/multi_trainer.cc:157).

The reference runs per-thread hogwild workers over DataFeed channels
with no Python in the loop. The TPU equivalent keeps the data pipeline
multi-threaded on host (dataset.py readers) but funnels batches through
the single compiled train step — device parallelism comes from the
mesh, not host threads.
"""

from __future__ import annotations

from typing import Optional


def run_from_dataset(
    executor,
    program,
    dataset,
    scope,
    fetch_list=None,
    fetch_info=None,
    print_period=100,
    train=True,
):
    if dataset is None:
        raise ValueError("dataset is required")
    fetch_list = fetch_list or []
    fetch_info = fetch_info or [v.name if hasattr(v, "name") else str(v) for v in fetch_list]
    step = 0
    results = None
    for batch in dataset._iter_batches():
        results = executor.run(
            program=program,
            feed=batch,
            fetch_list=fetch_list,
            scope=scope,
        )
        if fetch_list and step % print_period == 0:
            msgs = ", ".join(
                f"{n}={float(r.reshape(-1)[0]):.6f}" for n, r in zip(fetch_info, results)
            )
            print(f"[dataset] step {step}: {msgs}")
        step += 1
    return results

"""Checkpoint cadence + atomic commit + retention for supervised runs.

The commit protocol (the part a crash can never corrupt):

1. persistables are saved into a STAGING directory
   (``<dir>/.staging.<step>.<pid>``) through the existing orbax path
   (io.save_checkpoint), which stamps the commit marker — a manifest of
   every file plus the supervisor's resume metadata — as its last
   write;
2. the staging dir is published as ``<dir>/<step>`` via
   ``LocalFS.atomic_rename`` (os.replace + parent-dir fsync), so
   ``io.latest_checkpoint`` observes either nothing or a complete,
   committed checkpoint;
3. retention GC then deletes committed checkpoints beyond ``keep_last``
   (newest kept) and any stale staging dirs a previous crash left
   behind.

A checkpoint directory name is the number of COMPLETED steps — i.e.
the step index the resumed run starts at.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional, Tuple

from .. import io
from ..fs import LocalFS

__all__ = ["CheckpointPolicy"]

_STAGING_PREFIX = ".staging."


class CheckpointPolicy:
    """every-N-steps / every-T-seconds cadence + keep_last retention.

    ``every_steps`` / ``every_secs`` / ``keep_last`` default from the
    ``resilience_*`` flags; 0 disables that trigger (both disabled =
    only final/preemption flushes are written).
    """

    def __init__(self, dirname: str, every_steps: Optional[int] = None,
                 every_secs: Optional[float] = None,
                 keep_last: Optional[int] = None):
        from ..flags import flag

        self.dirname = os.path.abspath(dirname)
        self.every_steps = int(
            flag("resilience_ckpt_every_steps")
            if every_steps is None else every_steps)
        self.every_secs = float(
            flag("resilience_ckpt_every_secs")
            if every_secs is None else every_secs)
        self.keep_last = int(
            flag("resilience_keep_last") if keep_last is None else keep_last)
        self._fs = LocalFS()
        self._last_save_time = time.time()
        self._last_saved_step: Optional[int] = None

    # -- cadence ------------------------------------------------------------
    def should_save(self, completed_steps: int) -> bool:
        if completed_steps == self._last_saved_step:
            return False
        if self.every_steps > 0 and completed_steps > 0 \
                and completed_steps % self.every_steps == 0:
            return True
        if self.every_secs > 0 \
                and time.time() - self._last_save_time >= self.every_secs:
            return True
        return False

    # -- commit -------------------------------------------------------------
    def save(self, completed_steps: int, main_program=None, scope=None,
             extra: Optional[Dict[str, Any]] = None) -> str:
        """Atomically commit a checkpoint for ``completed_steps`` and
        run retention GC. Returns the committed directory.

        Multi-host: every rank saves into ONE shared staging directory
        (``.staging.<step>.shared`` — the pid suffix would split the
        world across directories); io.save_checkpoint runs the
        two-phase shard-done/marker protocol inside it, and process 0
        alone publishes (atomic_rename) and GCs. Non-zero ranks return
        once they have SEEN the commit marker — a collective save, like
        every multi-host checkpoint format's."""
        step = int(completed_steps)
        rank, world = io._dist_info()
        staging = os.path.join(
            self.dirname,
            f"{_STAGING_PREFIX}{step}."
            f"{'shared' if world > 1 else os.getpid()}")
        final = os.path.join(self.dirname, str(step))
        meta = {"step": step}
        meta.update(extra or {})
        if self._same_trajectory_commit(final, meta):
            # a committed dir for this step already exists AND its
            # resume metadata (run counter, seed, step) matches ours —
            # i.e. a post-rollback replay re-reached a cadence point,
            # where the replay is bit-exact and the content identical.
            # Skipping avoids moving a live committed checkpoint aside.
            # A mismatching commit is a FOREIGN run's (reused dir):
            # fall through and replace it with this run's state.
            # (Multi-host: the metadata is deterministic-identical
            # across ranks, so every rank takes this branch together.)
            self._last_save_time = time.time()
            self._last_saved_step = step
            if rank == 0:
                self.gc()
            return final
        self._fs.mkdirs(self.dirname)
        if world == 1:
            self._fs.delete(staging)
        # multi-host: deleting the SHARED staging here would race the
        # other ranks' writes — io's stage-ready handshake (rank 0
        # clears debris, then posts the attempt token) owns cleanup
        io.save_checkpoint(staging, main_program=main_program, scope=scope,
                           extra=meta, publish_path=final)
        if rank == 0:
            # dst, if present, is an uncommitted leftover or a foreign
            # run's commit (checked above) — atomic_rename's aside
            # protocol replaces it with the narrowest possible
            # destruction window
            self._fs.atomic_rename(staging, final)
        self._last_save_time = time.time()
        self._last_saved_step = step
        if rank == 0:
            self.gc()
        return final

    @staticmethod
    def _same_trajectory_commit(path: str, meta: Dict[str, Any]) -> bool:
        """True when ``path`` holds a committed checkpoint whose resume
        metadata matches ``meta`` — the signature of a bit-exact replay
        re-committing its own step (run counter + RNG seed + step pin
        the trajectory; ``reason`` may legitimately differ)."""
        if not io.is_committed_checkpoint(path):
            return False
        existing = (io.read_commit_marker(path) or {}).get("extra", {})
        return all(existing.get(k) == v for k, v in meta.items()
                   if k != "reason")

    # -- restore ------------------------------------------------------------
    def latest(self) -> Optional[int]:
        return io.latest_checkpoint(self.dirname)

    def committed_steps(self):
        return io.committed_checkpoint_steps(self.dirname)

    def restore(self, main_program=None, scope=None,
                step: Optional[int] = None, mesh=None
                ) -> Optional[Tuple[int, Dict[str, Any]]]:
        """Load the latest (or a specific) committed checkpoint into
        ``scope``; returns (completed_steps, marker extra) or None when
        no committed checkpoint exists. ``mesh`` forwards to
        ``io.load_checkpoint``'s strict topology check (multi-host
        resume refuses a foreign-mesh trajectory by name)."""
        if step is None:
            step = self.latest()
            if step is None:
                return None
        path = os.path.join(self.dirname, str(int(step)))
        io.load_checkpoint(self.dirname, main_program=main_program,
                           scope=scope, step=step, mesh=mesh)
        marker = io.read_commit_marker(path) or {}
        return int(step), dict(marker.get("extra", {}))

    # -- retention ----------------------------------------------------------
    def gc(self) -> int:
        """Delete committed checkpoints beyond keep_last (newest kept;
        keep_last <= 0 keeps everything), uncommitted numeric dirs, and
        stale staging / rename-aside debris. Returns the number of
        directories removed.

        Foreign-pid staging dirs are only collected once older than
        ``stale_after_s`` (15 min): a second live writer sharing the
        directory — or a recycled pid — must not have its in-progress
        save deleted from under it. Single-writer-per-dir remains the
        supported deployment; the staleness window just bounds the
        damage of a violation."""
        stale_after_s = 15 * 60.0
        if not os.path.isdir(self.dirname):
            return 0

        def stale(path):
            try:
                return time.time() - os.path.getmtime(path) > stale_after_s
            except OSError:
                return False  # vanished concurrently

        removed = 0
        committed = self.committed_steps()
        drop = set(committed[:-self.keep_last]) if self.keep_last > 0 else set()
        # never collect the commit THIS policy wrote last: in a reused
        # dir, foreign higher-step commits would otherwise outrank and
        # immediately delete a fresh run's only checkpoint (the
        # foreigners get dropped progressively by later saves instead)
        drop.discard(self._last_saved_step)
        for entry in os.listdir(self.dirname):
            full = os.path.join(self.dirname, entry)
            if entry.startswith(_STAGING_PREFIX) or ".old." in entry:
                # a LIVE staging dir only exists inside save() in this
                # process (deleted/renamed before save returns); a
                # foreign-pid one that stopped changing is the debris
                # of a crashed writer. ".old." dirs are atomic_rename
                # asides a crash stranded.
                if not entry.endswith(f".{os.getpid()}") and stale(full):
                    self._fs.delete(full)
                    removed += 1
            elif entry.isdigit():
                s = int(entry)
                if s in drop or (s not in committed and stale(full)):
                    self._fs.delete(full)
                    removed += 1
        return removed

"""Fault-tolerant training: preemption-aware checkpointing, auto-
resume, retry/rollback, hang watchdog, and a deterministic chaos
harness.

The reference's only recovery story is "checkpoint restart on the same
topology"; here the training loop itself owns the fault lifecycle. A
``Supervisor`` wraps ``Executor.run``: checkpoints commit atomically
(write-to-staging + marker + rename — ``io.latest_checkpoint`` can
never observe a partial write), a killed/preempted run auto-resumes
bit-exactly (step counter, PRNG fold counter and reader position ride
in the commit marker), transient step failures retry with backoff, a
non-finite loss rolls back to the last commit and fires a user hook,
and a watchdog catches hung steps. Every path is testable on demand
through flag-gated fault injection (``resilience_fault_spec``).

    from paddle_tpu import resilience

    sup = resilience.Supervisor(
        exe, train_prog, checkpoint_dir="ckpts/run0",
        feed_fn=lambda step: make_feed(step), fetch_list=[loss])
    stats = sup.run_loop(num_steps=10_000)   # survives kill -9 restarts

Chaos-drive it: ``python tools/chaos_train.py --smoke``.
"""

from .checkpoint import CheckpointPolicy
from .faults import (KILL_EXIT_CODE, FaultInjector, FaultSpec,
                     InjectedFault, check_save_kill)
from .supervisor import NonFiniteLossError, Supervisor, WatchdogTimeout

__all__ = [
    "Supervisor",
    "CheckpointPolicy",
    "FaultSpec",
    "FaultInjector",
    "InjectedFault",
    "WatchdogTimeout",
    "NonFiniteLossError",
    "KILL_EXIT_CODE",
    "check_save_kill",
]

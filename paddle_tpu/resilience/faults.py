"""Deterministic fault injection for the training supervisor.

The whole fault lifecycle — retry, rollback, watchdog, kill/auto-resume
— is only trustworthy if it can be exercised on demand, so faults are a
first-class, flag-gated input: ``FLAGS_resilience_fault_spec`` (or the
``fault_injector`` Supervisor argument) names exactly which step each
fault fires at, and every fault is ONE-SHOT — after the supervisor
recovers (retry or rollback) the re-run of the same step proceeds
clean, which is what makes the recovered loss trajectory comparable
bitwise against an uninterrupted run.

Spec grammar (comma-separated, ``[rR:]kind@step`` with an optional
``:arg``)::

    raise@12            step 12 raises InjectedFault before running
    nan@20              step 20's fetched loss is replaced with NaN
    hang@30:2.5         step 30 sleeps 2.5s before running (watchdog bait)
    kill@40             step 40 hard-kills the process (os._exit) —
                        simulates preemption without a signal
    killsave@8          the checkpoint save following step 8 dies AFTER
                        this rank's shards are written but BEFORE its
                        shard-done file — the torn-commit scenario the
                        two-phase cross-host protocol must absorb
    r2:kill@40          rank-scoped: fires only on the process whose
                        PADDLE_TRAINER_ID is 2 — "kill exactly one
                        host of N", the dominant real failure mode
                        (entries without a rank prefix fire everywhere)
"""

from __future__ import annotations

import os
import re
import time
from typing import Any, List, Optional, Tuple

import numpy as np

__all__ = ["FaultSpec", "FaultInjector", "InjectedFault", "KILL_EXIT_CODE",
           "check_save_kill"]

# distinctive exit status so a test/driver can tell an injected kill
# from a genuine crash of the child process
KILL_EXIT_CODE = 43

_KINDS = ("raise", "nan", "hang", "kill", "killsave")

_RANK_RE = re.compile(r"^r(\d+):(.+)$")


class InjectedFault(RuntimeError):
    """The transient step failure raised by a ``raise@N`` fault."""


class FaultSpec:
    """Parsed fault plan: a list of (kind, step, arg, rank) actions
    (rank None = every rank)."""

    def __init__(self, actions: List[Tuple]):
        norm = []
        for act in actions:
            kind, step, arg = act[0], act[1], act[2]
            rank = act[3] if len(act) > 3 else None
            if kind not in _KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (expected one of {_KINDS})")
            if step < 0:
                raise ValueError(f"fault step must be >= 0, got {step}")
            if rank is not None and rank < 0:
                raise ValueError(f"fault rank must be >= 0, got {rank}")
            norm.append((kind, step, arg, rank))
        self.actions = norm

    @classmethod
    def parse(cls, spec: str) -> "FaultSpec":
        """Parse ``"raise@12,nan@20,hang@30:2.5,r1:kill@40"``."""
        actions: List[Tuple[str, int, Optional[float], Optional[int]]] = []
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            rank: Optional[int] = None
            m = _RANK_RE.match(part)
            if m:
                rank, part = int(m.group(1)), m.group(2)
            try:
                kind, rest = part.split("@", 1)
                arg: Optional[float] = None
                if ":" in rest:
                    rest, arg_s = rest.split(":", 1)
                    arg = float(arg_s)
                actions.append((kind.strip(), int(rest), arg, rank))
            except ValueError as e:
                raise ValueError(
                    f"bad fault spec entry {part!r} (grammar: [rN:]kind@step"
                    f"[:arg], kinds {_KINDS}): {e}"
                ) from None
        return cls(actions)

    def __bool__(self):
        return bool(self.actions)


# one-shot flag set by an armed ``killsave`` fault and consumed by the
# checkpoint writer (io.py) at its pre-done-file injection point — this
# is how "a host dies mid-save, after its data but before its
# done-file" is simulated deterministically
_SAVE_KILL_ARMED = {"on": False}


def check_save_kill(point: str = "before_shard_done") -> None:
    """Called by the checkpoint writer at its injection points; a
    pending ``killsave`` fault hard-kills the process here (after the
    shard data landed, before the done-file), leaving a torn save the
    two-phase commit must never publish."""
    if _SAVE_KILL_ARMED["on"] and point == "before_shard_done":
        _SAVE_KILL_ARMED["on"] = False
        os._exit(KILL_EXIT_CODE)


class FaultInjector:
    """Applies a FaultSpec around each supervised step, one shot per
    action. ``before_step`` runs where the step would (raise / hang /
    kill, and arms a pending killsave); ``after_step`` poisons the
    fetched loss (nan). Rank-scoped entries (``rN:``) only fire on the
    process whose rank (``PADDLE_TRAINER_ID``, or the ``rank=``
    argument) matches — on every other rank they are dropped at
    construction and never reported by ``fired()``."""

    def __init__(self, spec: Optional[FaultSpec] = None,
                 rank: Optional[int] = None):
        if isinstance(spec, str):
            spec = FaultSpec.parse(spec)
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", "0")
                        if rank is None else rank)
        self.spec = spec or FaultSpec([])
        # rank filter applied once: foreign-rank entries are not "ours"
        self.spec = FaultSpec([
            a for a in self.spec.actions
            if a[3] is None or a[3] == self.rank
        ])
        self._fired: List[Tuple[str, int]] = []

    @classmethod
    def from_flags(cls) -> "FaultInjector":
        from ..flags import flag

        return cls(FaultSpec.parse(flag("resilience_fault_spec")))

    _NOT_PENDING = object()

    def _take(self, kind: str, step: int):
        """Pop the pending action (kind, step) and return its arg
        (None when the spec gave no ``:arg``) — one-shot. Returns the
        ``_NOT_PENDING`` sentinel when no such action is pending, so an
        explicit ``:0`` arg stays distinguishable from "absent"."""
        for i, (k, s, arg, _rank) in enumerate(self.spec.actions):
            if k == kind and s == step:
                del self.spec.actions[i]
                self._fired.append((kind, step))
                return arg
        return self._NOT_PENDING

    def fired(self) -> List[Tuple[str, int]]:
        return list(self._fired)

    def before_step(self, step: int) -> None:
        arg = self._take("hang", step)
        if arg is not self._NOT_PENDING:
            # bare `hang@N` = hang "forever" (an hour dwarfs any
            # sane watchdog timeout); `hang@N:x` sleeps exactly x
            time.sleep(3600.0 if arg is None else arg)
        if self._take("kill", step) is not self._NOT_PENDING:
            # hard preemption: no cleanup, no atexit, no signal handler
            # — exactly what a spot-VM reclaim looks like to the child
            os._exit(KILL_EXIT_CODE)
        if self._take("killsave", step) is not self._NOT_PENDING:
            _SAVE_KILL_ARMED["on"] = True
        if self._take("raise", step) is not self._NOT_PENDING:
            raise InjectedFault(f"injected transient fault at step {step}")

    def after_step(self, step: int, fetched: List[Any], loss_index: int):
        if not fetched or loss_index >= len(fetched):
            # nothing to poison: leave the action PENDING (and
            # unreported by fired()) rather than consuming it silently
            # — a chaos run with an empty fetch_list should not claim
            # the NaN path was exercised
            return fetched
        if self._take("nan", step) is not self._NOT_PENDING:
            bad = np.asarray(fetched[loss_index], dtype=np.float32).copy()
            bad.fill(np.nan)
            fetched = list(fetched)
            fetched[loss_index] = bad
        return fetched

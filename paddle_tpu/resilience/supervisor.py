"""Fault-tolerant training supervisor: owns the step loop's whole
fault lifecycle.

``Supervisor.run_loop`` wraps ``Executor.run`` with, in order of
escalation:

* **auto-resume** — on start, the latest COMMITTED checkpoint under
  ``checkpoint_dir`` is loaded (uncommitted/truncated dirs are never
  selected — io.latest_checkpoint's commit-marker contract) and the
  loop continues from its step. Resume is BIT-EXACT: the commit marker
  carries the step counter, the Executor's run counter (the per-step
  PRNG fold key — dropout/random ops replay identically) and the
  reader position, so the recovered loss trajectory matches an
  uninterrupted run bitwise;
* **bounded retry** — a step that raises is retried with exponential
  backoff, up to ``max_retries`` times;
* **NaN/Inf loss guard** — a non-finite loss rolls the scope back to
  the last committed checkpoint (restoring the run counter too, so the
  replay stays bit-exact) and fires the ``on_nan`` hook — the place to
  drop the loss scale or LR — at most ``max_rollbacks`` times;
* **hang watchdog** — with ``watchdog_timeout_s`` > 0 each step runs
  on a persistent worker thread; a step that exceeds the timeout
  raises ``WatchdogTimeout`` in the supervisor (feeding the retry
  path) and the stuck worker is abandoned. A python thread cannot be
  killed, so if the abandoned step later UNWEDGES and completes, it
  mutates the scope behind the retry's back — the supervisor detects
  this (``stats()["zombie_steps"]``) and rolls back to the last
  commit, discarding the corruption. (Residual risk: a zombie
  completing exactly during a checkpoint save can tear that one
  commit; the manifest check rejects torn directories only when files
  are missing/resized, not same-size rewrites.);
* **preemption handling** — SIGTERM sets a flag; at the next step
  boundary a final checkpoint is flushed and the loop exits cleanly
  (``stats()["preempted"]``), so a preempted run resumes exactly where
  it stopped.

Feeds come from either ``feed_fn(step) -> dict`` (preferred: any step
is re-derivable, rollback replays for free) or a ``data`` iterable —
a ``GeneratorLoader`` is fast-forwarded on resume via its resumable
position, and feeds consumed since the last checkpoint are buffered so
rollback can replay them.

Checkpoint save/restore paths are wrapped in structured
``observability.tracing`` spans (``resilience/checkpoint`` etc. —
plain ``profiler.record_event`` ranges when tracing is off) so they
show up, with step/path metadata and trace parentage, in timeline
traces. Every fault-lifecycle event (retry, rollback, NaN, watchdog,
zombie) also lands in the crash-time flight recorder, and the recorder
dumps a JSON snapshot on NaN rollback, watchdog hang, any exception
that escapes the loop, and the SIGTERM preemption flush
(``stats()["flight_dumps"]`` lists the paths).
"""

from __future__ import annotations

import os
import queue
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..observability import flight, tracing
from .checkpoint import CheckpointPolicy
from .faults import FaultInjector

__all__ = ["Supervisor", "WatchdogTimeout", "NonFiniteLossError"]


class WatchdogTimeout(RuntimeError):
    """A supervised step exceeded the watchdog timeout."""


class NonFiniteLossError(RuntimeError):
    """The NaN/Inf loss guard tripped and no recovery was possible."""


class _StepWorker:
    """Persistent worker thread the watchdog path runs steps on (a
    thread per step would cost ~100us/step; two queue hops cost ~10us).
    On timeout the worker is abandoned — its in-flight result is
    discarded via the cancellation token — and the next step gets a
    fresh worker."""

    def __init__(self):
        self._req: "queue.Queue" = queue.Queue(1)
        self._resp: "queue.Queue" = queue.Queue(1)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            fn, token = self._req.get()
            if fn is None:
                return
            try:
                out = ("ok", fn(token))
            except BaseException as e:  # noqa: BLE001 — marshalled to caller
                out = ("err", e)
            finally:
                # visible to the supervisor even after abandonment: an
                # orphaned step that eventually COMPLETED has mutated
                # the scope behind the retry's back (zombie detection)
                token["finished"] = True
            if not token["cancelled"]:
                self._resp.put(out)

    def call(self, fn, timeout: float):
        token = {"cancelled": False, "finished": False, "ran": False}
        self._req.put((fn, token))
        try:
            kind, val = self._resp.get(timeout=timeout)
        except queue.Empty:
            token["cancelled"] = True
            err = WatchdogTimeout(
                f"step exceeded watchdog timeout of {timeout}s; worker "
                "thread abandoned")
            err.token = token
            raise err from None
        if kind == "err":
            raise val
        return val

    def stop(self):
        try:
            self._req.put_nowait((None, {"cancelled": True}))
        except queue.Full:
            pass  # worker is wedged mid-step; it is a daemon thread


class Supervisor:
    """Wraps an Executor's step loop with the full fault lifecycle.

    Minimal usage::

        sup = resilience.Supervisor(
            exe, train_prog, checkpoint_dir="ckpts/run0",
            feed_fn=lambda step: feeds[step % len(feeds)],
            fetch_list=[loss])
        stats = sup.run_loop(num_steps=1000)

    ``program`` may be a Program or CompiledProgram (checkpointing uses
    the underlying main Program's persistables either way). The first
    entry of ``fetch_list`` is the loss the NaN/Inf guard watches
    (``loss_index`` overrides).
    """

    def __init__(self, exe, program, checkpoint_dir: str,
                 feed_fn: Optional[Callable[[int], Dict[str, Any]]] = None,
                 data=None, fetch_list=None, loss_index: int = 0,
                 scope=None, policy: Optional[CheckpointPolicy] = None,
                 max_retries: Optional[int] = None,
                 retry_backoff_s: Optional[float] = None,
                 max_rollbacks: Optional[int] = None,
                 watchdog_timeout_s: Optional[float] = None,
                 fault_injector: Optional[FaultInjector] = None,
                 on_step: Optional[Callable[[int, List[Any]], None]] = None,
                 on_nan: Optional[Callable[[int, float], None]] = None,
                 on_retry: Optional[Callable[[int, BaseException], None]] = None,
                 on_checkpoint: Optional[Callable[[int, str], None]] = None):
        from ..core.executor import global_scope
        from ..flags import flag

        if (feed_fn is None) == (data is None):
            raise ValueError(
                "Supervisor needs exactly one feed source: feed_fn(step) "
                "OR a data iterable")
        self.exe = exe
        self.program = program
        # CompiledProgram wraps the Program whose persistables we save
        self._main = getattr(program, "_program", program)
        self.feed_fn = feed_fn
        self.data = data
        self.fetch_list = list(fetch_list or [])
        self.loss_index = loss_index
        self.scope = scope or global_scope()
        self.policy = policy or CheckpointPolicy(checkpoint_dir)
        if policy is not None and checkpoint_dir and \
                os.path.abspath(checkpoint_dir) != policy.dirname:
            raise ValueError("checkpoint_dir disagrees with policy.dirname")
        self.max_retries = int(
            flag("resilience_max_retries") if max_retries is None
            else max_retries)
        self.retry_backoff_s = float(
            flag("resilience_retry_backoff_s") if retry_backoff_s is None
            else retry_backoff_s)
        self.max_rollbacks = int(
            flag("resilience_max_rollbacks") if max_rollbacks is None
            else max_rollbacks)
        self.watchdog_timeout_s = float(
            flag("resilience_watchdog_timeout_s") if watchdog_timeout_s is None
            else watchdog_timeout_s)
        self.fault = fault_injector or FaultInjector.from_flags()
        self.on_step = on_step
        self.on_nan = on_nan
        self.on_retry = on_retry
        self.on_checkpoint = on_checkpoint
        self._worker: Optional[_StepWorker] = None
        self._preempted = threading.Event()
        self._data_iter = None
        self._replay: Dict[int, Dict[str, Any]] = {}
        self._data_consumed = 0  # next fresh index the iterator serves
        # rollback can only target a committed checkpoint, so feeds are
        # buffered only once one exists AND the cadence keeps creating
        # pruning points (each commit drops everything before it) —
        # bounded by the checkpoint cadence. With the cadence disabled
        # nothing is buffered, and a rollback that would need an
        # unbuffered feed fails loudly instead of silently feeding the
        # wrong batch (use feed_fn for unbounded replay).
        self._last_commit_step: Optional[int] = None
        self._abandoned: List[Dict[str, Any]] = []  # watchdog-orphaned tokens
        self._data_exhausted = False
        self._flight_dumps: List[str] = []
        self._stats: Dict[str, Any] = {
            "steps_completed": 0,
            "checkpoints_written": 0,
            "checkpoints_loaded": 0,
            "retries": 0,
            "rollbacks": 0,
            "watchdog_fires": 0,
            "zombie_steps": 0,
            "nan_events": 0,
            "faults_injected": 0,
            "preempted": False,
            "resumed_from": None,
        }
        # unified registry: counters export as paddle_resilience_*
        from ..observability import watch_supervisor

        watch_supervisor(self)

    # -- introspection ------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Counter snapshot (copies; safe to mutate)."""
        out = dict(self._stats)
        out["faults_injected"] = len(self.fault.fired())
        out["flight_dumps"] = list(self._flight_dumps)
        return out

    def _flight_dump(self, reason: str, **extra) -> None:
        path = flight.dump(reason, extra=extra or None)
        if path is not None:
            self._flight_dumps.append(path)

    def request_preempt(self):
        """What the SIGTERM handler does — callable directly (tests,
        external schedulers): flush a final checkpoint at the next step
        boundary and exit the loop cleanly."""
        self._preempted.set()

    # -- resume -------------------------------------------------------------
    def resume(self) -> int:
        """Load the latest committed checkpoint (if any) and return the
        step index to continue from."""
        with tracing.span("resilience/restore",
                          {"dir": self.policy.dirname}):
            restored = self.policy.restore(main_program=self._main,
                                           scope=self.scope,
                                           mesh=self._strict_mesh())
        if restored is None:
            return 0
        step, extra = restored
        start = int(extra.get("step", step))
        if "run_counter" in extra:
            # the per-step PRNG key is fold_in(base, run_counter):
            # restoring it makes dropout/random ops replay bit-exactly
            self.exe._run_counter = int(extra["run_counter"])
        self._stats["checkpoints_loaded"] += 1
        self._stats["resumed_from"] = start
        self._last_commit_step = start
        if self.data is not None:
            pos = int(extra.get("reader_position", start))
            self._data_consumed = start
            if hasattr(self.data, "set_resume_position"):
                self.data.set_resume_position(pos)
            else:
                # plain iterable: fast-forward by consuming
                self._data_iter = iter(self.data)
                for _ in range(pos):
                    if next(self._data_iter, None) is None:
                        break
        return start

    def _strict_mesh(self):
        """The mesh to hold a restore to. Multi-host resume is strict —
        a trajectory committed on a foreign mesh shape must be refused
        by name, not die as a shard-count mismatch mid-assembly —
        while single-host resume stays elastic (PR-8: sharding is a
        property of the compile, any topology restores)."""
        from .. import io

        _, world = io._dist_info()
        if world <= 1:
            return None
        mesh = getattr(self.program, "_mesh", None)
        return mesh if hasattr(mesh, "shape") else None

    # -- checkpointing ------------------------------------------------------
    def _save(self, completed_steps: int, reason: str) -> str:
        # multi-host: all ranks must REACH this save point before any
        # shard write starts — a peer that died mid-step turns into one
        # bounded BarrierTimeout here (escalated below to a clean
        # restartable exit) instead of a phase-2 commit timeout minutes
        # later. The SIGTERM preemption flush gets a SHORT bound: in a
        # coordinated preemption every live rank reaches its step
        # boundary within a step time, and when a peer is already dead
        # the flush must fail before the launcher's SIGKILL grace —
        # stalling the full dist_barrier_timeout_s would turn the
        # graceful flush into a guaranteed SIGKILL.
        from ..distributed.coordinator import get_coordinator

        coord = get_coordinator()
        if coord is not None and coord.is_distributed:
            from ..flags import flag

            timeout = float(flag("dist_barrier_timeout_s"))
            if reason == "preempt":
                timeout = min(timeout, 5.0)
            coord.barrier("resilience/pre_save", timeout_s=timeout)
        extra = {
            "run_counter": int(self.exe._run_counter),
            "random_seed": int(getattr(self._main, "random_seed", 0) or 0),
            "reason": reason,
            # the loop consumes exactly one batch per step, so the
            # position a FRESH process must fast-forward to is the step
            # counter itself — NOT data.position(), which runs ahead of
            # the step during post-rollback replay (replayed feeds come
            # from the buffer while the loader's count still includes
            # the rolled-back pulls)
            "reader_position": int(completed_steps),
        }
        # mesh-bound runs stamp the mesh shape into the commit marker:
        # resume on ANY topology stays supported (arrays land as host
        # values and the next compile re-places them), but the marker
        # records which mesh produced the trajectory being resumed
        mesh = getattr(self.program, "_mesh", None)
        if mesh is not None and hasattr(mesh, "shape"):
            extra["mesh"] = {str(k): int(v)
                             for k, v in dict(mesh.shape).items()}
        from .. import io as _io

        _, world = _io._dist_info()
        if world > 1:
            # the marker records which world committed this trajectory
            # (and how many restarts deep the run was) — the restore
            # side's strict check and the chaos report both read it
            extra["world"] = world
            extra["restart_count"] = int(
                os.environ.get("PADDLE_RESTART_COUNT", "0"))
        with tracing.span(
                "resilience/checkpoint",
                {"step": completed_steps, "reason": reason}):
            path = self.policy.save(completed_steps,
                                    main_program=self._main,
                                    scope=self.scope, extra=extra)
        self._stats["checkpoints_written"] += 1
        self._last_commit_step = completed_steps
        # feeds before this point can never be replayed again
        self._replay = {s: f for s, f in self._replay.items()
                        if s >= completed_steps}
        if self.on_checkpoint is not None:
            self.on_checkpoint(completed_steps, path)
        return path

    def _rollback(self) -> Optional[int]:
        """Reload the last checkpoint THIS RUN committed or resumed
        from; returns the step to re-run from, or None when there is
        nothing to roll back to. Deliberately never "latest on disk":
        a fresh run (resume=False) pointed at a dir holding a previous
        run's commits must not silently restore foreign state."""
        if self._last_commit_step is None:
            return None
        with tracing.span("resilience/rollback",
                          {"dir": self.policy.dirname}):
            restored = self.policy.restore(main_program=self._main,
                                          scope=self.scope,
                                          step=self._last_commit_step,
                                          mesh=self._strict_mesh())
        if restored is None:
            return None
        step, extra = restored
        if "run_counter" in extra:
            self.exe._run_counter = int(extra["run_counter"])
        self._stats["checkpoints_loaded"] += 1
        self._stats["rollbacks"] += 1
        self._last_commit_step = int(extra.get("step", step))
        flight.note("event", what="rollback",
                    to_step=self._last_commit_step)
        return self._last_commit_step

    # -- feeds --------------------------------------------------------------
    def _feed_for(self, step: int) -> Optional[Dict[str, Any]]:
        if self.feed_fn is not None:
            return self.feed_fn(step)
        if step in self._replay:
            return self._replay[step]
        if step < self._data_consumed:
            # rollback reached a step whose feed was never buffered
            # (cadence disabled) — pulling the iterator here would
            # silently train on the WRONG batch
            raise RuntimeError(
                f"cannot replay step {step}: its feed is no longer "
                "available from the data iterator — enable a checkpoint "
                "cadence (which bounds the replay buffer) or supply "
                "feed_fn(step) so any step is re-derivable")
        if self._data_iter is None:
            self._data_iter = iter(self.data)
        try:
            feed = next(self._data_iter)
        except StopIteration:
            self._data_exhausted = True
            return None
        self._data_consumed = step + 1
        # buffer until the next checkpoint commits: rollback re-runs
        # these steps and an iterator cannot rewind. Before the first
        # commit there is nothing to roll back TO, and without a
        # cadence there is no pruning point — in both cases nothing is
        # buffered, keeping the buffer bounded by the cadence.
        if self._last_commit_step is not None and (
                self.policy.every_steps > 0 or self.policy.every_secs > 0):
            self._replay[step] = feed
        return feed

    # -- the step itself ----------------------------------------------------
    def _run_step(self, step: int, feed: Dict[str, Any]) -> List[Any]:
        def attempt(token=None):
            self.fault.before_step(step)
            if token is not None and token["cancelled"]:
                # the watchdog already gave up on this attempt (the
                # fault hang outlived the timeout); running the step
                # now would mutate the scope behind the retry's back
                return None
            if token is not None:
                # state mutation starts here: only attempts that got
                # this far count as zombies if abandoned (a cancelled
                # attempt that parked above never touched the scope)
                token["ran"] = True
            return self.exe.run(self.program, feed=feed,
                                fetch_list=self.fetch_list,
                                scope=self.scope)

        if self.watchdog_timeout_s > 0:
            if self._worker is None:
                self._worker = _StepWorker()
            try:
                out = self._worker.call(attempt, self.watchdog_timeout_s)
            except WatchdogTimeout as e:
                self._stats["watchdog_fires"] += 1
                self._worker = None  # abandoned; next attempt gets a fresh one
                token = getattr(e, "token", None)
                if token is not None:
                    self._abandoned.append(token)
                flight.note("event", what="watchdog_fire", step=step,
                            timeout_s=self.watchdog_timeout_s)
                self._flight_dump("watchdog_hang", step=step,
                                  timeout_s=self.watchdog_timeout_s)
                raise
            if out is None:
                raise WatchdogTimeout("step cancelled by watchdog")
            return out
        return attempt()

    def _zombie_completed(self) -> bool:
        """True when a watchdog-abandoned step has since COMPLETED —
        its exe.run mutated the scope (and bumped the run counter)
        behind the retry's back, so the live state can no longer be
        trusted and the caller must roll back to the last commit.
        Tokens that finish WITHOUT having reached exe.run (parked in
        the cancellation check before it) never touched the scope —
        they are discarded, not treated as corruption. Tokens whose
        step never finishes (hung forever) stay pending and are
        harmless."""
        finished = [t for t in self._abandoned if t.get("finished")]
        if not finished:
            return False
        self._abandoned = [t for t in self._abandoned
                           if not t.get("finished")]
        zombies = [t for t in finished if t.get("ran")]
        self._stats["zombie_steps"] += len(zombies)
        return bool(zombies)

    def _absorb_zombies(self) -> Optional[int]:
        """Checked at every point that trusts the live scope (loop top,
        and immediately BEFORE every checkpoint save — committing
        zombie-corrupted state would poison the very checkpoint a later
        rollback restores). Returns the step to re-run from after
        rolling back, or None when the state is clean."""
        if not self._abandoned or not self._zombie_completed():
            return None
        rolled = self._rollback()
        if rolled is None:
            raise WatchdogTimeout(
                "a watchdog-abandoned step completed after its timeout "
                "and mutated training state, and no committed checkpoint "
                "exists to restore from")
        return rolled

    # -- the loop -----------------------------------------------------------
    def run_loop(self, num_steps: int, resume: bool = True,
                 final_checkpoint: bool = True) -> Dict[str, Any]:
        """Run (up to) ``num_steps`` supervised steps; returns
        ``stats()``. Safe to call again after a clean exit."""
        old_handler = None
        # a preempt flag from a PREVIOUS run_loop (external
        # request_preempt that was then rescinded) must not wedge this
        # call into flushing 0 steps forever. Cleared BEFORE the
        # handler installs so a SIGTERM landing in between is kept.
        self._preempted.clear()
        in_main = threading.current_thread() is threading.main_thread()
        # cleared BEFORE the handler installs (same discipline as
        # _preempted above): a SIGTERM landing mid-install must keep
        # its dump request, not have it wiped by a late reset
        self._dump_on_preempt = False
        if in_main:
            def _on_sigterm(signum, frame):
                # flag-set ONLY: the handler runs on the main thread,
                # which may hold the flight/telemetry locks mid-step —
                # dumping here would self-deadlock on those
                # non-reentrant locks. The loop body dumps at the next
                # step boundary (safe context) before the flush.
                self._dump_on_preempt = True
                self.request_preempt()

            old_handler = signal.signal(signal.SIGTERM, _on_sigterm)
        try:
            step = self.resume() if resume else 0
            rollbacks_left = self.max_rollbacks
            while True:
                # zombie absorption comes before ANYTHING that trusts
                # or commits the live state; a rollback re-enters the
                # loop so the discarded tail steps are re-run
                rolled = self._absorb_zombies()
                if rolled is not None:
                    step = rolled
                    continue
                if step >= num_steps:
                    # end of budget. step == num_steps guards the
                    # resumed-past-the-budget case (resume() beyond
                    # num_steps): saving there would label later-step
                    # state with num_steps metadata
                    if final_checkpoint and step == num_steps and \
                            self.policy._last_saved_step != num_steps:
                        self._save(num_steps, reason="final")
                    break
                if self._preempted.is_set():
                    self._stats["preempted"] = True
                    if getattr(self, "_dump_on_preempt", False):
                        # evidence of what was in flight when the
                        # reclaim landed, captured BEFORE the flush
                        self._flight_dump("sigterm", step=step)
                    if final_checkpoint:
                        # best-effort in a multi-host teardown: when a
                        # peer is already dead the flush CANNOT commit
                        # (two-phase needs every rank) — exit cleanly
                        # on the last committed checkpoint instead of
                        # stalling into the launcher's SIGKILL
                        try:
                            self._save(step, reason="preempt")
                        except BaseException as e:  # noqa: BLE001
                            from .. import io as _io
                            from ..distributed.coordinator import \
                                BarrierTimeout

                            if not isinstance(
                                    e, (BarrierTimeout,
                                        _io.CheckpointCommitTimeout)):
                                raise
                            self._stats["preempt_flush_failed"] = True
                            flight.note(
                                "event", what="preempt_flush_failed",
                                step=step, error=repr(e))
                    break
                feed = self._feed_for(step)
                if feed is None:
                    # data exhausted: flush what was actually reached
                    if final_checkpoint and \
                            self.policy._last_saved_step != step:
                        self._save(step, reason="final")
                    break
                fetched, nan_loss = self._attempt(step, feed,
                                                  rollbacks_left)
                if nan_loss is not None:
                    # the NaN guard tripped with rollback budget left:
                    # restore OUTSIDE the retry try/except — a failing
                    # restore must propagate, not be retried as a
                    # transient step fault. The flight dump happens
                    # BEFORE the rollback: the evidence of interest is
                    # the state that produced the NaN, not the restored
                    # one.
                    flight.note("event", what="nan_loss", step=step,
                                loss=repr(nan_loss))
                    self._flight_dump("nan_rollback", step=step,
                                      loss=repr(nan_loss))
                    if self.on_nan is not None:
                        self.on_nan(step, nan_loss)
                    rolled = self._rollback()
                    if rolled is None:
                        raise NonFiniteLossError(
                            f"loss is {nan_loss} at step {step} and no "
                            "committed checkpoint exists to roll back to")
                    rollbacks_left -= 1
                    step = rolled
                    continue
                self._stats["steps_completed"] += 1
                if self.on_step is not None:
                    self.on_step(step, fetched)
                step += 1
                if self.policy.should_save(step):
                    # a zombie completing DURING the step just run must
                    # not be committed — absorb before the save
                    rolled = self._absorb_zombies()
                    if rolled is not None:
                        step = rolled
                        continue
                    self._save(step, reason="policy")
            return self.stats()
        except SystemExit:
            raise
        except BaseException as e:
            # an exception escaping the supervisor IS the crash the
            # flight recorder exists for: dump before propagating
            # (retryable faults never reach here — _attempt absorbed
            # them — so this fires once per terminal failure)
            self._flight_dump(f"exception:{type(e).__name__}",
                              error=repr(e))
            # multi-host: a stall (hung step under the watchdog, or a
            # coordination barrier that timed out because a peer died)
            # is not a crash to debug, it is a world to restart — exit
            # with the code the elastic launcher treats as "re-form the
            # world and auto-resume" instead of an arbitrary traceback
            # status
            from .. import io as _io
            from ..distributed.coordinator import (BarrierTimeout,
                                                   RESTART_EXIT_CODE)

            _, world = _io._dist_info()
            if world > 1 and isinstance(
                    e, (WatchdogTimeout, BarrierTimeout,
                        _io.CheckpointCommitTimeout)):
                raise SystemExit(RESTART_EXIT_CODE) from e
            raise
        finally:
            if in_main and old_handler is not None:
                signal.signal(signal.SIGTERM, old_handler)
            if self._worker is not None:
                self._worker.stop()
                self._worker = None

    def _attempt(self, step: int, feed: Dict[str, Any], rollbacks_left: int):
        """One logical step with retry handling. Returns (fetched,
        None) on success, or (None, nan_loss) when the NaN guard
        tripped and the caller should roll back (the restore itself
        happens in run_loop, outside this retry scope)."""
        attempts = 0
        while True:
            try:
                if tracing.enabled():
                    # per-attempt span: a retried step renders as two
                    # sibling ranges, each carrying its attempt index
                    with tracing.span("resilience/step",
                                      {"step": step, "attempt": attempts}):
                        fetched = self._run_step(step, feed)
                else:
                    fetched = self._run_step(step, feed)
                fetched = self.fault.after_step(step, fetched,
                                                self.loss_index)
                loss = self._loss_of(fetched)
                if loss is not None and not np.isfinite(loss):
                    self._stats["nan_events"] += 1
                    if rollbacks_left <= 0:
                        if self.on_nan is not None:
                            self.on_nan(step, loss)
                        raise NonFiniteLossError(
                            f"loss is {loss} at step {step} and the "
                            f"rollback budget ({self.max_rollbacks}) is "
                            "exhausted — the run is diverging")
                    return None, loss
                return fetched, None
            except (KeyboardInterrupt, SystemExit, NonFiniteLossError):
                raise
            except Exception as e:  # noqa: BLE001 — transient step faults
                attempts += 1
                if attempts > self.max_retries:
                    raise
                self._stats["retries"] += 1
                flight.note("event", what="retry", step=step,
                            attempt=attempts, error=repr(e))
                if self.on_retry is not None:
                    self.on_retry(step, e)
                time.sleep(self.retry_backoff_s * (2 ** (attempts - 1)))

    def _loss_of(self, fetched) -> Optional[float]:
        if not fetched or self.loss_index >= len(fetched):
            return None
        v = fetched[self.loss_index]
        try:
            return float(np.asarray(v).reshape(-1)[0])
        except (TypeError, ValueError):
            return None

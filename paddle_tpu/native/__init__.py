"""Native (C++) runtime components, loaded via ctypes.

Reference's native surface: data_feed.cc parsing, fs/shell IO,
allocators, executors. On TPU the executor/allocator roles belong to
XLA; the pieces that stay host-side native here: the datafeed parser
(and future: checkpoint packing, tokenizer).
"""

from . import datafeed

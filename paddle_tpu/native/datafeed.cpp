// Native MultiSlot text parser.
//
// Reference: paddle/fluid/framework/data_feed.cc MultiSlotDataFeed —
// C++ multi-threaded file->channel sample parsing so the training loop
// never waits on Python text parsing. Same role here: this library does
// the byte-level parsing; Python threads call it with the GIL released
// (ctypes), giving true parallel file ingest.
//
// Format per line, per slot:  <n> v1 v2 ... vn
//
// Build: g++ -O2 -shared -fPIC -o libptfeed.so datafeed.cpp

#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct SlotData {
  std::vector<int64_t> lengths;   // per sample
  std::vector<float> fvals;       // used when slot is float
  std::vector<int64_t> ivals;     // used when slot is int
  bool is_float = true;
};

struct ParseResult {
  std::vector<SlotData> slots;
  int64_t num_samples = 0;
};

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

}  // namespace

extern "C" {

void* pt_parse_file(const char* path, int num_slots,
                    const unsigned char* slot_is_float) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::string buf;
  buf.resize(size);
  if (size > 0 && std::fread(&buf[0], 1, size, f) != static_cast<size_t>(size)) {
    std::fclose(f);
    return nullptr;
  }
  std::fclose(f);

  auto* res = new ParseResult();
  res->slots.resize(num_slots);
  for (int s = 0; s < num_slots; ++s) res->slots[s].is_float = slot_is_float[s];

  char* p = buf.empty() ? nullptr : &buf[0];
  char* end = p + buf.size();
  while (p && p < end) {
    char* line_end = static_cast<char*>(memchr(p, '\n', end - p));
    bool had_nl = line_end != nullptr;
    if (!line_end) line_end = end;
    // NUL-terminate the line in place so strtof/strtoll cannot read
    // past it into the next line (silent cross-line corruption)
    char saved = *line_end;
    if (line_end < end) *line_end = '\0';
    const char* q = skip_ws(p, line_end);
    if (q < line_end) {
      bool ok = true;
      // remember sizes for exact rollback of a malformed line
      std::vector<size_t> fsz(num_slots), isz(num_slots), lsz(num_slots);
      for (int s = 0; s < num_slots; ++s) {
        fsz[s] = res->slots[s].fvals.size();
        isz[s] = res->slots[s].ivals.size();
        lsz[s] = res->slots[s].lengths.size();
      }
      for (int s = 0; s < num_slots && ok; ++s) {
        q = skip_ws(q, line_end);
        char* next = nullptr;
        long n = std::strtol(q, &next, 10);
        if (next == q || n < 0) { ok = false; break; }
        q = next;
        SlotData& sd = res->slots[s];
        sd.lengths.push_back(n);
        for (long i = 0; i < n; ++i) {
          q = skip_ws(q, line_end);
          if (sd.is_float) {
            float v = std::strtof(q, &next);
            if (next == q) { ok = false; break; }
            sd.fvals.push_back(v);
          } else {
            long long v = std::strtoll(q, &next, 10);
            if (next == q) { ok = false; break; }
            sd.ivals.push_back(v);
          }
          q = next;
        }
      }
      if (ok) {
        res->num_samples++;
      } else {
        for (int s = 0; s < num_slots; ++s) {
          SlotData& sd = res->slots[s];
          sd.fvals.resize(fsz[s]);
          sd.ivals.resize(isz[s]);
          sd.lengths.resize(lsz[s]);
        }
      }
    }
    if (line_end < end) *line_end = saved;
    p = line_end + (had_nl ? 1 : 0);
    if (!had_nl) break;
  }
  return res;
}

int64_t pt_samples(void* h) {
  return h ? static_cast<ParseResult*>(h)->num_samples : -1;
}

int64_t pt_slot_total(void* h, int slot) {
  auto* r = static_cast<ParseResult*>(h);
  const SlotData& sd = r->slots[slot];
  return sd.is_float ? sd.fvals.size() : sd.ivals.size();
}

void pt_slot_lengths(void* h, int slot, int64_t* out) {
  auto* r = static_cast<ParseResult*>(h);
  const auto& L = r->slots[slot].lengths;
  std::memcpy(out, L.data(), L.size() * sizeof(int64_t));
}

void pt_slot_values_f(void* h, int slot, float* out) {
  auto* r = static_cast<ParseResult*>(h);
  const auto& v = r->slots[slot].fvals;
  std::memcpy(out, v.data(), v.size() * sizeof(float));
}

void pt_slot_values_i(void* h, int slot, int64_t* out) {
  auto* r = static_cast<ParseResult*>(h);
  const auto& v = r->slots[slot].ivals;
  std::memcpy(out, v.data(), v.size() * sizeof(int64_t));
}

void pt_release(void* h) { delete static_cast<ParseResult*>(h); }

}  // extern "C"

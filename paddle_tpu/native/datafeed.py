"""ctypes binding for the native MultiSlot parser (datafeed.cpp).

Builds the shared library on first use with g++ (no pybind11 in the
image; plain C ABI + ctypes). Falls back cleanly when no compiler.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, List, Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "build", "libptfeed.so")
_SRC = os.path.join(_HERE, "datafeed.cpp")

_lib = None
_lock = threading.Lock()
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if not os.path.exists(_SO) or os.path.getmtime(_SO) < os.path.getmtime(_SRC):
                os.makedirs(os.path.dirname(_SO), exist_ok=True)
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", _SO, _SRC],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(_SO)
            lib.pt_parse_file.restype = ctypes.c_void_p
            lib.pt_parse_file.argtypes = [
                ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_ubyte),
            ]
            lib.pt_samples.restype = ctypes.c_int64
            lib.pt_samples.argtypes = [ctypes.c_void_p]
            lib.pt_slot_total.restype = ctypes.c_int64
            lib.pt_slot_total.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.pt_slot_lengths.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            ]
            lib.pt_slot_values_f.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_float),
            ]
            lib.pt_slot_values_i.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            ]
            lib.pt_release.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception:
            _build_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


def parse_file(path: str, num_slots: int, dtypes: List[str]) -> Iterator[List[np.ndarray]]:
    """Parse a MultiSlot file natively; yield per-sample slot arrays."""
    lib = _load()
    assert lib is not None
    is_float = (ctypes.c_ubyte * num_slots)(
        *[1 if "float" in dt else 0 for dt in dtypes]
    )
    h = lib.pt_parse_file(path.encode(), num_slots, is_float)
    if not h:
        raise IOError(f"native datafeed failed to open {path}")
    try:
        n = lib.pt_samples(h)
        slots = []
        for s in range(num_slots):
            total = lib.pt_slot_total(h, s)
            lengths = np.empty(n, np.int64)
            lib.pt_slot_lengths(h, s, lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
            if is_float[s]:
                vals = np.empty(total, np.float32)
                lib.pt_slot_values_f(h, s, vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            else:
                vals = np.empty(total, np.int64)
                lib.pt_slot_values_i(h, s, vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
            offsets = np.zeros(n + 1, np.int64)
            np.cumsum(lengths, out=offsets[1:])
            slots.append((offsets, vals))
        for i in range(n):
            yield [vals[offs[i] : offs[i + 1]] for offs, vals in slots]
    finally:
        lib.pt_release(h)

"""Weighted averaging helper.

Reference: python/paddle/fluid/average.py:40 (WeightedAverage) — host-
side streaming average of fetched metrics.
"""

from __future__ import annotations

import numpy as np

__all__ = ["WeightedAverage"]


def _is_number(v):
    return isinstance(v, (int, float)) or (
        isinstance(v, np.ndarray) and v.size == 1)


class WeightedAverage:
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = 0.0
        self.denominator = 0.0

    def add(self, value, weight):
        if not (_is_number(value) or isinstance(value, np.ndarray)):
            raise ValueError("add(): value must be a number or ndarray")
        if not _is_number(weight):
            raise ValueError("add(): weight must be a number")
        w = float(np.asarray(weight).reshape(()))
        self.numerator += float(np.sum(np.asarray(value))) * w
        self.denominator += w

    def eval(self):
        if self.denominator == 0.0:
            raise ValueError(
                "eval() on an empty WeightedAverage (add() something first)")
        return self.numerator / self.denominator

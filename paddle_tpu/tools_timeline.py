"""Chrome-trace writer for host profiler events.

Reference: tools/timeline.py:36 (_ChromeTraceFormatter) / :131
(Timeline) — converts profiler output to the chrome://tracing JSON
format. Device-side timing here comes from jax.profiler's
xplane/perfetto traces; this writer covers the HOST event log
(profiler.record_event ranges + observability.tracing spans), same
viewer.

Three things beyond plain "X" ranges:

* **process lanes** — events may carry a ``pid`` (spans imported from
  another process via ``/v1/admin/trace/<id>`` are pid-stamped by
  ``observability.propagate.local_trace``); each pid becomes its own
  process group with a ``process_name`` metadata event (from
  ``process_names`` or the span's ``worker``/``process`` arg), so a
  cross-process trace renders router / prefill / page-store / decode
  as separate lanes instead of collapsing foreign spans onto local
  tids. Events without a pid land in process 0 ("paddle_tpu host").
* **thread metadata** — events carry the profiler's stable per-thread
  tids; each (pid, tid) gets a ``thread_name`` metadata event so lanes
  read "pt-serving-worker-1", not a bare number (names only apply to
  the local process — a foreign pid's tids are its own).
* **flow arrows** — spans carry ``span_id``/``parent_id`` (and
  optionally ``flow_from``, a list of source span ids) in their args.
  When parent and child ran on a DIFFERENT thread or process, a
  ``ph: s`` / ``ph: f`` flow-event pair is emitted so Perfetto draws
  the arrow: a serving request's submit span visibly hands off to the
  worker thread's batch-execute span, and a router's HTTP span hands
  off to the prefill worker's span one process lane over.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional


def to_chrome_trace(events: List[Dict],
                    thread_names: Optional[Dict[int, str]] = None,
                    process_names: Optional[Dict[int, str]] = None) -> Dict:
    """events: [{name, ts (s), dur (s), tid, pid?, args?}] -> chrome
    trace dict. ``thread_names`` overrides/extends the profiler's
    registry (tid -> display name, local process only);
    ``process_names`` names foreign pids (pid -> lane title)."""
    names = {}
    try:
        from . import profiler

        names.update(profiler.thread_names())
    except Exception:  # noqa: BLE001 — standalone use on raw event dicts
        pass
    names.update(thread_names or {})

    t0 = min((e["ts"] for e in events), default=0.0)
    # index span_id -> its rendered (pid, tid, ts, dur) for flow links
    span_index: Dict[str, Dict] = {}
    rendered = []
    seen_tids = set()            # (pid, tid) pairs
    pid_titles: Dict[int, str] = dict(process_names or {})
    seen_pids = set()
    for e in events:
        tid = int(e.get("tid", 0))
        pid = int(e.get("pid", 0))
        seen_tids.add((pid, tid))
        seen_pids.add(pid)
        ch = {
            "name": e["name"],
            "ph": "X",  # complete event
            "pid": pid,
            "tid": tid,
            "ts": (e["ts"] - t0) * 1e6,   # microseconds
            "dur": e["dur"] * 1e6,
            "cat": "host",
        }
        args = e.get("args") or {k: v for k, v in e.items()
                                 if k not in ("name", "ph", "ts", "dur",
                                              "tid", "pid", "kind", "t")}
        if args:
            ch["args"] = args  # structured span metadata
            sid = args.get("span_id")
            if sid:
                span_index[sid] = ch
            if pid not in pid_titles:
                lane = args.get("worker") or args.get("process")
                if lane:
                    pid_titles[pid] = str(lane)
        rendered.append(ch)

    trace_events = []
    for pid in sorted(seen_pids | set(pid_titles)):
        title = pid_titles.get(
            pid, "paddle_tpu host" if pid == 0 else f"pid {pid}")
        trace_events.append({
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": title},
        })
    for pid, tid in sorted(seen_tids):
        # thread names come from THIS process's profiler registry:
        # only meaningful for local (pid 0) lanes — a foreign pid's
        # tid numbering is its own
        name = names.get(tid) if pid == 0 else None
        if name:
            trace_events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": name},
            })

    trace_events.extend(rendered)

    # flow arrows for cross-thread/cross-process parentage: s at the
    # source span's midpoint, f (binding point "e": enclosing slice)
    # at the child's start
    flow_n = 0
    for ch in rendered:
        args = ch.get("args") or {}
        sources = []
        if args.get("parent_id"):
            sources.append(args["parent_id"])
        sources.extend(args.get("flow_from") or [])
        for src_id in sources:
            src = span_index.get(src_id)
            if (src is None or (src["tid"] == ch["tid"]
                                and src["pid"] == ch["pid"])):
                continue  # same-lane nesting needs no arrow
            flow_n += 1
            fid = f"flow{flow_n}"
            trace_events.append({
                "name": "handoff", "ph": "s", "cat": "flow", "id": fid,
                "pid": src["pid"], "tid": src["tid"],
                "ts": src["ts"] + src["dur"] * 0.5,
            })
            trace_events.append({
                "name": "handoff", "ph": "f", "bp": "e", "cat": "flow",
                "id": fid, "pid": ch["pid"], "tid": ch["tid"],
                "ts": ch["ts"],
            })
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def save_chrome_trace(path: str, events: List[Dict],
                      thread_names: Optional[Dict[int, str]] = None,
                      process_names: Optional[Dict[int, str]] = None) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(events, thread_names, process_names), f)
    return path

"""Chrome-trace writer for host profiler events.

Reference: tools/timeline.py:36 (_ChromeTraceFormatter) / :131
(Timeline) — converts profiler output to the chrome://tracing JSON
format. Device-side timing here comes from jax.profiler's
xplane/perfetto traces; this writer covers the HOST event log
(profiler.record_event ranges), same viewer."""

from __future__ import annotations

import json
from typing import Dict, List


def to_chrome_trace(events: List[Dict]) -> Dict:
    """events: [{name, ts (s), dur (s), tid}] -> chrome trace dict."""
    trace_events = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": 0,
            "args": {"name": "paddle_tpu host"},
        }
    ]
    t0 = min((e["ts"] for e in events), default=0.0)
    for e in events:
        ch = {
            "name": e["name"],
            "ph": "X",  # complete event
            "pid": 0,
            "tid": int(e.get("tid", 0)),
            "ts": (e["ts"] - t0) * 1e6,   # microseconds
            "dur": e["dur"] * 1e6,
            "cat": "host",
        }
        if e.get("args"):
            ch["args"] = e["args"]  # structured span metadata
        trace_events.append(ch)
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def save_chrome_trace(path: str, events: List[Dict]) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(events), f)
    return path

"""Chrome-trace writer for host profiler events.

Reference: tools/timeline.py:36 (_ChromeTraceFormatter) / :131
(Timeline) — converts profiler output to the chrome://tracing JSON
format. Device-side timing here comes from jax.profiler's
xplane/perfetto traces; this writer covers the HOST event log
(profiler.record_event ranges + observability.tracing spans), same
viewer.

Two things beyond plain "X" ranges:

* **thread metadata** — events carry the profiler's stable per-thread
  tids; each tid gets a ``thread_name`` metadata event so lanes read
  "pt-serving-worker-1", not a bare number.
* **flow arrows** — spans carry ``span_id``/``parent_id`` (and
  optionally ``flow_from``, a list of source span ids) in their args.
  When parent and child ran on DIFFERENT threads, a ``ph: s`` /
  ``ph: f`` flow-event pair is emitted so Perfetto draws the arrow:
  a serving request's submit span visibly hands off to the worker
  thread's batch-execute span.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional


def to_chrome_trace(events: List[Dict],
                    thread_names: Optional[Dict[int, str]] = None) -> Dict:
    """events: [{name, ts (s), dur (s), tid, args?}] -> chrome trace
    dict. ``thread_names`` overrides/extends the profiler's registry
    (tid -> display name)."""
    names = {}
    try:
        from . import profiler

        names.update(profiler.thread_names())
    except Exception:  # noqa: BLE001 — standalone use on raw event dicts
        pass
    names.update(thread_names or {})

    trace_events = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": 0,
            "args": {"name": "paddle_tpu host"},
        }
    ]
    t0 = min((e["ts"] for e in events), default=0.0)
    # index span_id -> its rendered (tid, ts, dur) for flow linking
    span_index: Dict[str, Dict] = {}
    rendered = []
    seen_tids = set()
    for e in events:
        tid = int(e.get("tid", 0))
        seen_tids.add(tid)
        ch = {
            "name": e["name"],
            "ph": "X",  # complete event
            "pid": 0,
            "tid": tid,
            "ts": (e["ts"] - t0) * 1e6,   # microseconds
            "dur": e["dur"] * 1e6,
            "cat": "host",
        }
        if e.get("args"):
            ch["args"] = e["args"]  # structured span metadata
            sid = e["args"].get("span_id")
            if sid:
                span_index[sid] = ch
        rendered.append(ch)

    for tid in sorted(seen_tids):
        name = names.get(tid)
        if name:
            trace_events.append({
                "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                "args": {"name": name},
            })

    trace_events.extend(rendered)

    # flow arrows for cross-thread parentage: s at the source span's
    # end, f (binding point "e": enclosing slice) at the child's start
    flow_n = 0
    for ch in rendered:
        args = ch.get("args") or {}
        sources = []
        if args.get("parent_id"):
            sources.append(args["parent_id"])
        sources.extend(args.get("flow_from") or [])
        for src_id in sources:
            src = span_index.get(src_id)
            if src is None or src["tid"] == ch["tid"]:
                continue  # same-lane nesting needs no arrow
            flow_n += 1
            fid = f"flow{flow_n}"
            trace_events.append({
                "name": "handoff", "ph": "s", "cat": "flow", "id": fid,
                "pid": 0, "tid": src["tid"],
                "ts": src["ts"] + src["dur"] * 0.5,
            })
            trace_events.append({
                "name": "handoff", "ph": "f", "bp": "e", "cat": "flow",
                "id": fid, "pid": 0, "tid": ch["tid"], "ts": ch["ts"],
            })
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def save_chrome_trace(path: str, events: List[Dict],
                      thread_names: Optional[Dict[int, str]] = None) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(events, thread_names), f)
    return path

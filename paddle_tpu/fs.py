"""Filesystem shell utilities: local + HDFS.

Reference: paddle/fluid/framework/io/fs.cc (+shell.cc) and
python/paddle/fluid/incubate/fleet/utils/hdfs.py:45 (HDFSClient driving
`hadoop fs` subcommands with retries). Same split here: LocalFS is
pure python; HDFSClient shells out to the hadoop CLI and degrades with
a clear error when no hadoop binary exists (this image has none — the
API is kept so fleet checkpoint paths type-check and unit tests can
exercise the command construction)."""

from __future__ import annotations

import os
import shutil
import subprocess
import time
from typing import List, Optional, Tuple


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FS:
    def ls_dir(self, path):
        raise NotImplementedError

    def is_file(self, path):
        raise NotImplementedError

    def is_dir(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def rename(self, src, dst):
        raise NotImplementedError

    def atomic_rename(self, src, dst):
        raise NotImplementedError


class LocalFS(FS):
    """Reference fs.cc localfs_* functions."""

    def ls_dir(self, path) -> Tuple[List[str], List[str]]:
        """Returns (dirs, files), the reference's split listing."""
        if not self.is_exist(path):
            return [], []
        dirs, files = [], []
        for e in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, e)) else files).append(e)
        return dirs, files

    def is_file(self, path) -> bool:
        return os.path.isfile(path)

    def is_dir(self, path) -> bool:
        return os.path.isdir(path)

    def is_exist(self, path) -> bool:
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if self.is_dir(path):
            shutil.rmtree(path)
        elif self.is_file(path):
            os.remove(path)

    def rename(self, src, dst):
        if not self.is_exist(src):
            raise FSFileNotExistsError(src)
        os.replace(src, dst)

    def atomic_rename(self, src, dst):
        """Crash-safe publication: rename src over dst, DURABLE (parent
        directory fsync'd) — the checkpoint commit primitive
        (io.save_checkpoint's write-to-temp + marker + rename protocol
        funnels through here). For files and a fresh dst this is one
        atomic os.replace. POSIX cannot rename over a non-empty
        DIRECTORY, so an existing dst dir is first moved aside and
        deleted after the publish — that leaves a short crash window
        where dst is absent (never partial); callers needing dst to
        always exist must not target a live directory (CheckpointPolicy
        skips re-publishing committed steps for exactly this reason)."""
        if not self.is_exist(src):
            raise FSFileNotExistsError(src)
        aside = None
        if self.is_dir(dst):
            aside = f"{dst}.old.{os.getpid()}"
            if self.is_exist(aside):
                shutil.rmtree(aside)
            os.replace(dst, aside)
        os.replace(src, dst)
        parent = os.path.dirname(os.path.abspath(dst)) or "."
        try:
            fd = os.open(parent, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass  # fsync on a directory is unsupported on some FSes
        if aside is not None:
            shutil.rmtree(aside, ignore_errors=True)

    def mv(self, src, dst, overwrite=False):
        if not overwrite and self.is_exist(dst):
            raise FSFileExistsError(dst)
        self.rename(src, dst)

    def touch(self, path, exist_ok=True):
        if self.is_exist(path) and not exist_ok:
            raise FSFileExistsError(path)
        open(path, "a").close()

    def cat(self, path) -> str:
        with open(path) as f:
            return f.read()

    def need_upload_download(self) -> bool:
        return False

    def list_dirs(self, path):
        return self.ls_dir(path)[0]


class HDFSClient(FS):
    """Reference incubate/fleet/utils/hdfs.py:45: every operation is a
    `hadoop fs -<cmd>` subprocess with bounded retries."""

    def __init__(self, hadoop_home: Optional[str] = None, configs=None,
                 time_out=5 * 60 * 1000, sleep_inter=1000):
        self._hadoop_home = hadoop_home or os.environ.get("HADOOP_HOME", "")
        self._configs = configs or {}
        self._time_out = time_out
        self._sleep_inter = sleep_inter
        pre = []
        for k, v in self._configs.items():
            pre.append(f"-D{k}={v}")
        binpath = (
            os.path.join(self._hadoop_home, "bin", "hadoop")
            if self._hadoop_home else "hadoop"
        )
        self._base_cmd = [binpath, "fs"] + pre

    def _hadoop_available(self) -> bool:
        return shutil.which(self._base_cmd[0]) is not None

    def _cmd(self, *args) -> List[str]:
        return self._base_cmd + list(args)

    def _run(self, args, retry_times=5) -> Tuple[int, str]:
        """Reference __run_hdfs_cmd: retry transient failures."""
        if not self._hadoop_available():
            raise ExecuteError(
                f"hadoop binary not found ({self._base_cmd[0]!r}) — set "
                "hadoop_home or HADOOP_HOME (this environment has no "
                "hadoop; use LocalFS)"
            )
        last = ""
        for i in range(retry_times):
            try:
                proc = subprocess.run(
                    self._cmd(*args), capture_output=True, text=True,
                    timeout=self._time_out / 1000.0,
                )
                if proc.returncode == 0:
                    return 0, proc.stdout
                last = proc.stderr
            except subprocess.TimeoutExpired:
                last = f"timed out after {self._time_out}ms"
            if i < retry_times - 1:
                time.sleep(self._sleep_inter / 1000.0)
        raise ExecuteError(f"hadoop fs {' '.join(args)} failed: {last[-500:]}")

    # -- operations (each mirrors a reference method) -----------------------
    def is_exist(self, path) -> bool:
        try:
            self._run(["-test", "-e", path], retry_times=1)
            return True
        except ExecuteError as e:
            if "hadoop binary not found" in str(e):
                raise
            return False

    def is_dir(self, path) -> bool:
        try:
            self._run(["-test", "-d", path], retry_times=1)
            return True
        except ExecuteError as e:
            if "hadoop binary not found" in str(e):
                raise
            return False

    def is_file(self, path) -> bool:
        return self.is_exist(path) and not self.is_dir(path)

    def ls_dir(self, path):
        _, out = self._run(["-ls", path])
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = parts[-1]
            (dirs if parts[0].startswith("d") else files).append(
                os.path.basename(name))
        return dirs, files

    def mkdirs(self, path):
        self._run(["-mkdir", "-p", path])

    def delete(self, path):
        self._run(["-rm", "-r", "-f", path])

    def rename(self, src, dst, overwrite=False):
        if overwrite:
            self._run(["-rm", "-r", "-f", dst])
        self._run(["-mv", src, dst])

    def atomic_rename(self, src, dst):
        raise NotImplementedError(
            "HDFSClient.atomic_rename: `hadoop fs -mv` gives no "
            "atomicity or durability guarantee when dst exists (it can "
            "move src INSIDE a dst directory), so it cannot implement "
            "the checkpoint commit protocol — write checkpoints to a "
            "LocalFS staging dir and upload() the committed result"
        )

    def cat(self, path) -> str:
        _, out = self._run(["-cat", path])
        return out

    def upload(self, hdfs_path, local_path, overwrite=False, retry_times=5):
        if overwrite:
            self._run(["-rm", "-r", "-f", hdfs_path], retry_times=1)
        self._run(["-put", local_path, hdfs_path], retry_times)

    def download(self, hdfs_path, local_path, overwrite=False, retry_times=5):
        if overwrite and os.path.exists(local_path):
            LocalFS().delete(local_path)
        self._run(["-get", hdfs_path, local_path], retry_times)

    def need_upload_download(self) -> bool:
        return True

    @staticmethod
    def split_files(files: List[str], trainer_id: int, trainers: int):
        """Reference hdfs.py:396 — contiguous file partition per
        trainer."""
        remainder = len(files) % trainers
        blocksize = len(files) // trainers
        blocks = [blocksize] * trainers
        for i in range(remainder):
            blocks[i] += 1
        trainer_files = [[]] * trainers
        begin = 0
        for i in range(trainers):
            trainer_files[i] = files[begin:begin + blocks[i]]
            begin += blocks[i]
        return trainer_files[trainer_id]

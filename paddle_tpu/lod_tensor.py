"""User-facing LoDTensor helpers.

Reference: python/paddle/fluid/lod_tensor.py:24 (create_lod_tensor),
:114 (create_random_int_lodtensor) over the C++ LoDTensor
(framework/lod_tensor.h:104 — ragged level-of-detail offsets).

TPU-native representation: XLA shapes are static, so raggedness lives
as DENSE PADDED data + per-sequence lengths (the convention every
sequence op in ops/sequence.py and the rank-table family in ops/lod.py
consume). ``LoDTensor`` here is the host-side carrier pairing the
padded array with its recursive sequence lengths; feeding one to the
executor feeds the padded array, and its ``lengths()`` feed the ops'
Length slots.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = ["LoDTensor", "create_lod_tensor", "create_random_int_lodtensor"]


class LoDTensor:
    """Dense padded data + recursive sequence lengths.

    ``recursive_sequence_lengths()`` matches the reference API
    (lod_tensor.h length-based LoD); ``lod()`` returns offset form."""

    def __init__(self, data: np.ndarray, recursive_seq_lens: Sequence[Sequence[int]]):
        self._data = np.asarray(data)
        self._seq_lens = [list(l) for l in recursive_seq_lens]

    def recursive_sequence_lengths(self) -> List[List[int]]:
        return [list(l) for l in self._seq_lens]

    def set_recursive_sequence_lengths(self, lens):
        self._seq_lens = [list(l) for l in lens]

    def lod(self) -> List[List[int]]:
        out = []
        for level in self._seq_lens:
            offs = [0]
            for l in level:
                offs.append(offs[-1] + int(l))
            out.append(offs)
        return out

    def has_valid_recursive_sequence_lengths(self) -> bool:
        # non-leaf levels: sum == next level's sequence count; the
        # LEAF level in dense padding owns one padded row per sequence
        # and each length must fit within the padded time extent
        try:
            for i, level in enumerate(self._seq_lens):
                if not level or any(l < 0 for l in level):
                    return False
                if i + 1 < len(self._seq_lens):
                    if sum(level) != len(self._seq_lens[i + 1]):
                        return False
                else:
                    if len(level) != self._data.shape[0]:
                        return False
                    if self._data.ndim > 1 and max(level) > self._data.shape[1]:
                        return False
        except (IndexError, TypeError):
            return False
        return True

    def numpy(self) -> np.ndarray:
        return self._data

    def lengths(self) -> np.ndarray:
        """Leaf-level lengths vector for ops' Length slots."""
        return np.asarray(self._seq_lens[-1], dtype=np.int64)

    @property
    def shape(self):
        return self._data.shape

    def __array__(self, dtype=None):
        return self._data.astype(dtype) if dtype else self._data


def create_lod_tensor(data, recursive_seq_lens, place=None) -> LoDTensor:
    """Reference lod_tensor.py:24. ``data`` may be:

    * a flat [sum(lens), ...] array (reference layout) — rows are
      re-packed into dense padding [num_seqs, max_len, ...];
    * a list of per-sequence row-lists (reference nested-list form);
    * an already-padded [num_seqs, max_len, ...] array whose row count
      matches len(lens) — kept as-is.
    """
    lens = [list(l) for l in recursive_seq_lens]
    leaf = lens[-1]
    if isinstance(data, (list, tuple)):
        rows = [np.asarray(r).reshape(-1, *np.asarray(r).shape[1:])
                for r in data]
        flat = np.concatenate(rows, axis=0)
    else:
        flat = np.asarray(data)
    if not leaf:
        return LoDTensor(flat, lens)  # empty: nothing to repack
    max_len = max(leaf)
    # already-padded detection: [num_seqs, time >= max(leaf), ...]
    # (bucketed batches may pad past max(leaf)). When all lengths are 1
    # the flat and padded row counts coincide — then only a 3-D+ block
    # whose time axis is exactly max(leaf) reads as padded.
    if flat.shape[0] == sum(leaf):  # ambiguous or flat
        padded_like = (flat.shape[0] == len(leaf) and flat.ndim >= 3
                       and flat.shape[1] == max_len)
    else:
        padded_like = (flat.shape[0] == len(leaf) and flat.ndim >= 2
                       and flat.shape[1] >= max_len)
    if padded_like:
        return LoDTensor(flat, lens)
    assert flat.shape[0] == sum(leaf), (
        f"data rows {flat.shape[0]} match neither sum(lengths) "
        f"{sum(leaf)} (flat layout) nor a padded "
        f"[{len(leaf)}, >={max_len}, ...] block")
    out = np.zeros((len(leaf), max_len) + flat.shape[1:], flat.dtype)
    off = 0
    for i, l in enumerate(leaf):
        out[i, :l] = flat[off:off + l]
        off += l
    return LoDTensor(out, lens)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=10) -> LoDTensor:
    """Reference lod_tensor.py:114."""
    leaf = list(recursive_seq_lens[-1])
    total = sum(leaf)
    flat = np.random.randint(low, high + 1,
                             size=(total,) + tuple(base_shape)).astype("int64")
    return create_lod_tensor(flat, recursive_seq_lens, place)

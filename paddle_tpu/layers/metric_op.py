"""Metric layers. Reference: python/paddle/fluid/layers/metric_op.py."""

from __future__ import annotations

from ..layer_helper import LayerHelper
from .nn import _out, topk


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    _, idx = topk(input, k)
    acc = _out(helper, input, shape=(1,), stop_gradient=True)
    correct = correct or _out(helper, input, shape=(1,), dtype="int32", stop_gradient=True)
    total = total or _out(helper, input, shape=(1,), dtype="int32", stop_gradient=True)
    helper.append_op(
        type="accuracy",
        inputs={"Out": [input], "Indices": [idx], "Label": [label]},
        outputs={"Accuracy": [acc], "Correct": [correct], "Total": [total]},
    )
    return acc


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):
    helper = LayerHelper("auc")
    stat_pos = helper.create_global_variable(
        persistable=True, dtype="float32", shape=[1, num_thresholds + 1]
    )
    stat_neg = helper.create_global_variable(
        persistable=True, dtype="float32", shape=[1, num_thresholds + 1]
    )
    from ..initializer import ConstantInitializer

    for v in (stat_pos, stat_neg):
        v.persistable = True
        helper.set_variable_initializer(v, ConstantInitializer(0.0))
    auc_out = _out(helper, input, shape=(), stop_gradient=True)
    helper.append_op(
        type="auc",
        inputs={
            "Predict": [input],
            "Label": [label],
            "StatPos": [stat_pos],
            "StatNeg": [stat_neg],
        },
        outputs={
            "AUC": [auc_out],
            "StatPosOut": [stat_pos],
            "StatNegOut": [stat_neg],
        },
        attrs={"curve": curve, "num_thresholds": num_thresholds},
    )
    return auc_out, [stat_pos, stat_neg]

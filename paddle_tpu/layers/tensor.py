"""Tensor-creation layers. Reference: python/paddle/fluid/layers/tensor.py."""

from __future__ import annotations

import numpy as np

from ..core.framework import Variable, convert_dtype
from ..layer_helper import LayerHelper

__all__ = [
    "create_tensor",
    "create_global_var",
    "fill_constant",
    "fill_constant_batch_size_like",
    "assign",
    "concat",
    "sums",
    "zeros",
    "ones",
    "zeros_like",
    "ones_like",
    "range",
    "linspace",
    "uniform_random",
    "gaussian_random",
    "create_parameter",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.main_block.create_var(
        name=name or helper.name, dtype=dtype, persistable=persistable
    )


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    from ..core.framework import default_main_program, default_startup_program, unique_name
    from ..initializer import ConstantInitializer

    name = name or unique_name.generate("global_var")
    var = default_main_program().global_block().create_var(
        name=name, shape=shape, dtype=dtype, persistable=persistable, stop_gradient=True
    )
    sgb = default_startup_program().global_block()
    sv = sgb.create_var(name=name, shape=shape, dtype=dtype, persistable=persistable)
    ConstantInitializer(value)(sv, sgb)
    default_startup_program()._bump()
    return var


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None):
    helper = LayerHelper("create_parameter", param_attr=attr, name=name)
    pa = helper.param_attr
    if name is not None and pa.name is None:
        pa.name = name
    return helper.create_parameter(pa, shape, dtype, is_bias, default_initializer)


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    dtype = convert_dtype(dtype)
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype=dtype, shape=tuple(shape), stop_gradient=True
        )
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": dtype, "value": float(value)},
    )
    return out


def fill_constant_batch_size_like(
    input, shape, dtype, value, input_dim_idx=0, output_dim_idx=0
):
    helper = LayerHelper("fill_constant_batch_size_like")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(
        dtype=dtype, shape=tuple(shape), stop_gradient=True
    )
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "dtype": dtype,
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
    )
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(
                dtype=input.dtype, shape=input.shape
            )
        helper.append_op(
            type="assign", inputs={"X": [input]}, outputs={"Out": [output]}
        )
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(
                dtype=str(arr.dtype), shape=arr.shape
            )
        helper.append_op(
            type="assign_value",
            outputs={"Out": [output]},
            attrs={
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "values": arr.reshape(-1).tolist(),
            },
        )
    return output


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    xs = list(input)
    shp = list(xs[0].shape or ())
    if shp:
        tot = 0
        for v in xs:
            d = (v.shape or [None] * len(shp))[axis]
            if d is None or d < 0:
                tot = -1
                break
            tot += d
        shp[axis] = tot
    out = helper.create_variable_for_type_inference(
        dtype=xs[0].dtype, shape=tuple(shp) if shp else None
    )
    helper.append_op(
        type="concat", inputs={"X": xs}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    xs = list(input)
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype=xs[0].dtype, shape=xs[0].shape
        )
    helper.append_op(type="sum", inputs={"X": xs}, outputs={"Out": [out]})
    return out


def zeros(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros_like(x, out=None):
    helper = LayerHelper("fill_zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype=x.dtype, shape=x.shape, stop_gradient=True
        )
    helper.append_op(type="fill_zeros_like", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def ones_like(x, out=None):
    z = zeros_like(x)
    from .nn import scale

    return scale(z, scale=1.0, bias=1.0)


def range(start, end, step, dtype="float32"):
    """Static range: arguments must be python scalars (XLA needs static
    shapes; the reference's tensor-input range has data-dependent shape)."""
    vals = np.arange(start, end, step)
    return assign(vals.astype(convert_dtype(dtype)))


def linspace(start, stop, num, dtype="float32"):
    vals = np.linspace(start, stop, int(num))
    return assign(vals.astype(convert_dtype(dtype)))


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(
        dtype=convert_dtype(dtype), shape=tuple(shape), stop_gradient=True
    )
    helper.append_op(
        type="uniform_random",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": convert_dtype(dtype), "min": min, "max": max, "seed": seed},
    )
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(
        dtype=convert_dtype(dtype), shape=tuple(shape), stop_gradient=True
    )
    helper.append_op(
        type="gaussian_random",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": convert_dtype(dtype), "mean": mean, "std": std, "seed": seed},
    )
    return out

"""Control-flow layers.

Reference: python/paddle/fluid/layers/control_flow.py (While, cond,
Switch, increment, array ops over LoDTensorArray).

TPU-native approach: structured control flow must be *functional* to
compile (lax.while_loop / lax.cond). The reference's imperative
While-with-side-effecting-block style is supported for the common
pattern (loop state = vars written in the block); the executor lowers
`while` / `conditional_block` ops via sub-block tracing — see
core/control_flow.py.
"""

from __future__ import annotations

from ..core.framework import Variable, default_main_program
from ..layer_helper import LayerHelper

__all__ = [
    "increment", "array_write", "array_read", "less_than", "less_equal",
    "greater_than", "greater_equal", "equal", "not_equal", "While",
    "Switch", "cond",
]


def _compare(op_type, x, y, out=None):
    helper = LayerHelper(op_type)
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype="bool", shape=x.shape, stop_gradient=True
        )
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]})
    return out


def less_equal(x, y, cond=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare("greater_equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(dtype=x.dtype, shape=x.shape)
    helper.append_op(
        type="increment", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"step": float(value)}
    )
    return out


def less_than(x, y, force_cpu=None, cond=None):
    helper = LayerHelper("less_than")
    if cond is None:
        cond = helper.create_variable_for_type_inference(
            dtype="bool", shape=x.shape, stop_gradient=True
        )
    helper.append_op(
        type="less_than", inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]}
    )
    return cond


def equal(x, y, cond=None):
    helper = LayerHelper("equal")
    if cond is None:
        cond = helper.create_variable_for_type_inference(
            dtype="bool", shape=x.shape, stop_gradient=True
        )
    helper.append_op(type="equal", inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]})
    return cond


def array_write(x, i, array=None):
    raise NotImplementedError(
        "LoDTensorArray is inherently dynamic; on TPU use lax.scan-style "
        "rnn() (layers.rnn) or static python lists of Variables"
    )


def array_read(array, i):
    raise NotImplementedError(
        "LoDTensorArray is inherently dynamic; on TPU use lax.scan-style "
        "rnn() (layers.rnn) or static python lists of Variables"
    )


class While:
    """Reference layers/control_flow.py While. Usage:

        i = fill_constant([1], 'int64', 0)
        loop = While(cond_var)
        with loop.block():
            ...ops...
            layers.assign(new_cond, cond_var)

    The executor compiles the sub-block as a lax.while_loop whose carry
    is the set of vars read-then-written by the block.
    """

    def __init__(self, cond, is_test=False, name=None):
        self.cond_var = cond
        self.helper = LayerHelper("while", name=name)

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            prog = default_main_program()
            parent = prog.current_block()
            sub = prog._create_block()
            try:
                yield
            finally:
                prog._rollback()
                parent.append_op(
                    type="while",
                    inputs={"Condition": [self.cond_var]},
                    outputs={},
                    attrs={"sub_block": sub, "is_test": False},
                )
                prog._bump()

        return _ctx()


class Switch:
    """Reference Switch: exclusive chained cases — the FIRST matching
    case runs; default runs only when no case matched. Each case
    lowers to a conditional_block whose predicate is
    (cond AND NOT any-earlier-matched)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._matched = None  # bool var: any earlier case fired

    def _effective_cond(self, condition):
        from ..layer_helper import LayerHelper as LH

        helper = LH("switch_case")
        if self._matched is None:
            eff = condition
            self._matched = condition
            return eff
        not_prev = helper.create_variable_for_type_inference(
            dtype="bool", shape=condition.shape, stop_gradient=True
        )
        helper.append_op(
            type="logical_not", inputs={"X": [self._matched]}, outputs={"Out": [not_prev]}
        )
        eff = helper.create_variable_for_type_inference(
            dtype="bool", shape=condition.shape, stop_gradient=True
        )
        helper.append_op(
            type="logical_and",
            inputs={"X": [condition], "Y": [not_prev]},
            outputs={"Out": [eff]},
        )
        new_matched = helper.create_variable_for_type_inference(
            dtype="bool", shape=condition.shape, stop_gradient=True
        )
        helper.append_op(
            type="logical_or",
            inputs={"X": [self._matched], "Y": [condition]},
            outputs={"Out": [new_matched]},
        )
        self._matched = new_matched
        return eff

    def case(self, condition):
        import contextlib

        effective = self._effective_cond(condition)

        @contextlib.contextmanager
        def _ctx():
            prog = default_main_program()
            parent = prog.current_block()
            sub = prog._create_block()
            try:
                yield
            finally:
                prog._rollback()
                parent.append_op(
                    type="conditional_block",
                    inputs={"Cond": [effective]},
                    outputs={},
                    attrs={"sub_block": sub, "is_scalar_condition": True},
                )
                prog._bump()

        return _ctx()

    def default(self):
        from .tensor import fill_constant

        cond = fill_constant([1], "bool", 1.0)
        return self.case(cond)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Functional cond (modeled on the later-API layers.cond): both
    branches are traced; lowered to lax.cond via conditional_select op
    pattern. Branches must return Variables of matching shape."""
    t = true_fn() if true_fn is not None else None
    f = false_fn() if false_fn is not None else None
    if t is None or f is None:
        return t if t is not None else f
    from .nn import where, cast, expand_as

    # evaluate both branches, select (XLA does the same for lax.select)
    p = pred
    if t.shape and (p.shape is None or len(p.shape or ()) < len(t.shape)):
        # broadcast scalar predicate
        from .nn import _elementwise_binary

        pass
    return where(_bool_like(p, t), t, f)


def _bool_like(pred, template):
    from .nn import cast, expand_as
    from .tensor import fill_constant_batch_size_like

    p = cast(pred, "bool")
    return p

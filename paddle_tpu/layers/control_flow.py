"""Control-flow layers.

Reference: python/paddle/fluid/layers/control_flow.py (While, cond,
Switch, increment, array ops over LoDTensorArray).

TPU-native approach: structured control flow must be *functional* to
compile (lax.while_loop / lax.cond). The reference's imperative
While-with-side-effecting-block style is supported for the common
pattern (loop state = vars written in the block); the executor lowers
`while` / `conditional_block` ops via sub-block tracing — see
core/control_flow.py.
"""

from __future__ import annotations

from ..core.framework import Variable, default_main_program, unique_name
from ..layer_helper import LayerHelper

__all__ = [
    "increment", "create_array", "array_write", "array_read", "array_length",
    "less_than", "less_equal",
    "greater_than", "greater_equal", "equal", "not_equal", "While",
    "Switch", "cond", "StaticRNN", "DynamicRNN",
]


def _compare(op_type, x, y, out=None):
    helper = LayerHelper(op_type)
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype="bool", shape=x.shape, stop_gradient=True
        )
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]})
    return out


def less_equal(x, y, cond=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare("greater_equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(dtype=x.dtype, shape=x.shape)
    helper.append_op(
        type="increment", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"step": float(value)}
    )
    return out


def less_than(x, y, force_cpu=None, cond=None):
    helper = LayerHelper("less_than")
    if cond is None:
        cond = helper.create_variable_for_type_inference(
            dtype="bool", shape=x.shape, stop_gradient=True
        )
    helper.append_op(
        type="less_than", inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]}
    )
    return cond


def equal(x, y, cond=None):
    helper = LayerHelper("equal")
    if cond is None:
        cond = helper.create_variable_for_type_inference(
            dtype="bool", shape=x.shape, stop_gradient=True
        )
    helper.append_op(type="equal", inputs={"X": [x], "Y": [y]}, outputs={"Out": [cond]})
    return cond


def create_array(dtype, capacity, elem_shape):
    """Allocate a dense tensor array [capacity, *elem_shape].

    Reference create_array makes an empty LoDTensorArray that grows on
    write; XLA needs the capacity up front (= the loop trip count in
    every reference usage pattern)."""
    helper = LayerHelper("create_array")
    # differentiable carrier: grads must flow through array writes back
    # to what was written (fill_constant outputs default stop_gradient)
    out = helper.create_variable_for_type_inference(
        dtype=dtype, shape=(capacity,) + tuple(elem_shape), stop_gradient=False
    )
    from .tensor import fill_constant

    return fill_constant([capacity] + list(elem_shape), dtype, 0.0, out=out)


def array_write(x, i, array=None, capacity=None):
    """A[i] = x. Reference: tensor_array_read_write_op.cc (write_to_array).

    With array=None a fresh dense array is allocated and ``capacity``
    is REQUIRED (the reference grows the array dynamically; XLA shapes
    are static, so the bound must be declared — usually the loop trip
    count). Prefer ``create_array`` + in-place writes."""
    helper = LayerHelper("array_write")
    inputs = {"X": [x], "I": [i]}
    if array is not None:
        inputs["Array"] = [array]
        out = array  # in-place semantics: read-then-write -> loop carry
    else:
        if capacity is None:
            raise ValueError(
                "array_write(array=None) needs an explicit capacity: dense "
                "tensor arrays are fixed-size on TPU (use create_array)"
            )
        out = helper.create_variable_for_type_inference(
            dtype=x.dtype, shape=(capacity,) + tuple(x.shape or ())
        )
    helper.append_op(
        type="write_to_array", inputs=inputs, outputs={"Out": [out]},
        attrs={"capacity": int(capacity or 0)},
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference(
        dtype="int64", shape=(1,), stop_gradient=True
    )
    helper.append_op(
        type="lod_array_length", inputs={"X": [array]}, outputs={"Out": [out]}
    )
    return out


def array_read(array, i):
    """out = A[i]. Reference: tensor_array_read_write_op.cc."""
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(
        dtype=array.dtype, shape=tuple((array.shape or (1,))[1:])
    )
    helper.append_op(
        type="read_from_array", inputs={"X": [array], "I": [i]},
        outputs={"Out": [out]},
    )
    return out


class While:
    """Reference layers/control_flow.py While. Usage:

        i = fill_constant([1], 'int64', 0)
        loop = While(cond_var)
        with loop.block():
            ...ops...
            layers.assign(new_cond, cond_var)

    The executor compiles the sub-block as a lax.while_loop whose carry
    is the set of vars read-then-written by the block.
    """

    def __init__(self, cond, is_test=False, name=None):
        self.cond_var = cond
        self.helper = LayerHelper("while", name=name)

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            prog = default_main_program()
            parent = prog.current_block()
            sub = prog._create_block()
            try:
                yield
            finally:
                prog._rollback()
                parent.append_op(
                    type="while",
                    inputs={"Condition": [self.cond_var]},
                    outputs={},
                    attrs={"sub_block": sub, "is_test": False},
                )
                prog._bump()

        return _ctx()


class Switch:
    """Reference Switch: exclusive chained cases — the FIRST matching
    case runs; default runs only when no case matched. Each case
    lowers to a conditional_block whose predicate is
    (cond AND NOT any-earlier-matched)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._matched = None  # bool var: any earlier case fired

    def _effective_cond(self, condition):
        from ..layer_helper import LayerHelper as LH

        helper = LH("switch_case")
        if self._matched is None:
            eff = condition
            self._matched = condition
            return eff
        not_prev = helper.create_variable_for_type_inference(
            dtype="bool", shape=condition.shape, stop_gradient=True
        )
        helper.append_op(
            type="logical_not", inputs={"X": [self._matched]}, outputs={"Out": [not_prev]}
        )
        eff = helper.create_variable_for_type_inference(
            dtype="bool", shape=condition.shape, stop_gradient=True
        )
        helper.append_op(
            type="logical_and",
            inputs={"X": [condition], "Y": [not_prev]},
            outputs={"Out": [eff]},
        )
        new_matched = helper.create_variable_for_type_inference(
            dtype="bool", shape=condition.shape, stop_gradient=True
        )
        helper.append_op(
            type="logical_or",
            inputs={"X": [self._matched], "Y": [condition]},
            outputs={"Out": [new_matched]},
        )
        self._matched = new_matched
        return eff

    def case(self, condition):
        import contextlib

        effective = self._effective_cond(condition)

        @contextlib.contextmanager
        def _ctx():
            prog = default_main_program()
            parent = prog.current_block()
            sub = prog._create_block()
            try:
                yield
            finally:
                prog._rollback()
                parent.append_op(
                    type="conditional_block",
                    inputs={"Cond": [effective]},
                    outputs={},
                    attrs={"sub_block": sub, "is_scalar_condition": True},
                )
                prog._bump()

        return _ctx()

    def default(self):
        from .tensor import fill_constant

        cond = fill_constant([1], "bool", 1.0)
        return self.case(cond)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Functional cond (modeled on the later-API layers.cond): both
    branches are traced; lowered to lax.cond via conditional_select op
    pattern. Branches must return Variables of matching shape."""
    t = true_fn() if true_fn is not None else None
    f = false_fn() if false_fn is not None else None
    if t is None or f is None:
        return t if t is not None else f
    from .nn import where, cast, expand_as

    # evaluate both branches, select (XLA does the same for lax.select)
    p = pred
    if t.shape and (p.shape is None or len(p.shape or ()) < len(t.shape)):
        # broadcast scalar predicate
        from .nn import _elementwise_binary

        pass
    return where(_bool_like(p, t), t, f)


def _bool_like(pred, template):
    from .nn import cast, expand_as
    from .tensor import fill_constant_batch_size_like

    p = cast(pred, "bool")
    return p


class StaticRNN:
    """User-authored recurrent block over a fixed number of steps.

    Reference: python/paddle/fluid/layers/control_flow.py StaticRNN
    (backed by operators/recurrent_op.cc). Inputs are time-major
    [T, B, ...]; the step block you build inside ``with rnn.step():``
    becomes the body of ONE lax.scan (ops/rnn.py `recurrent`), and
    training works through the scan via the registry auto-vjp.
    """

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self._sub_block = None
        self._step_inputs = []      # (parent seq var, in-block var)
        self._memories = []         # dicts: pre var, init var/spec, updated name
        self._outputs = []          # in-block vars
        self.seq_len = None

    def step(self):
        import contextlib

        prog = self.helper.main_program

        @contextlib.contextmanager
        def _ctx():
            self._sub_block = prog._create_block()
            self.status = StaticRNN.IN_RNN_BLOCK
            try:
                yield
            finally:
                prog._rollback()
                self.status = StaticRNN.AFTER_RNN_BLOCK
                self._complete()

        return _ctx()

    def _assert_in_rnn_block(self, method):
        if self.status != StaticRNN.IN_RNN_BLOCK:
            raise ValueError(f"You must invoke {method} in rnn.step()")

    def step_input(self, x):
        self._assert_in_rnn_block("step_input")
        if self.seq_len is None:
            self.seq_len = x.shape[0]
        ipt = self._sub_block.create_var(
            name=unique_name.generate(f"{self.helper.name}.step_in"),
            shape=tuple(x.shape[1:]) if x.shape else None,
            dtype=x.dtype,
        )
        self._step_inputs.append((x, ipt))
        return ipt

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=None):
        """ref_batch_dim_idx indexes batch_ref AS THE CALLER SEES IT
        (slice-relative for a step var). Default None = auto: dim 0 of
        a sliced step var, dim 1 of a full [T, B, ...] sequence."""
        self._assert_in_rnn_block("memory")
        if init is None and (shape is None or batch_ref is None):
            raise ValueError(
                "if init is None, memory at least needs shape and batch_ref")
        if init is not None:
            mshape, mdtype = tuple(init.shape or ()), init.dtype
        else:
            # keep a placeholder batch dim: downstream layers size
            # weights from shape[1:]
            mshape = tuple(1 if (s is None or s <= 0) else s for s in shape)
            mdtype = "float32"
        pre = self._sub_block.create_var(
            name=unique_name.generate(f"{self.helper.name}.mem"),
            shape=mshape, dtype=mdtype,
        )
        self._memories.append({
            "pre": pre, "init": init, "shape": shape, "batch_ref": batch_ref,
            "value": init_value, "init_dim": init_batch_dim_idx,
            "ref_dim": ref_batch_dim_idx, "updated": None,
        })
        return pre

    def update_memory(self, mem, var):
        self._assert_in_rnn_block("update_memory")
        for m in self._memories:
            if m["pre"] is mem or m["pre"].name == mem.name:
                m["updated"] = var
                return
        raise ValueError(f"{mem.name} is not a memory of this StaticRNN")

    def step_output(self, o):
        self._assert_in_rnn_block("step_output")
        self._outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        from .tensor import fill_constant_batch_size_like

        block = self.helper.main_program.current_block()  # parent
        sub = self._sub_block
        for m in self._memories:
            if m["updated"] is None:
                raise ValueError(f"memory {m['pre'].name} was never updated "
                                 "(call rnn.update_memory)")
        # init vars (parent block): explicit init or batch-ref fill
        in_block_to_parent = {v.name: x for x, v in self._step_inputs}
        init_vars = []
        for m in self._memories:
            if m["init"] is not None:
                init_vars.append(m["init"])
            else:
                ref, ref_dim = m["batch_ref"], m["ref_dim"]
                if ref.name in in_block_to_parent:
                    # user pointed at the sliced step var; the init op
                    # runs in the parent block, so use the full [T,...]
                    # sequence and shift the batch dim past the T axis
                    ref = in_block_to_parent[ref.name]
                    ref_dim = 1 if ref_dim is None else ref_dim + 1
                elif ref_dim is None:
                    ref_dim = 1
                init_vars.append(fill_constant_batch_size_like(
                    ref,
                    [s if s and s > 0 else 1 for s in m["shape"]],
                    "float32", m["value"],
                    input_dim_idx=ref_dim, output_dim_idx=m["init_dim"],
                ))
        # externals: names read in the sub block but produced neither
        # there nor by slicing/memory links (fc weights etc.)
        produced = {n for op_ in sub.ops for ns in op_.outputs.values() for n in ns}
        bound = ({v.name for _, v in self._step_inputs}
                 | {m["pre"].name for m in self._memories})
        ext = []
        for op_ in sub.ops:
            for ns in op_.inputs.values():
                for n in ns:
                    if n not in produced and n not in bound and n not in ext:
                        ext.append(n)

        T = self.seq_len
        out_vars = []
        for o in self._outputs:
            out_vars.append(block.create_var(
                name=unique_name.generate(f"{self.helper.name}.out"),
                shape=(T,) + tuple(o.shape or ()), dtype=o.dtype,
            ))
        final_mems = [
            block.create_var(
                name=unique_name.generate(f"{self.helper.name}.final_mem"),
                shape=tuple(m["pre"].shape or ()), dtype=m["pre"].dtype,
            )
            for m in self._memories
        ]
        block.append_op(
            type="recurrent",
            inputs={
                "StepInputs": [x for x, _ in self._step_inputs],
                "InitMemories": init_vars,
                "Parameters": ext,
            },
            outputs={"StepOutputs": out_vars, "FinalMemories": final_mems},
            attrs={
                "sub_block": sub,
                "step_input_names": [v.name for _, v in self._step_inputs],
                "pre_memory_names": [m["pre"].name for m in self._memories],
                "memory_names": [m["updated"].name for m in self._memories],
                "step_output_names": [o.name for o in self._outputs],
                "parameter_names": list(ext),
                "time_major": True,
            },
        )
        self.helper.main_program._bump()
        self._out_vars = out_vars

    def __call__(self, *args, **kwargs):
        if self.status != StaticRNN.AFTER_RNN_BLOCK:
            raise ValueError("rnn output can only be retrieved after rnn.step()")
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return self._out_vars


class DynamicRNN:
    """Recurrent block over variable-length batch-major sequences.

    Reference: python/paddle/fluid/layers/control_flow.py DynamicRNN
    (LoD-based shrinking batches). Dense TPU form: inputs are
    [B, T, ...] plus a per-row Length; finished rows freeze their
    memories and emit zeros (ops/rnn.py `recurrent`,
    time_major=False). ``drnn()`` returns [B, T, ...] outputs.
    """

    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.status = DynamicRNN.BEFORE_RNN
        self._sub_block = None
        self._step_inputs = []
        self._static_inputs = []
        self._memories = []
        self._outputs = []
        self._lengths = None
        self.max_len = None

    def block(self):
        import contextlib

        prog = self.helper.main_program

        @contextlib.contextmanager
        def _ctx():
            self._sub_block = prog._create_block()
            self.status = DynamicRNN.IN_RNN
            try:
                yield
            finally:
                prog._rollback()
                self.status = DynamicRNN.AFTER_RNN
                self._complete()

        return _ctx()

    def step_input(self, x, length=None, level=0):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError("step_input must be called in drnn.block()")
        if self.max_len is None:
            self.max_len = x.shape[1]
        if length is not None:
            self._lengths = length
        ipt = self._sub_block.create_var(
            name=unique_name.generate(f"{self.helper.name}.step_in"),
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype,
        )
        self._step_inputs.append((x, ipt))
        return ipt

    def static_input(self, x):
        """Per-sequence constant input (reference reorders by LoD rank;
        dense batches keep row order, so it passes through)."""
        return x

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype="float32"):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError("memory must be called in drnn.block()")
        if init is not None:
            mshape, mdtype = tuple(init.shape or ()), init.dtype
        else:
            if not self._step_inputs:
                raise ValueError("call step_input before value-initialized memory")
            batch = self._step_inputs[0][0].shape[0]
            mshape = (batch,) + tuple(s for s in (shape or []) if s and s > 0)
            mdtype = dtype
        pre = self._sub_block.create_var(
            name=unique_name.generate(f"{self.helper.name}.mem"),
            shape=mshape, dtype=mdtype,
        )
        self._memories.append({"pre": pre, "init": init, "shape": shape,
                               "value": value, "updated": None})
        return pre

    def update_memory(self, mem, var):
        for m in self._memories:
            if m["pre"] is mem or m["pre"].name == mem.name:
                m["updated"] = var
                return
        raise ValueError(f"{mem.name} is not a memory of this DynamicRNN")

    def output(self, *outputs):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError("output must be called in drnn.block()")
        self._outputs.extend(outputs)

    def _complete(self):
        from .tensor import fill_constant_batch_size_like

        block = self.helper.main_program.current_block()
        sub = self._sub_block
        for m in self._memories:
            if m["updated"] is None:
                raise ValueError(f"memory {m['pre'].name} never updated")
        init_vars = []
        for m in self._memories:
            if m["init"] is not None:
                init_vars.append(m["init"])
            else:
                ref = self._step_inputs[0][0]
                init_vars.append(fill_constant_batch_size_like(
                    ref, [1] + [s for s in (m["shape"] or []) if s and s > 0],
                    "float32", m["value"], input_dim_idx=0, output_dim_idx=0,
                ))
        produced = {n for op_ in sub.ops for ns in op_.outputs.values() for n in ns}
        bound = ({v.name for _, v in self._step_inputs}
                 | {m["pre"].name for m in self._memories})
        ext = []
        for op_ in sub.ops:
            for ns in op_.inputs.values():
                for n in ns:
                    if n not in produced and n not in bound and n not in ext:
                        ext.append(n)
        out_vars = []
        for o in self._outputs:
            oshape = tuple(o.shape or ())
            out_vars.append(block.create_var(
                name=unique_name.generate(f"{self.helper.name}.out"),
                shape=(oshape[0], self.max_len) + oshape[1:], dtype=o.dtype,
            ))
        final_mems = [
            block.create_var(
                name=unique_name.generate(f"{self.helper.name}.final_mem"),
                shape=tuple(m["pre"].shape or ()), dtype=m["pre"].dtype,
            )
            for m in self._memories
        ]
        inputs = {
            "StepInputs": [x for x, _ in self._step_inputs],
            "InitMemories": init_vars,
            "Parameters": ext,
        }
        if self._lengths is not None:
            inputs["SeqLengths"] = [self._lengths]
        block.append_op(
            type="recurrent",
            inputs=inputs,
            outputs={"StepOutputs": out_vars, "FinalMemories": final_mems},
            attrs={
                "sub_block": sub,
                "step_input_names": [v.name for _, v in self._step_inputs],
                "pre_memory_names": [m["pre"].name for m in self._memories],
                "memory_names": [m["updated"].name for m in self._memories],
                "step_output_names": [o.name for o in self._outputs],
                "parameter_names": list(ext),
                "time_major": False,
            },
        )
        self.helper.main_program._bump()
        self._out_vars = out_vars

    def __call__(self, *args, **kwargs):
        if self.status != DynamicRNN.AFTER_RNN:
            raise ValueError("drnn output only after drnn.block()")
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return self._out_vars

"""Callable registry for the py_func op (reference py_func_op.cc keeps
a global vector of py::objects indexed by callable id; pybind looks
them up at kernel time). Here the executor's lowering resolves ids via
this module, and jax.pure_callback hosts the call."""

from __future__ import annotations

from typing import Callable, Dict, List

_CALLABLES: List[Callable] = []


def register_callable(fn: Callable) -> int:
    _CALLABLES.append(fn)
    return len(_CALLABLES) - 1


def get_callable(fid: int) -> Callable:
    return _CALLABLES[fid]


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Layer API (reference fluid.layers.py_func): run `func` on the
    host over x, producing `out` (Variables with declared shape/dtype
    — pure_callback needs static result shapes)."""
    from ..layer_helper import LayerHelper

    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    fid = register_callable(func)
    attrs = {
        "forward_callable_id": fid,
        "out_shapes": [list(o.shape or ()) for o in outs],
        "out_dtypes": [str(o.dtype) for o in outs],
    }
    if backward_func is not None:
        # backward_func(*x_values, *out_grad_values) -> grads per x
        attrs["backward_callable_id"] = register_callable(backward_func)
    helper.append_op(
        type="py_func",
        inputs={"X": list(xs)},
        outputs={"Out": list(outs)},
        attrs=attrs,
    )
    return out

"""Reference layers.ops module parity: thin re-exports of activation
layers (reference python/paddle/fluid/layers/ops.py autogenerates these
from the op registry)."""

from .nn import (  # noqa: F401
    abs,
    ceil,
    cos,
    exp,
    floor,
    hard_shrink,
    logsigmoid,
    reciprocal,
    round,
    rsqrt,
    sigmoid,
    sin,
    softplus,
    softsign,
    sqrt,
    square,
    tanh,
    thresholded_relu,
)

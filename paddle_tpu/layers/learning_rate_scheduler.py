"""LR schedules as in-graph ops over a persistable step counter.

Reference: python/paddle/fluid/layers/learning_rate_scheduler.py — each
schedule creates a global step counter var `@LR_DECAY_COUNTER@`
(incremented once per executor run) and computes the lr from it with
ops, so the schedule travels with the Program (and with checkpoints).
"""

from __future__ import annotations

import math

from ..core.framework import default_main_program
from ..layer_helper import LayerHelper
from .tensor import create_global_var, fill_constant
from .control_flow import increment
from .nn import (
    cast,
    elementwise_div,
    elementwise_max,
    elementwise_min,
    elementwise_mul,
    elementwise_sub,
    elementwise_add,
    exp,
    pow as pow_layer,
    scale,
    sqrt,
    cos as cos_layer,
    where,
)

__all__ = [
    "noam_decay",
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "cosine_decay",
    "linear_lr_warmup",
]

_COUNTER_NAME = "@LR_DECAY_COUNTER@"


def _global_step():
    prog = default_main_program()
    gb = prog.global_block()
    if gb.has_var(_COUNTER_NAME):
        # counter already created+incremented this program
        return cast(gb.var(_COUNTER_NAME), "float32")
    counter = create_global_var(
        [1], 0, "float32", persistable=True, name=_COUNTER_NAME
    )
    increment(counter, value=1.0, in_place=True)
    return cast(counter, "float32")


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    step = _global_step()
    a = pow_layer(step, -0.5)
    b = elementwise_mul(step, fill_constant([1], "float32", warmup_steps ** -1.5))
    lr = scale(
        elementwise_min(a, b), scale=float(learning_rate) * (d_model ** -0.5)
    )
    return lr


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    ratio = scale(step, scale=1.0 / decay_steps)
    if staircase:
        from .nn import floor

        ratio = floor(ratio)
    return scale(elementwise_pow_const(decay_rate, ratio), scale=float(learning_rate))


def elementwise_pow_const(base, exponent_var):
    # base^x = exp(x * ln base)
    return exp(scale(exponent_var, scale=math.log(base)))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    ratio = scale(step, scale=1.0 / decay_steps)
    if staircase:
        from .nn import floor

        ratio = floor(ratio)
    return scale(exp(scale(ratio, scale=-decay_rate)), scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _global_step()
    ratio = scale(step, scale=1.0 / decay_steps)
    if staircase:
        from .nn import floor

        ratio = floor(ratio)
    denom = scale(ratio, scale=decay_rate, bias=1.0, bias_after_scale=True)
    return elementwise_div(fill_constant([1], "float32", learning_rate), denom)


def polynomial_decay(
    learning_rate, decay_steps, end_learning_rate=0.0001, power=1.0, cycle=False
):
    step = _global_step()
    if cycle:
        from .nn import ceil, elementwise_max as emax

        div = ceil(scale(step, scale=1.0 / decay_steps))
        div = elementwise_max(div, fill_constant([1], "float32", 1.0))
        decay_steps_var = scale(div, scale=float(decay_steps))
        frac = elementwise_div(step, decay_steps_var)
    else:
        capped = elementwise_min(step, fill_constant([1], "float32", decay_steps))
        frac = scale(capped, scale=1.0 / decay_steps)
    one_minus = scale(frac, scale=-1.0, bias=1.0)
    poly = pow_layer(one_minus, factor=power)
    return scale(poly, scale=learning_rate - end_learning_rate, bias=end_learning_rate)


def piecewise_decay(boundaries, values):
    step = _global_step()
    lr = fill_constant([1], "float32", values[-1])
    # select backwards so earlier boundaries win
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        from .control_flow import less_than

        c = less_than(step, fill_constant([1], "float32", float(b)))
        lr = where(c, fill_constant([1], "float32", v), lr)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _global_step()
    from .nn import floor

    epoch = floor(scale(step, scale=1.0 / step_each_epoch))
    frac = scale(epoch, scale=math.pi / epochs)
    return scale(
        scale(cos_layer(frac), scale=0.5, bias=0.5, bias_after_scale=True),
        scale=float(learning_rate),
    )


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _global_step()
    from .control_flow import less_than

    warm_lr = scale(
        step, scale=(end_lr - start_lr) / warmup_steps, bias=start_lr
    )
    if not hasattr(learning_rate, "name"):
        learning_rate = fill_constant([1], "float32", float(learning_rate))
    c = less_than(step, fill_constant([1], "float32", float(warmup_steps)))
    return where(c, warm_lr, learning_rate)

"""Sequence layers over dense padded tensors. Reference:
python/paddle/fluid/layers/sequence_lod.py (LoD-based)."""

from __future__ import annotations

from ..layer_helper import LayerHelper
from .nn import _out

__all__ = [
    "sequence_pool",
    "sequence_softmax",
    "sequence_reshape",
    "sequence_concat",
    "sequence_reverse",
    "sequence_mask",
    "sequence_pad",
    "sequence_unpad",
    "sequence_expand",
]


def sequence_pool(input, pool_type, length=None, is_test=False):
    helper = LayerHelper("sequence_pool")
    shp = tuple(input.shape or ())
    out_shape = (shp[0],) + tuple(shp[2:]) if len(shp) >= 2 else shp
    out = _out(helper, input, shape=out_shape)
    max_index = _out(helper, input, shape=(0,), dtype="int32", stop_gradient=True)
    inputs = {"X": [input]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="sequence_pool",
        inputs=inputs,
        outputs={"Out": [out], "MaxIndex": [max_index]},
        attrs={"pooltype": pool_type.upper(), "is_test": is_test},
    )
    return out


def sequence_softmax(input, length=None, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = _out(helper, input, shape=input.shape)
    inputs = {"X": [input]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="sequence_softmax", inputs=inputs, outputs={"Out": [out]}
    )
    return out


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape")
    shp = tuple(input.shape or ())
    out = _out(helper, input, shape=(shp[0] if shp else -1, -1, new_dim))
    helper.append_op(
        type="sequence_reshape",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"new_dim": new_dim},
    )
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", name=name)
    out = _out(helper, input[0], shape=None)
    helper.append_op(type="sequence_concat", inputs={"X": list(input)}, outputs={"Out": [out]})
    return out


def sequence_reverse(x, length=None, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = _out(helper, x, shape=x.shape)
    inputs = {"X": [x]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(type="sequence_reverse", inputs=inputs, outputs={"Y": [out]})
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    shp = tuple(x.shape or ()) + (maxlen if maxlen else -1,)
    out = _out(helper, x, shape=shp, dtype=dtype, stop_gradient=True)
    helper.append_op(
        type="sequence_mask",
        inputs={"X": [x]},
        outputs={"Y": [out]},
        attrs={"maxlen": maxlen or -1, "out_dtype": dtype},
    )
    return out


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    helper = LayerHelper("sequence_pad", name=name)
    out = _out(helper, x, shape=x.shape)
    ln = _out(helper, x, shape=(x.shape[0] if x.shape else -1,), dtype="int64", stop_gradient=True)
    inputs = {"X": [x], "PadValue": [pad_value]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="sequence_pad", inputs=inputs, outputs={"Out": [out], "Length": [ln]}
    )
    return out, ln


def sequence_unpad(x, length, name=None):
    helper = LayerHelper("sequence_unpad", name=name)
    out = _out(helper, x, shape=x.shape)
    helper.append_op(
        type="sequence_unpad",
        inputs={"X": [x], "Length": [length]},
        outputs={"Out": [out]},
    )
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out = _out(helper, x, shape=y.shape)
    helper.append_op(
        type="sequence_expand",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"ref_level": ref_level},
    )
    return out

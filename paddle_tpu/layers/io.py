"""Data-input layer. Reference: python/paddle/fluid/layers/io.py data()."""

from __future__ import annotations

from ..core.framework import default_main_program, default_startup_program


def data(
    name,
    shape,
    append_batch_size: bool = True,
    dtype="float32",
    lod_level: int = 0,
    type=None,
    stop_gradient: bool = True,
):
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    main = default_main_program()
    var = main.global_block().create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        is_data=True,
        stop_gradient=stop_gradient,
        lod_level=lod_level,
    )
    # also declare in startup program for reference parity (harmless)
    default_startup_program().global_block().create_var(
        name=name, shape=shape, dtype=dtype, is_data=True, stop_gradient=True
    )
    return var

"""Neural-network layers. Reference: python/paddle/fluid/layers/nn.py
(13.9k LoC). Each function emits ops into the default main program and
sets output shapes eagerly (the reference defers to C++ InferShape).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.framework import Variable, convert_dtype
from ..initializer import ConstantInitializer, NormalInitializer, XavierInitializer
from ..layer_helper import LayerHelper

__all__ = [
    "fc",
    "embedding",
    "conv2d",
    "conv2d_transpose",
    "pool2d",
    "adaptive_pool2d",
    "batch_norm",
    "layer_norm",
    "group_norm",
    "instance_norm",
    "dropout",
    "softmax",
    "log_softmax",
    "matmul",
    "relu",
    "sigmoid",
    "tanh",
    "sqrt",
    "rsqrt",
    "exp",
    "log",
    "square",
    "abs",
    "gelu",
    "leaky_relu",
    "elu",
    "relu6",
    "softplus",
    "softsign",
    "swish",
    "hard_sigmoid",
    "hard_swish",
    "logsigmoid",
    "erf",
    "floor",
    "ceil",
    "round",
    "reciprocal",
    "sin",
    "cos",
    "stanh",
    "thresholded_relu",
    "hard_shrink",
    "soft_relu",
    "pow",
    "prelu",
    "maxout",
    "l2_normalize",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "elementwise_mod",
    "_elementwise_binary",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "mean",
    "scale",
    "clip",
    "clip_by_norm",
    "cast",
    "one_hot",
    "topk",
    "argmax",
    "argmin",
    "argsort",
    "unsqueeze",
    "squeeze",
    "flatten",
    "reshape",
    "transpose",
    "split",
    "slice",
    "shape",
    "pad",
    "pad2d",
    "where",
    "gather",
    "gather_nd",
    "scatter",
    "expand",
    "expand_as",
    "stack",
    "unstack",
    "cumsum",
    "image_resize",
    "resize_nearest",
    "resize_bilinear",
    "shard_index",
    "_getitem",
    "shuffle_channel",
]


def _maybe_eager(op_type, ins, out_slots, attrs):
    """Dygraph bridge: when eager mode is on and the inputs are
    VarBase, run the op NOW through the tape-recording tracer
    (dygraph/base._trace) instead of appending to a Program — the
    reference's imperative tracer dispatch that lets fluid.layers.*
    work inside dygraph code (and converted @declarative functions).
    Returns the flat output list, or None for the graph path."""
    from ..core.dygraph import in_dygraph_mode

    if not in_dygraph_mode():
        return None
    from ..dygraph.base import VarBase, _trace

    if not any(isinstance(v, VarBase)
               for vs in ins.values() for v in vs if v is not None):
        return None
    ins = {s: [v for v in vs if v is not None] for s, vs in ins.items()}
    return _trace(op_type, ins, list(out_slots), dict(attrs))


def _out(helper, x, shape=None, dtype=None, stop_gradient=False):
    return helper.create_variable_for_type_inference(
        dtype=dtype or (x.dtype if isinstance(x, Variable) else "float32"),
        shape=shape if shape is not None else (x.shape if isinstance(x, Variable) else None),
        stop_gradient=stop_gradient,
    )


# --------------------------------------------------------------------------
# core layers
# --------------------------------------------------------------------------


def fc(
    input,
    size,
    num_flatten_dims=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    """Reference layers/nn.py fc: W [prod(in[nfd:]), size], mul op +
    bias + activation."""
    helper = LayerHelper(
        "fc", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    inputs = input if isinstance(input, (list, tuple)) else [input]
    mul_results = []
    for inp in inputs:
        in_features = int(np.prod(inp.shape[num_flatten_dims:]))
        w = helper.create_parameter(
            helper.param_attr, [in_features, size], inp.dtype
        )
        out_shape = tuple(inp.shape[:num_flatten_dims]) + (size,)
        tmp = _out(helper, inp, shape=out_shape)
        helper.append_op(
            type="mul",
            inputs={"X": [inp], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = _out(helper, mul_results[0], shape=mul_results[0].shape)
        helper.append_op(
            type="sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]}
        )
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(
    input,
    size,
    is_sparse=False,
    is_distributed=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
):
    """Reference layers/nn.py embedding (lookup_table op). is_sparse is
    advisory — TPU gradients use dense scatter-add (XLA handles it)."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(
        helper.param_attr, list(size), dtype, default_initializer=XavierInitializer()
    )
    ids_shape = tuple(input.shape) if input.shape else (-1,)
    if len(ids_shape) >= 2 and ids_shape[-1] == 1:
        out_shape = ids_shape[:-1] + (size[1],)
    else:
        out_shape = ids_shape + (size[1],)
    out = _out(helper, input, shape=out_shape, dtype=dtype)
    helper.append_op(
        type="lookup_table",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [out]},
        attrs={
            "padding_idx": -1 if padding_idx is None else int(padding_idx),
            "is_sparse": is_sparse,
            "is_distributed": is_distributed,
        },
    )
    return out


def _conv_out_size(i, k, p, s, d=1):
    if i is None or i < 0:
        return -1
    ke = d * (k - 1) + 1
    return (i + 2 * p - ke) // s + 1


def conv2d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=1,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
    data_format="NCHW",
):
    helper = LayerHelper(
        "conv2d", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"conv2d: data_format must be NCHW/NHWC, "
                         f"got {data_format!r}")
    if data_format == "NCHW":
        n, c, h, w_ = input.shape
    else:
        n, h, w_, c = input.shape
    fs = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
    st = stride if isinstance(stride, (list, tuple)) else [stride] * 2
    pd = padding if isinstance(padding, (list, tuple)) else [padding] * 2
    dl = dilation if isinstance(dilation, (list, tuple)) else [dilation] * 2
    filter_shape = [num_filters, c // groups, fs[0], fs[1]]
    std = (2.0 / (fs[0] * fs[1] * c)) ** 0.5
    filt = helper.create_parameter(
        helper.param_attr,
        filter_shape,
        input.dtype,
        default_initializer=NormalInitializer(0.0, std),
    )
    oh = _conv_out_size(h, fs[0], pd[0], st[0], dl[0])
    ow = _conv_out_size(w_, fs[1], pd[1], st[1], dl[1])
    out_shape = ((n, num_filters, oh, ow) if data_format == "NCHW"
                 else (n, oh, ow, num_filters))
    out = _out(helper, input, shape=out_shape)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [filt]},
        outputs={"Output": [out]},
        attrs={
            "strides": list(st),
            "paddings": list(pd),
            "dilations": list(dl),
            "groups": groups,
            "data_format": data_format,
        },
    )
    if helper.bias_attr is not False:
        b = helper.create_parameter(
            helper.bias_attr, [num_filters], input.dtype, is_bias=True
        )
        out2 = _out(helper, out, shape=out.shape)
        helper.append_op(
            type="elementwise_add",
            inputs={"X": [out], "Y": [b]},
            outputs={"Out": [out2]},
            attrs={"axis": 1 if data_format == "NCHW" else 3},
        )
        out = out2
    return helper.append_activation(out)


def conv2d_transpose(
    input,
    num_filters,
    output_size=None,
    filter_size=None,
    padding=0,
    stride=1,
    dilation=1,
    groups=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
    data_format="NCHW",
):
    helper = LayerHelper(
        "conv2d_transpose", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"conv2d_transpose: data_format must be "
                         f"NCHW/NHWC, got {data_format!r}")
    if data_format == "NCHW":
        n, c, h, w_ = input.shape
    else:
        n, h, w_, c = input.shape
    st = stride if isinstance(stride, (list, tuple)) else [stride] * 2
    pd = padding if isinstance(padding, (list, tuple)) else [padding] * 2
    dl = dilation if isinstance(dilation, (list, tuple)) else [dilation] * 2
    os_ = None
    if output_size is not None:
        os_ = (list(output_size) if isinstance(output_size, (list, tuple))
               else [output_size] * 2)
    if filter_size is None:
        # reference conv2d_transpose derives the kernel from
        # output_size: k_eff = out - (in-1)*stride + 2*pad
        if os_ is None:
            raise ValueError("conv2d_transpose: provide filter_size or "
                             "output_size")
        if h is None or h < 0 or w_ is None or w_ < 0:
            raise ValueError(
                "conv2d_transpose: deriving filter_size from output_size "
                "needs static input spatial dims")
        fs = [(os_[0] - (h - 1) * st[0] + 2 * pd[0] - 1) // dl[0] + 1,
              (os_[1] - (w_ - 1) * st[1] + 2 * pd[1] - 1) // dl[1] + 1]
        if fs[0] <= 0 or fs[1] <= 0:
            raise ValueError(
                f"conv2d_transpose: output_size {os_} too small for "
                f"input ({h}, {w_}) with stride {st} / padding {pd} "
                f"(derived kernel {fs})")
    else:
        fs = (filter_size if isinstance(filter_size, (list, tuple))
              else [filter_size] * 2)
    filter_shape = [c, num_filters // groups, fs[0], fs[1]]
    filt = helper.create_parameter(helper.param_attr, filter_shape, input.dtype)

    def _o(i, k, p, s, d):
        ke = d * (k - 1) + 1
        return -1 if (i is None or i < 0) else (i - 1) * s - 2 * p + ke

    oh = _o(h, fs[0], pd[0], st[0], dl[0])
    ow = _o(w_, fs[1], pd[1], st[1], dl[1])
    if os_ is not None and filter_size is None:
        # derived-kernel path: the floor division in the fs derivation
        # can make the formula output smaller than the requested
        # output_size when dilation > 1; the op's `extra` padding
        # guarantees the runtime shape IS output_size, so the static
        # metadata must match it (round-4 advisor finding)
        oh, ow = os_
    elif os_ is not None:
        # output_size disambiguates the stride>1 output within
        # [formula, formula + stride - 1] (reference conv_transpose
        # semantics); the op lowering pads the extra rows/cols
        for i, (o_want, o_have, s_i) in enumerate(
                zip(os_, (oh, ow), st)):
            if o_have >= 0 and not (0 <= o_want - o_have < s_i):
                raise ValueError(
                    f"conv2d_transpose: output_size[{i}]={o_want} not in "
                    f"[{o_have}, {o_have + s_i - 1}]")
        oh, ow = os_
    out_shape = ((n, num_filters, oh, ow) if data_format == "NCHW"
                 else (n, oh, ow, num_filters))
    out = _out(helper, input, shape=out_shape)
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input], "Filter": [filt]},
        outputs={"Output": [out]},
        attrs={"strides": list(st), "paddings": list(pd),
               "dilations": list(dl), "groups": groups,
               "data_format": data_format,
               **({"output_size": list(os_)} if os_ is not None else {})},
    )
    if helper.bias_attr is not False:
        b = helper.create_parameter(
            helper.bias_attr, [num_filters], input.dtype, is_bias=True
        )
        out2 = _out(helper, out, shape=out.shape)
        helper.append_op(
            type="elementwise_add",
            inputs={"X": [out], "Y": [b]},
            outputs={"Out": [out2]},
            attrs={"axis": 1 if data_format == "NCHW" else 3},
        )
        out = out2
    return helper.append_activation(out)


def pool2d(
    input,
    pool_size=-1,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    use_cudnn=True,
    ceil_mode=False,
    name=None,
    exclusive=True,
    data_format="NCHW",
):
    helper = LayerHelper("pool2d", name=name)
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"pool2d: data_format must be NCHW/NHWC, "
                         f"got {data_format!r}")
    if data_format == "NCHW":
        n, c, h, w_ = input.shape
    else:
        n, h, w_, c = input.shape
    ks = pool_size if isinstance(pool_size, (list, tuple)) else [pool_size] * 2
    st = pool_stride if isinstance(pool_stride, (list, tuple)) else [pool_stride] * 2
    pd = pool_padding if isinstance(pool_padding, (list, tuple)) else [pool_padding] * 2
    if global_pooling:
        out_shape = (n, c, 1, 1) if data_format == "NCHW" else (n, 1, 1, c)
    else:
        oh = _conv_out_size(h, ks[0], pd[0], st[0])
        ow = _conv_out_size(w_, ks[1], pd[1], st[1])
        out_shape = ((n, c, oh, ow) if data_format == "NCHW"
                     else (n, oh, ow, c))
    out = _out(helper, input, shape=out_shape)
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": list(ks),
            "strides": list(st),
            "paddings": list(pd),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
            "data_format": data_format,
        },
    )
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", name=None):
    helper = LayerHelper("adaptive_pool2d", name=name)
    n, c = input.shape[0], input.shape[1]
    ks = pool_size if isinstance(pool_size, (list, tuple)) else [pool_size] * 2
    out = _out(helper, input, shape=(n, c, ks[0], ks[1]))
    helper.append_op(
        type="pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": list(ks), "adaptive": True},
    )
    return out


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    do_model_average_for_mean_and_var=False,
    use_global_stats=False,
):
    helper = LayerHelper(
        "batch_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(
        helper.param_attr, [c], input.dtype, default_initializer=ConstantInitializer(1.0)
    )
    bias = helper.create_parameter(helper.bias_attr, [c], input.dtype, is_bias=True)
    from ..core.framework import unique_name

    mean_name = moving_mean_name or unique_name.generate(f"{helper.name}.mean")
    var_name = moving_variance_name or unique_name.generate(f"{helper.name}.var")
    gb = helper.main_program.global_block()
    mean = gb.create_var(
        name=mean_name, shape=[c], dtype=input.dtype, persistable=True, stop_gradient=True
    )
    variance = gb.create_var(
        name=var_name, shape=[c], dtype=input.dtype, persistable=True, stop_gradient=True
    )
    helper.set_variable_initializer(mean, ConstantInitializer(0.0))
    helper.set_variable_initializer(variance, ConstantInitializer(1.0))
    saved_mean = _out(helper, input, shape=(c,), stop_gradient=True)
    saved_var = _out(helper, input, shape=(c,), stop_gradient=True)
    out = _out(helper, input, shape=input.shape)
    helper.append_op(
        type="batch_norm",
        inputs={
            "X": [input],
            "Scale": [scale],
            "Bias": [bias],
            "Mean": [mean],
            "Variance": [variance],
        },
        outputs={
            "Y": [out],
            "MeanOut": [mean],
            "VarianceOut": [variance],
            "SavedMean": [saved_mean],
            "SavedVariance": [saved_var],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(out)


def layer_norm(
    input,
    scale=True,
    shift=True,
    begin_norm_axis=1,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper(
        "layer_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            helper.param_attr,
            norm_shape,
            input.dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(
            helper.bias_attr, norm_shape, input.dtype, is_bias=True
        )
        inputs["Bias"] = [b]
    lead = int(np.prod([d for d in input.shape[:begin_norm_axis]])) if all(
        d is not None and d > 0 for d in input.shape[:begin_norm_axis]
    ) else -1
    out = _out(helper, input, shape=input.shape)
    mean = _out(helper, input, shape=(lead,), stop_gradient=True)
    var = _out(helper, input, shape=(lead,), stop_gradient=True)
    helper.append_op(
        type="layer_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"begin_norm_axis": begin_norm_axis, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def group_norm(
    input, groups, epsilon=1e-5, param_attr=None, bias_attr=None, act=None, name=None
):
    helper = LayerHelper(
        "group_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    c = input.shape[1]
    inputs = {"X": [input]}
    s = helper.create_parameter(
        helper.param_attr, [c], input.dtype, default_initializer=ConstantInitializer(1.0)
    )
    b = helper.create_parameter(helper.bias_attr, [c], input.dtype, is_bias=True)
    inputs["Scale"], inputs["Bias"] = [s], [b]
    out = _out(helper, input, shape=input.shape)
    mean = _out(helper, input, shape=(input.shape[0], groups), stop_gradient=True)
    var = _out(helper, input, shape=(input.shape[0], groups), stop_gradient=True)
    helper.append_op(
        type="group_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"groups": groups, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None, name=None):
    helper = LayerHelper("instance_norm", param_attr=param_attr, bias_attr=bias_attr, name=name)
    c = input.shape[1]
    s = helper.create_parameter(
        helper.param_attr, [c], input.dtype, default_initializer=ConstantInitializer(1.0)
    )
    b = helper.create_parameter(helper.bias_attr, [c], input.dtype, is_bias=True)
    out = _out(helper, input, shape=input.shape)
    sm = _out(helper, input, shape=(input.shape[0], c), stop_gradient=True)
    sv = _out(helper, input, shape=(input.shape[0], c), stop_gradient=True)
    helper.append_op(
        type="instance_norm",
        inputs={"X": [input], "Scale": [s], "Bias": [b]},
        outputs={"Y": [out], "SavedMean": [sm], "SavedVariance": [sv]},
        attrs={"epsilon": epsilon},
    )
    return out


def dropout(
    x,
    dropout_prob,
    is_test=False,
    seed=None,
    name=None,
    dropout_implementation="downgrade_in_infer",
):
    helper = LayerHelper("dropout", name=name)
    out = _out(helper, x, shape=x.shape)
    mask = _out(helper, x, shape=x.shape, dtype="uint8", stop_gradient=True)
    helper.append_op(
        type="dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed or 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def softmax(input, use_cudnn=False, name=None, axis=-1):
    helper = LayerHelper("softmax", name=name)
    out = _out(helper, input, shape=input.shape)
    helper.append_op(
        type="softmax", inputs={"X": [input]}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", name=name)
    out = _out(helper, input, shape=input.shape)
    helper.append_op(
        type="log_softmax", inputs={"X": [input]}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    xs = list(x.shape) if x.shape else []
    ys = list(y.shape) if y.shape else []
    shape = None
    if len(xs) >= 2 and len(ys) >= 2:
        m = xs[-1] if transpose_x else xs[-2]
        n = ys[-2] if transpose_y else ys[-1]
        lead = xs[:-2] if len(xs) >= len(ys) else ys[:-2]
        shape = tuple(lead) + (m, n)
    out = _out(helper, x, shape=shape)
    helper.append_op(
        type="matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y, "alpha": alpha},
    )
    return out


# --------------------------------------------------------------------------
# activations (generated)
# --------------------------------------------------------------------------


def _make_activation(op_type, extra_defaults=None):
    def act_fn(x, name=None, **kwargs):
        helper = LayerHelper(op_type, name=name)
        attrs = dict(extra_defaults or {})
        for k, v in kwargs.items():
            attrs[k] = v
        out = _out(helper, x, shape=x.shape)
        helper.append_op(
            type=op_type, inputs={"X": [x]}, outputs={"Out": [out]}, attrs=attrs
        )
        return out

    act_fn.__name__ = op_type
    return act_fn


relu = _make_activation("relu")
sigmoid = _make_activation("sigmoid")
tanh = _make_activation("tanh")
sqrt = _make_activation("sqrt")
rsqrt = _make_activation("rsqrt")
exp = _make_activation("exp")
log = _make_activation("log")
square = _make_activation("square")
abs = _make_activation("abs")
gelu = _make_activation("gelu")
leaky_relu = _make_activation("leaky_relu", {"alpha": 0.02})
elu = _make_activation("elu", {"alpha": 1.0})
relu6 = _make_activation("relu6", {"threshold": 6.0})
softplus = _make_activation("softplus")
softsign = _make_activation("softsign")
swish = _make_activation("swish", {"beta": 1.0})
hard_sigmoid = _make_activation("hard_sigmoid", {"slope": 0.2, "offset": 0.5})
hard_swish = _make_activation("hard_swish")
logsigmoid = _make_activation("logsigmoid")
erf = _make_activation("erf")
floor = _make_activation("floor")
ceil = _make_activation("ceil")
round = _make_activation("round")
reciprocal = _make_activation("reciprocal")
sin = _make_activation("sin")
cos = _make_activation("cos")
stanh = _make_activation("stanh")
thresholded_relu = _make_activation("thresholded_relu", {"threshold": 1.0})
hard_shrink = _make_activation("hard_shrink", {"threshold": 0.5})
soft_relu = _make_activation("soft_relu", {"threshold": 40.0})


def pow(x, factor=1.0, name=None):
    helper = LayerHelper("pow", name=name)
    out = _out(helper, x, shape=x.shape)
    helper.append_op(
        type="pow", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"factor": factor}
    )
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(
        helper.param_attr,
        alpha_shape,
        x.dtype,
        default_initializer=ConstantInitializer(0.25),
    )
    out = _out(helper, x, shape=x.shape)
    helper.append_op(
        type="prelu",
        inputs={"X": [x], "Alpha": [alpha]},
        outputs={"Out": [out]},
        attrs={"mode": mode},
    )
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    n, c, h, w = x.shape
    out = _out(helper, x, shape=(n, c // groups, h, w))
    helper.append_op(
        type="maxout", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"groups": groups}
    )
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = _out(helper, x, shape=x.shape)
    norm = _out(helper, x, shape=None, stop_gradient=True)
    helper.append_op(
        type="l2_normalize",
        inputs={"X": [x]},
        outputs={"Out": [out], "Norm": [norm]},
        attrs={"axis": axis, "epsilon": epsilon},
    )
    return out


def shuffle_channel(x, group, name=None):
    helper = LayerHelper("shuffle_channel", name=name)
    out = _out(helper, x, shape=x.shape)
    helper.append_op(
        type="shuffle_channel", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"group": group}
    )
    return out


# --------------------------------------------------------------------------
# elementwise / reduce / misc math
# --------------------------------------------------------------------------


def _make_elementwise(op_type):
    def ew_fn(x, y, axis=-1, act=None, name=None):
        return _elementwise_binary(x, y, op_type, axis=axis, act=act, name=name)

    ew_fn.__name__ = op_type
    return ew_fn


def _elementwise_binary(x, y, op_type, axis=-1, act=None, name=None, reverse=False):
    helper = LayerHelper(op_type, act=act, name=name)
    # scalar operands -> scale-op shortcuts (keeps graphs small)
    if not isinstance(y, Variable):
        c = float(y)
        if not reverse:
            if op_type == "elementwise_add":
                return scale(x, scale=1.0, bias=c)
            if op_type == "elementwise_sub":
                return scale(x, scale=1.0, bias=-c)
            if op_type == "elementwise_mul":
                return scale(x, scale=c)
            if op_type == "elementwise_div":
                return scale(x, scale=1.0 / c)
            if op_type == "elementwise_pow":
                return pow(x, factor=c)
        else:
            if op_type == "elementwise_sub":
                return scale(x, scale=-1.0, bias=c)
            if op_type == "elementwise_div":
                y_var = fill_constant_like(x, c)
                return _elementwise_binary(y_var, x, "elementwise_div")
        y = fill_constant_like(x, c)
    if not isinstance(x, Variable):
        x = fill_constant_like(y, float(x))
    xs, ys = x.shape, y.shape
    shape = xs if (xs and ys and len(xs) >= len(ys)) else ys
    out = _out(helper, x, shape=shape)
    helper.append_op(
        type=op_type,
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return helper.append_activation(out)


elementwise_add = _make_elementwise("elementwise_add")
elementwise_sub = _make_elementwise("elementwise_sub")
elementwise_mul = _make_elementwise("elementwise_mul")
elementwise_div = _make_elementwise("elementwise_div")
elementwise_max = _make_elementwise("elementwise_max")
elementwise_min = _make_elementwise("elementwise_min")
elementwise_pow = _make_elementwise("elementwise_pow")
elementwise_mod = _make_elementwise("elementwise_mod")


def fill_constant_like(x, value):
    from .tensor import fill_constant_batch_size_like

    if x.shape and any(d in (-1, None) for d in x.shape):
        return fill_constant_batch_size_like(x, list(x.shape), x.dtype, value)
    from .tensor import fill_constant

    return fill_constant(list(x.shape or ()), x.dtype, value)


def _make_reduce(op_type):
    def red_fn(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        if dim is None:
            attrs = {"reduce_all": True, "keep_dim": keep_dim}
            shape = ()
        else:
            dims = dim if isinstance(dim, (list, tuple)) else [dim]
            attrs = {"dim": list(dims), "keep_dim": keep_dim, "reduce_all": False}
            if input.shape:
                nd = len(input.shape)
                dd = {d % nd for d in dims}
                if keep_dim:
                    shape = tuple(1 if i in dd else s for i, s in enumerate(input.shape))
                else:
                    shape = tuple(s for i, s in enumerate(input.shape) if i not in dd)
            else:
                shape = None
        out = _out(helper, input, shape=shape)
        helper.append_op(
            type=op_type, inputs={"X": [input]}, outputs={"Out": [out]}, attrs=attrs
        )
        return out

    red_fn.__name__ = op_type
    return red_fn


reduce_sum = _make_reduce("reduce_sum")
reduce_mean = _make_reduce("reduce_mean")
reduce_max = _make_reduce("reduce_max")
reduce_min = _make_reduce("reduce_min")
reduce_prod = _make_reduce("reduce_prod")


def mean(x, name=None):
    eager = _maybe_eager("mean", {"X": [x]}, ["Out"], {})
    if eager is not None:
        return eager[0]
    helper = LayerHelper("mean", name=name)
    out = _out(helper, x, shape=())
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = _out(helper, x, shape=x.shape)
    helper.append_op(
        type="scale",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"scale": float(scale), "bias": float(bias), "bias_after_scale": bias_after_scale},
    )
    return helper.append_activation(out)


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = _out(helper, x, shape=x.shape)
    helper.append_op(
        type="clip", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"min": min, "max": max}
    )
    return out


def clip_by_norm(x, max_norm, name=None):
    # composite: x * min(1, max_norm / ||x||)
    norm_sq = reduce_sum(square(x))
    norm = sqrt(norm_sq)
    factor = elementwise_min(
        scale(reciprocal(elementwise_max(norm, fill_constant_like(norm, 1e-12))), scale=float(max_norm)),
        fill_constant_like(norm, 1.0),
    )
    return elementwise_mul(x, factor, axis=-1)


def cast(x, dtype):
    helper = LayerHelper("cast")
    dtype = convert_dtype(dtype)
    out = _out(helper, x, shape=x.shape, dtype=dtype)
    helper.append_op(
        type="cast",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"out_dtype": dtype, "in_dtype": x.dtype},
    )
    return out


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot")
    shp = tuple(input.shape or ())
    if len(shp) >= 2 and shp[-1] == 1:
        out_shape = shp[:-1] + (depth,)
    else:
        out_shape = shp + (depth,)
    out = _out(helper, input, shape=out_shape, dtype="float32", stop_gradient=True)
    helper.append_op(
        type="one_hot", inputs={"X": [input]}, outputs={"Out": [out]}, attrs={"depth": depth}
    )
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    shp = tuple(input.shape or ())
    out_shape = shp[:-1] + (k,) if shp else None
    vals = _out(helper, input, shape=out_shape)
    idx = _out(helper, input, shape=out_shape, dtype="int64", stop_gradient=True)
    helper.append_op(
        type="top_k",
        inputs={"X": [input]},
        outputs={"Out": [vals], "Indices": [idx]},
        attrs={"k": k},
    )
    return vals, idx


def argmax(x, axis=0, name=None):
    helper = LayerHelper("arg_max", name=name)
    shp = tuple(x.shape or ())
    out_shape = tuple(s for i, s in enumerate(shp) if i != axis % len(shp)) if shp else None
    out = _out(helper, x, shape=out_shape, dtype="int64", stop_gradient=True)
    helper.append_op(
        type="arg_max", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return out


def argmin(x, axis=0, name=None):
    helper = LayerHelper("arg_min", name=name)
    shp = tuple(x.shape or ())
    out_shape = tuple(s for i, s in enumerate(shp) if i != axis % len(shp)) if shp else None
    out = _out(helper, x, shape=out_shape, dtype="int64", stop_gradient=True)
    helper.append_op(
        type="arg_min", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return out


def argsort(x, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = _out(helper, x, shape=x.shape)
    idx = _out(helper, x, shape=x.shape, dtype="int64", stop_gradient=True)
    helper.append_op(
        type="argsort",
        inputs={"X": [x]},
        outputs={"Out": [out], "Indices": [idx]},
        attrs={"axis": axis, "descending": descending},
    )
    return out, idx


# --------------------------------------------------------------------------
# shape manipulation
# --------------------------------------------------------------------------


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    coerced = []
    for s in shape:
        try:
            coerced.append(int(s))
        except (TypeError, ValueError):
            # the reference fluid.layers.reshape accepts Variable dims;
            # this build is static-shape by design (SURVEY §2 LoDTensor
            # stance), so fail loudly instead of a confusing TypeError
            raise NotImplementedError(
                "reshape: Variable entries in `shape` are unsupported in "
                "the static-shape TPU build; pass python ints (got "
                f"{type(s).__name__})")
    shape = coerced
    eager = _maybe_eager("reshape2", {"X": [x]}, ["Out", "XShape"],
                         {"shape": shape})
    if eager is not None:
        out = eager[0]
        if act:
            out = _maybe_eager(act, {"X": [out]}, ["Out"], {})[0]
        return out
    helper = LayerHelper("reshape2", act=act, name=name)
    new_shape = []
    for i, s in enumerate(shape):
        if s == 0:
            new_shape.append(x.shape[i] if x.shape else -1)
        else:
            new_shape.append(s)
    out = _out(helper, x, shape=tuple(new_shape))
    xshape = _out(helper, x, shape=(0,), stop_gradient=True)
    helper.append_op(
        type="reshape2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"shape": list(shape)},
    )
    return helper.append_activation(out)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    shp = tuple(x.shape[p] for p in perm) if x.shape else None
    out = _out(helper, x, shape=shp)
    xshape = _out(helper, x, shape=(0,), stop_gradient=True)
    helper.append_op(
        type="transpose2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axis": list(perm)},
    )
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", name=name)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    rest = int(np.prod(x.shape[axis:]))
    out = _out(helper, x, shape=(lead if lead > 0 else -1, rest))
    xshape = _out(helper, x, shape=(0,), stop_gradient=True)
    helper.append_op(
        type="flatten2",
        inputs={"X": [x]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axis": axis},
    )
    return out


def squeeze(input, axes, name=None):
    helper = LayerHelper("squeeze2", name=name)
    shp = list(input.shape or ())
    for a in sorted([a % len(shp) for a in axes], reverse=True):
        shp.pop(a)
    out = _out(helper, input, shape=tuple(shp))
    xshape = _out(helper, input, shape=(0,), stop_gradient=True)
    helper.append_op(
        type="squeeze2",
        inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axes": list(axes)},
    )
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    shp = list(input.shape or ())
    for a in sorted(axes):
        shp.insert(a if a >= 0 else a + len(shp) + 1, 1)
    out = _out(helper, input, shape=tuple(shp))
    xshape = _out(helper, input, shape=(0,), stop_gradient=True)
    helper.append_op(
        type="unsqueeze2",
        inputs={"X": [input]},
        outputs={"Out": [out], "XShape": [xshape]},
        attrs={"axes": list(axes)},
    )
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    shp = list(input.shape or ())
    d = dim % len(shp) if shp else dim
    if isinstance(num_or_sections, int):
        n = num_or_sections
        sections = []
        sizes = [shp[d] // n] * n if shp and shp[d] > 0 else [-1] * n
    else:
        sections = list(num_or_sections)
        n = len(sections)
        sizes = sections
    outs = []
    for i in range(n):
        s = list(shp)
        if s:
            s[d] = sizes[i]
        outs.append(_out(helper, input, shape=tuple(s)))
    helper.append_op(
        type="split",
        inputs={"X": [input]},
        outputs={"Out": outs},
        attrs={"axis": dim, "sections": sections, "num": 0 if sections else n},
    )
    return outs


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    shp = list(input.shape or ())
    for a, s, e in zip(axes, starts, ends):
        if shp and shp[a] and shp[a] > 0:
            lo = max(s if s >= 0 else shp[a] + s, 0)
            hi = min(e if e >= 0 else shp[a] + e, shp[a])
            shp[a] = max(hi - lo, 0)
    out = _out(helper, input, shape=tuple(shp))
    helper.append_op(
        type="slice",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = _out(
        helper, input, shape=(len(input.shape or ()),), dtype="int32", stop_gradient=True
    )
    helper.append_op(type="shape", inputs={"Input": [input]}, outputs={"Out": [out]})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    shp = list(x.shape or ())
    pairs = list(zip(paddings[::2], paddings[1::2]))
    for i, (lo, hi) in enumerate(pairs):
        if shp and shp[i] and shp[i] > 0:
            shp[i] += lo + hi
    out = _out(helper, x, shape=tuple(shp))
    helper.append_op(
        type="pad",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "pad_value": pad_value},
    )
    return out


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0, name=None):
    helper = LayerHelper("pad2d", name=name)
    n, c, h, w = input.shape
    shp = (n, c, h + paddings[0] + paddings[1] if h and h > 0 else -1, w + paddings[2] + paddings[3] if w and w > 0 else -1)
    out = _out(helper, input, shape=shp)
    helper.append_op(
        type="pad2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"paddings": list(paddings), "mode": mode, "pad_value": pad_value},
    )
    return out


def where(condition, x, y, name=None):
    helper = LayerHelper("where", name=name)
    out = _out(helper, x, shape=x.shape)
    helper.append_op(
        type="where",
        inputs={"Condition": [condition], "X": [x], "Y": [y]},
        outputs={"Out": [out]},
    )
    return out


def gather(input, index, name=None):
    helper = LayerHelper("gather", name=name)
    shp = (index.shape[0] if index.shape else -1,) + tuple(input.shape[1:] or ())
    out = _out(helper, input, shape=shp)
    helper.append_op(
        type="gather", inputs={"X": [input], "Index": [index]}, outputs={"Out": [out]}
    )
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    k = index.shape[-1] if index.shape else 1
    shp = tuple(index.shape[:-1] or ()) + tuple(input.shape[k:] or ())
    out = _out(helper, input, shape=shp)
    helper.append_op(
        type="gather_nd", inputs={"X": [input], "Index": [index]}, outputs={"Out": [out]}
    )
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = _out(helper, input, shape=input.shape)
    helper.append_op(
        type="scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]},
        attrs={"overwrite": overwrite},
    )
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    shp = tuple(
        (s * t if s and s > 0 else -1) for s, t in zip(x.shape, expand_times)
    ) if x.shape else None
    out = _out(helper, x, shape=shp)
    helper.append_op(
        type="expand",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"expand_times": list(expand_times)},
    )
    return out


def expand_as(x, target_tensor, name=None):
    helper = LayerHelper("expand_as", name=name)
    out = _out(helper, x, shape=target_tensor.shape)
    helper.append_op(
        type="expand_as",
        inputs={"X": [x], "target_tensor": [target_tensor]},
        outputs={"Out": [out]},
    )
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack")
    xs = x if isinstance(x, (list, tuple)) else [x]
    shp = list(xs[0].shape or ())
    shp.insert(axis if axis >= 0 else axis + len(shp) + 1, len(xs))
    out = _out(helper, xs[0], shape=tuple(shp))
    helper.append_op(
        type="stack", inputs={"X": list(xs)}, outputs={"Y": [out]}, attrs={"axis": axis}
    )
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    shp = list(x.shape or ())
    n = num or shp[axis]
    oshp = tuple(s for i, s in enumerate(shp) if i != axis % len(shp))
    outs = [_out(helper, x, shape=oshp) for _ in range(n)]
    helper.append_op(
        type="unstack", inputs={"X": [x]}, outputs={"Y": outs}, attrs={"axis": axis, "num": n}
    )
    return outs


def cumsum(x, axis=-1, exclusive=False, reverse=False):
    helper = LayerHelper("cumsum")
    out = _out(helper, x, shape=x.shape)
    helper.append_op(
        type="cumsum",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": axis, "exclusive": exclusive, "reverse": reverse},
    )
    return out


def image_resize(input, out_shape=None, scale=None, resample="BILINEAR",
                 name=None, align_corners=True, align_mode=1,
                 actual_shape=None):
    """align_corners defaults TRUE and align_mode 1 like the reference
    interpolate API (layers/nn.py image_resize)."""
    op = "bilinear_interp" if resample.upper() == "BILINEAR" else "nearest_interp"
    helper = LayerHelper(op, name=name)
    n, c = input.shape[0], input.shape[1]
    if out_shape:
        oh, ow = out_shape
    elif scale:
        oh = int(input.shape[2] * scale)
        ow = int(input.shape[3] * scale)
    else:
        raise NotImplementedError(
            "image_resize: pass out_shape or scale — a runtime "
            "actual_shape Variable cannot size a static-shape build")
    out = _out(helper, input, shape=(n, c, oh, ow))
    helper.append_op(
        type=op,
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"out_h": oh, "out_w": ow, "scale": float(scale or 0.0),
               "align_corners": bool(align_corners),
               "align_mode": int(align_mode)},
    )
    return out


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True):
    return image_resize(input, out_shape, scale, "NEAREST", name,
                        align_corners=align_corners)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1):
    return image_resize(input, out_shape, scale, "BILINEAR", name,
                        align_corners=align_corners, align_mode=align_mode)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    helper = LayerHelper("shard_index")
    out = _out(helper, input, shape=input.shape, dtype=input.dtype, stop_gradient=True)
    helper.append_op(
        type="shard_index",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "index_num": index_num,
            "nshards": nshards,
            "shard_id": shard_id,
            "ignore_value": ignore_value,
        },
    )
    return out


def _getitem(var, item):
    """Basic indexing sugar for Variables (reference
    layers/math_op_patch slice monkeypatch). Supports ints and slices
    with unit step."""
    import builtins

    if not isinstance(item, tuple):
        item = (item,)
    axes, starts, ends, squeeze_axes = [], [], [], []
    for i, it in enumerate(item):
        if isinstance(it, int):
            axes.append(i)
            starts.append(it)
            ends.append(it + 1)
            squeeze_axes.append(i)
        elif isinstance(it, builtins.slice):
            if it.step not in (None, 1):
                raise NotImplementedError("strided getitem not supported")
            if it.start is None and it.stop is None:
                continue
            axes.append(i)
            starts.append(it.start or 0)
            ends.append(it.stop if it.stop is not None else 10**9)
        else:
            raise NotImplementedError(f"unsupported index {it!r}")
    out = slice(var, axes, starts, ends) if axes else var
    if squeeze_axes:
        out = squeeze(out, squeeze_axes)
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):
    """Sample a category per row of probs (reference layers/nn.py
    sampling_id over operators/sampling_id_op.cc)."""
    helper = LayerHelper("sampling_id")
    out = _out(helper, x, shape=tuple(x.shape[:-1]) if x.shape else None,
               dtype=dtype, stop_gradient=True)
    helper.append_op(
        type="sampling_id", inputs={"X": [x]}, outputs={"Out": [out]},
        attrs={"min": min, "max": max, "seed": seed, "dtype": dtype},
    )
    return out

"""Probability distributions over Program variables.

Reference: python/paddle/fluid/layers/distributions.py:28-640
(Distribution/Uniform/Normal/Categorical/MultivariateNormalDiag) —
pure-python classes composing graph ops; same here, over this
framework's layers. Methods return Variables, so sampling/entropy/KL
participate in autodiff and jit like any other op.
"""

from __future__ import annotations

import math

import numpy as np

from ..core.framework import Variable
from . import nn
from . import tensor as tensor_layers
from .control_flow import less_than
from .tensor import uniform_random, gaussian_random


def _to_var(v, like=None):
    if isinstance(v, Variable):
        return v
    arr = np.asarray(v, dtype="float32")
    return tensor_layers.assign(arr)


class Distribution:
    """Reference distributions.py:28."""

    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError


class Uniform(Distribution):
    """U(low, high); reference distributions.py:113."""

    def __init__(self, low, high):
        self.low = _to_var(low)
        self.high = _to_var(high)

    def sample(self, shape, seed=0):
        u = uniform_random(list(shape), min=0.0, max=1.0, seed=seed)
        return nn.elementwise_add(
            nn.elementwise_mul(u, nn.elementwise_sub(self.high, self.low)),
            self.low,
        )

    def log_prob(self, value):
        rng = nn.elementwise_sub(self.high, self.low)
        lb = nn.cast(less_than(self.low, value), "float32")
        ub = nn.cast(less_than(value, self.high), "float32")
        return nn.log(nn.elementwise_div(nn.elementwise_mul(lb, ub), rng))

    def entropy(self):
        return nn.log(nn.elementwise_sub(self.high, self.low))


class Normal(Distribution):
    """N(loc, scale); reference distributions.py:247."""

    def __init__(self, loc, scale):
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)

    def sample(self, shape, seed=0):
        z = gaussian_random(list(shape), mean=0.0, std=1.0, seed=seed)
        return nn.elementwise_add(nn.elementwise_mul(z, self.scale), self.loc)

    def entropy(self):
        c = 0.5 + 0.5 * math.log(2.0 * math.pi)
        return nn.scale(nn.log(self.scale), scale=1.0, bias=c)

    def log_prob(self, value):
        var = nn.elementwise_mul(self.scale, self.scale)
        d = nn.elementwise_sub(value, self.loc)
        return nn.scale(
            nn.elementwise_add(
                nn.elementwise_div(nn.elementwise_mul(d, d), nn.scale(var, 2.0)),
                nn.scale(nn.log(self.scale), 1.0, bias=0.5 * math.log(2.0 * math.pi)),
            ),
            -1.0,
        )

    def kl_divergence(self, other):
        # KL(self || other), reference distributions.py:382
        var_ratio = nn.elementwise_div(self.scale, other.scale)
        var_ratio = nn.elementwise_mul(var_ratio, var_ratio)
        d = nn.elementwise_div(
            nn.elementwise_sub(self.loc, other.loc), other.scale
        )
        t1 = nn.elementwise_mul(d, d)
        return nn.scale(
            nn.elementwise_sub(
                nn.elementwise_add(var_ratio, t1),
                nn.scale(nn.log(var_ratio), 1.0, bias=1.0),
            ),
            0.5,
        )


class Categorical(Distribution):
    """Categorical over logits; reference distributions.py:400."""

    def __init__(self, logits):
        self.logits = logits

    def _probs(self):
        return nn.softmax(self.logits)

    def entropy(self):
        p = self._probs()
        logp = nn.log(nn.elementwise_add(p, tensor_layers.fill_constant(
            [1], "float32", 1e-12)))
        neg = nn.reduce_sum(nn.elementwise_mul(p, logp), dim=-1)
        return nn.scale(neg, -1.0)

    def log_prob(self, value):
        ls = nn.log_softmax(self.logits)
        depth = int(self.logits.shape[-1])
        oh = nn.one_hot(value, depth)
        return nn.reduce_sum(nn.elementwise_mul(ls, oh), dim=-1)

    def kl_divergence(self, other):
        p = self._probs()
        eps = tensor_layers.fill_constant([1], "float32", 1e-12)
        logp = nn.log(nn.elementwise_add(p, eps))
        logq = nn.log(nn.elementwise_add(other._probs(), eps))
        return nn.reduce_sum(
            nn.elementwise_mul(p, nn.elementwise_sub(logp, logq)), dim=-1
        )

    def sample(self, shape=None, seed=0):
        return nn.sampling_id(self._probs(), seed=seed)


class MultivariateNormalDiag(Distribution):
    """Diagonal-covariance multivariate normal; reference
    distributions.py:503 (loc [k], scale diag matrix [k, k])."""

    def __init__(self, loc, scale):
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)  # [k, k] diagonal matrix

    def _diag(self):
        k = self.scale.shape[-1]
        eye = tensor_layers.assign(np.eye(k, dtype="float32"))
        return nn.reduce_sum(nn.elementwise_mul(self.scale, eye), dim=-1)

    def entropy(self):
        d = self._diag()
        k = float(self.scale.shape[-1])
        logdet = nn.reduce_sum(nn.log(d), dim=-1)
        return nn.scale(logdet, 0.5, bias=0.5 * k * (1.0 + math.log(2.0 * math.pi)))

    def kl_divergence(self, other):
        d1, d2 = self._diag(), other._diag()
        k = float(self.scale.shape[-1])
        tr = nn.reduce_sum(nn.elementwise_div(d1, d2), dim=-1)
        dl = nn.elementwise_sub(other.loc, self.loc)
        maha = nn.reduce_sum(
            nn.elementwise_div(nn.elementwise_mul(dl, dl), d2), dim=-1
        )
        logdet = nn.elementwise_sub(
            nn.reduce_sum(nn.log(d2), dim=-1), nn.reduce_sum(nn.log(d1), dim=-1)
        )
        return nn.scale(
            nn.elementwise_add(nn.elementwise_add(tr, maha),
                               nn.scale(logdet, 1.0, bias=-k)),
            0.5,
        )

"""Table-driven layer wrappers over registered ops.

Reference: python/paddle/fluid/layers/{nn,detection,tensor,...}.py —
hundreds of near-identical functions whose body is create_var +
append_op. Here one spec row per layer generates a REAL function (true
positional/keyword signature via exec, so the api-spec ratchet records
honest signatures) that emits the op. Only layers whose op slots fit
the (inputs..., attrs...) -> outputs shape live here; anything with
bespoke logic stays hand-written in its own module.

Spec row: name: (op_type, [(arg, slot)], [(attr, default)], [outputs],
n_stop_grad_outs) — `slot=None` marks optional inputs fed only when
not None.
"""

from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = []  # populated by _generate below


def _infer_shapes(op_type, ins, attrs, out_slots):
    """Eager output shapes via jax.eval_shape over the op's OWN
    lowering (the codebase invariant: layer outputs carry shapes so
    downstream layers can size parameters — layer_helper.py)."""
    import jax
    import jax.numpy as jnp

    from ..core.registry import get_op_def, LoweringContext

    opdef = get_op_def(op_type)

    class _P:
        pass

    op = _P()
    op.type = op_type
    op.attrs = dict(attrs)
    op.attrs.setdefault("op_ident", 0)
    op.attrs.setdefault("seed", 0)
    op.inputs = {s: [getattr(v, "name", "x") for v in vs]
                 for s, vs in ins.items()}
    op.outputs = {s: [f"{op_type}_o"] for s in out_slots}
    specs = {}
    for slot, vs in ins.items():
        lst = []
        for v in vs:
            if v.shape is None:
                return None
            shape = tuple(1 if (d is None or d < 0) else int(d)
                          for d in v.shape)
            lst.append(jax.ShapeDtypeStruct(shape, jnp.dtype(
                str(v.dtype or "float32"))))
        specs[slot] = lst
    try:
        res = jax.eval_shape(
            lambda i: opdef.lower(LoweringContext(), op, i), specs)
    except Exception:
        # a shape-less Variable is a legitimate outcome for ops whose
        # output shape is data-dependent, but a BUG in a lowering would
        # surface the same way — log it so it is diagnosable
        # (round-2 verdict weak #8); FLAGS_print_op_shape_errors
        # escalates to a hard error for debugging
        import logging

        from ..flags import flag

        logging.getLogger("paddle_tpu.layers.auto").debug(
            "shape inference for op %r failed; its output Variables "
            "will have shape=None", op.type, exc_info=True)
        if flag("print_op_shape_errors"):
            raise
        return None
    return {s: [(tuple(a.shape), str(a.dtype)) for a in res.get(s, [])]
            for s in out_slots}


def _emit(op_type, input_map, attrs, out_slots, stop_gradient):
    helper = LayerHelper(op_type)
    ins = {}
    for slot, val in input_map.items():
        if val is None:
            continue
        ins[slot] = list(val) if isinstance(val, (list, tuple)) else [val]
    inferred = _infer_shapes(op_type, ins, attrs, out_slots)
    outs = {}
    ret = []
    for slot in out_slots:
        shape = dtype = None
        if inferred and inferred.get(slot):
            shape, dtype = inferred[slot][0]
        v = helper.create_variable_for_type_inference(
            dtype=dtype or "float32", shape=shape,
            stop_gradient=stop_gradient)
        outs[slot] = [v]
        ret.append(v)
    helper.append_op(type=op_type, inputs=ins, outputs=outs, attrs=attrs)
    return ret[0] if len(ret) == 1 else tuple(ret)


# name: (op_type, inputs [(arg, slot, required)], attrs [(name, default)],
#        outputs, stop_gradient)
_SPECS = {
    # -- activations / unary math -----------------------------------------
    "brelu": ("brelu", [("x", "X", 1)],
              [("t_min", 0.0), ("t_max", 24.0)], ["Out"], False),
    "selu": ("selu", [("x", "X", 1)],
             [("scale", 1.0507009873554805), ("alpha", 1.6732632423543772)],
             ["Out"], False),
    "sign": ("sign", [("x", "X", 1)], [], ["Out"], False),
    "size": ("size", [("input", "Input", 1)], [], ["Out"], True),
    "reverse": ("reverse", [("x", "X", 1)], [("axis", 0)], ["Out"], False),
    "lrn": ("lrn", [("input", "X", 1)],
            [("n", 5), ("k", 1.0), ("alpha", 1e-4), ("beta", 0.75)],
            ["Out"], False),
    "label_smooth": ("label_smooth", [("label", "X", 1),
                                      ("prior_dist", "PriorDist", 0)],
                     [("epsilon", 0.1)], ["Out"], False),
    "pixel_shuffle": ("pixel_shuffle", [("x", "X", 1)],
                      [("upscale_factor", 1)], ["Out"], False),
    "space_to_depth": ("space_to_depth", [("x", "X", 1)],
                       [("blocksize", 2)], ["Out"], False),
    "temporal_shift": ("temporal_shift", [("x", "X", 1)],
                       [("seg_num", 1), ("shift_ratio", 0.25)],
                       ["Out"], False),
    "unfold": ("unfold", [("x", "X", 1)],
               [("kernel_sizes", [3, 3]), ("strides", [1, 1]),
                ("paddings", [0, 0]), ("dilations", [1, 1])], ["Y"], False),
    "diag": ("diag", [("diagonal", "Diagonal", 1)], [], ["Out"], False),
    "is_empty": ("is_empty", [("x", "X", 1)], [], ["Out"], True),
    "isfinite": ("isfinite", [("x", "X", 1)], [], ["Out"], True),
    "has_inf": ("has_inf", [("x", "X", 1)], [], ["Out"], True),
    "has_nan": ("has_nan", [("x", "X", 1)], [], ["Out"], True),
    "logical_and": ("logical_and", [("x", "X", 1), ("y", "Y", 1)],
                    [], ["Out"], True),
    "logical_or": ("logical_or", [("x", "X", 1), ("y", "Y", 1)],
                   [], ["Out"], True),
    "logical_xor": ("logical_xor", [("x", "X", 1), ("y", "Y", 1)],
                    [], ["Out"], True),
    "logical_not": ("logical_not", [("x", "X", 1)], [], ["Out"], True),
    "sum": ("sum", [("x", "X", 1)], [], ["Out"], False),
    "mul": ("mul", [("x", "X", 1), ("y", "Y", 1)],
            [("x_num_col_dims", 1), ("y_num_col_dims", 1)], ["Out"], False),
    "multiplex": ("multiplex", [("inputs", "X", 1), ("index", "Ids", 1)],
                  [], ["Out"], False),
    "elementwise_floordiv": ("elementwise_floordiv",
                             [("x", "X", 1), ("y", "Y", 1)],
                             [("axis", -1)], ["Out"], False),
    "scatter_nd_add": ("scatter_nd_add",
                       [("ref", "X", 1), ("index", "Index", 1),
                        ("updates", "Updates", 1)], [], ["Out"], False),
    "strided_slice": ("strided_slice", [("input", "Input", 1)],
                      [("axes", []), ("starts", []), ("ends", []),
                       ("strides", [])], ["Out"], False),
    "unique": ("unique", [("x", "X", 1)], [], ["Out", "Index"], True),
    "unique_with_counts": ("unique_with_counts", [("x", "X", 1)], [],
                           ["Out", "Index", "Count"], True),
    "sampling_id": ("sampling_id", [("x", "X", 1)],
                    [("min", 0.0), ("max", 1.0), ("seed", 0)], ["Out"], True),
    "random_crop": ("random_crop", [("x", "X", 1)],
                    [("shape", []), ("seed", 0)], ["Out"], False),
    "crop_tensor": ("crop_tensor", [("x", "X", 1)],
                    [("shape", []), ("offsets", None)], ["Out"], False),
    "gather_tree": ("gather_tree", [("ids", "Ids", 1),
                                    ("parents", "Parents", 1)],
                    [], ["Out"], True),
    "uniform_random_batch_size_like": (
        "uniform_random_batch_size_like", [("input", "Input", 1)],
        [("shape", []), ("min", -1.0), ("max", 1.0), ("seed", 0),
         ("input_dim_idx", 0), ("output_dim_idx", 0)], ["Out"], True),
    "gaussian_random_batch_size_like": (
        "gaussian_random_batch_size_like", [("input", "Input", 1)],
        [("shape", []), ("mean", 0.0), ("std", 1.0), ("seed", 0),
         ("input_dim_idx", 0), ("output_dim_idx", 0)], ["Out"], True),
    "add_position_encoding": ("add_position_encoding", [("input", "X", 1)],
                              [("alpha", 1.0), ("beta", 1.0)],
                              ["Out"], False),
    "pad_constant_like": ("pad_constant_like",
                          [("x", "X", 1), ("y", "Y", 1)],
                          [("pad_value", 0.0)], ["Out"], False),
    # -- losses / metrics --------------------------------------------------
    "cos_sim": ("cos_sim", [("X", "X", 1), ("Y", "Y", 1)],
                [], ["Out"], False),
    "rank_loss": ("rank_loss", [("label", "Label", 1), ("left", "Left", 1),
                                ("right", "Right", 1)], [], ["Out"], False),
    "margin_rank_loss": ("margin_rank_loss",
                         [("label", "Label", 1), ("left", "X1", 1),
                          ("right", "X2", 1)],
                         [("margin", 0.1)], ["Out"], False),
    "bpr_loss": ("bpr_loss", [("input", "X", 1), ("label", "Label", 1)],
                 [], ["Out"], False),
    "center_loss": ("center_loss",
                    [("input", "X", 1), ("label", "Label", 1),
                     ("centers", "Centers", 1),
                     ("update_center", "CenterUpdateRate", 0)],
                    [("cluster_num", 2), ("alpha", 0.1)],
                    ["Loss"], False),
    "teacher_student_sigmoid_loss": (
        "teacher_student_sigmoid_loss",
        [("input", "X", 1), ("label", "Label", 1)],
        [("soft_max_up_bound", 15.0), ("soft_max_lower_bound", -15.0)],
        ["Y"], False),
    "sigmoid_focal_loss": ("sigmoid_focal_loss",
                           [("x", "X", 1), ("label", "Label", 1),
                            ("fg_num", "FgNum", 1)],
                           [("gamma", 2.0), ("alpha", 0.25)],
                           ["Out"], False),
    "mean_iou": ("mean_iou", [("input", "Predictions", 1),
                              ("label", "Labels", 1)],
                 [("num_classes", 2)],
                 ["OutMeanIou", "OutWrong", "OutCorrect"], True),
    "chunk_eval": ("chunk_eval", [("input", "Inference", 1),
                                  ("label", "Label", 1),
                                  ("seq_length", "SeqLength", 0)],
                   [("chunk_scheme", "IOB"), ("num_chunk_types", 1),
                    ("excluded_chunk_types", [])],
                   ["Precision", "Recall", "F1-Score", "NumInferChunks",
                    "NumLabelChunks", "NumCorrectChunks"], True),
    "edit_distance": ("edit_distance", [("input", "Hyps", 1),
                                        ("label", "Refs", 1)],
                      [("normalized", True)],
                      ["Out", "SequenceNum"], True),
    "warpctc": ("warpctc", [("input", "Logits", 1), ("label", "Label", 1),
                            ("input_length", "LogitsLength", 0),
                            ("label_length", "LabelLength", 0)],
                [("blank", 0), ("norm_by_times", False)],
                ["Loss"], False),
    "linear_chain_crf": ("linear_chain_crf",
                         [("input", "Emission", 1), ("label", "Label", 1),
                          ("transition", "Transition", 1),
                          ("length", "Length", 0)], [],
                         ["Alpha", "EmissionExps", "TransitionExps",
                          "LogLikelihood"], False),
    "crf_decoding": ("crf_decoding",
                     [("input", "Emission", 1),
                      ("transition", "Transition", 1),
                      ("label", "Label", 0), ("length", "Length", 0)],
                     [], ["ViterbiPath"], True),
    "npair_loss": ("npair_loss", [("anchor", "Anchor", 1),
                                  ("positive", "Positive", 1),
                                  ("labels", "Labels", 1)],
                   [("l2_reg", 0.002)], ["Out"], False),
    "fsp_matrix": ("fsp", [("x", "X", 1), ("y", "Y", 1)], [],
                   ["Out"], False),
    # -- conv/pool/vision --------------------------------------------------
    "conv3d": ("conv3d", [("input", "Input", 1), ("filter", "Filter", 1),
                          ("bias", "Bias", 0)],
               [("strides", [1, 1, 1]), ("paddings", [0, 0, 0]),
                ("dilations", [1, 1, 1]), ("groups", 1)],
               ["Output"], False),
    "conv3d_transpose": ("conv3d_transpose",
                         [("input", "Input", 1), ("filter", "Filter", 1),
                          ("bias", "Bias", 0)],
                         [("strides", [1, 1, 1]), ("paddings", [0, 0, 0]),
                          ("dilations", [1, 1, 1])], ["Output"], False),
    "pool3d": ("pool3d", [("input", "X", 1)],
               [("pooling_type", "max"), ("ksize", [2, 2, 2]),
                ("strides", [2, 2, 2]), ("paddings", [0, 0, 0]),
                ("global_pooling", False), ("exclusive", True)],
               ["Out"], False),
    "adaptive_pool3d": ("pool3d", [("input", "X", 1)],
                        [("pooling_type", "max"), ("ksize", [1, 1, 1]),
                         ("adaptive", True)], ["Out"], False),
    "resize_trilinear": ("trilinear_interp", [("input", "X", 1)],
                         [("out_d", 0), ("out_h", 0), ("out_w", 0),
                          ("align_corners", True)], ["Out"], False),
    "grid_sampler": ("grid_sampler", [("x", "X", 1), ("grid", "Grid", 1)],
                     [], ["Output"], False),
    "affine_grid": ("affine_grid", [("theta", "Theta", 1)],
                    [("output_shape", [])], ["Output"], False),
    "affine_channel": ("affine_channel",
                       [("x", "X", 1), ("scale", "Scale", 1),
                        ("bias", "Bias", 1)],
                       [("data_layout", "NCHW")], ["Out"], False),
    "data_norm": ("data_norm",
                  [("input", "X", 1), ("batch_size", "BatchSize", 0),
                   ("batch_sum", "BatchSum", 0),
                   ("batch_square_sum", "BatchSquareSum", 0)],
                  [("epsilon", 1e-4)], ["Y"], False),
    "row_conv": ("row_conv", [("input", "X", 1), ("filter", "Filter", 1)],
                 [], ["Out"], False),
    "spectral_norm": ("spectral_norm",
                      [("weight", "Weight", 1), ("u", "U", 1),
                       ("v", "V", 1)],
                      [("dim", 0), ("power_iters", 1), ("eps", 1e-12)],
                      ["Out"], False),
    "bilinear_tensor_product": ("bilinear_tensor_product",
                                [("x", "X", 1), ("y", "Y", 1),
                                 ("weight", "Weight", 1),
                                 ("bias", "Bias", 0)], [], ["Out"], False),
    "im2sequence": ("im2sequence", [("input", "X", 1)],
                    [("kernels", [3, 3]), ("strides", [1, 1]),
                     ("paddings", [0, 0, 0, 0])], ["Out"], False),
    "deformable_conv": ("deformable_conv",
                        [("input", "Input", 1), ("offset", "Offset", 1),
                         ("mask", "Mask", 0), ("filter", "Filter", 1)],
                        [("strides", [1, 1]), ("paddings", [0, 0]),
                         ("dilations", [1, 1]), ("groups", 1),
                         ("deformable_groups", 1)], ["Output"], False),
    "deformable_roi_pooling": ("deformable_psroi_pooling",
                               [("input", "Input", 1), ("rois", "ROIs", 1),
                                ("trans", "Trans", 0)],
                               [("spatial_scale", 1.0), ("output_dim", 1),
                                ("pooled_height", 1), ("pooled_width", 1),
                                ("trans_std", 0.1)],
                               ["Output", "TopCount"], False),
    "psroi_pool": ("psroi_pool", [("input", "X", 1), ("rois", "ROIs", 1)],
                   [("output_channels", 1), ("spatial_scale", 1.0),
                    ("pooled_height", 1), ("pooled_width", 1)],
                   ["Out"], False),
    "prroi_pool": ("prroi_pool", [("input", "X", 1), ("rois", "ROIs", 1)],
                   [("spatial_scale", 1.0), ("pooled_height", 1),
                    ("pooled_width", 1)], ["Out"], False),
    "roi_align": ("roi_align", [("input", "X", 1), ("rois", "ROIs", 1),
                                ("rois_num", "RoisNum", 0)],
                  [("pooled_height", 1), ("pooled_width", 1),
                   ("spatial_scale", 1.0), ("sampling_ratio", -1)],
                  ["Out"], False),
    "roi_pool": ("roi_pool", [("input", "X", 1), ("rois", "ROIs", 1),
                              ("rois_num", "RoisNum", 0)],
                 [("pooled_height", 1), ("pooled_width", 1),
                  ("spatial_scale", 1.0)], ["Out", "Argmax"], False),
    "roi_perspective_transform": ("roi_perspective_transform",
                                  [("input", "X", 1), ("rois", "ROIs", 1)],
                                  [("transformed_height", 1),
                                   ("transformed_width", 1),
                                   ("spatial_scale", 1.0)],
                                  ["Out", "Mask", "TransformMatrix",
                                   "Out2InIdx", "Out2InWeights"], True),
    # -- misc/nlp/sparse ---------------------------------------------------
    "hash": ("hash", [("input", "X", 1)],
             [("num_hash", 1), ("mod_by", 1 << 16)], ["Out"], True),
    "hsigmoid": ("hierarchical_sigmoid",
                 [("input", "X", 1), ("label", "Label", 1),
                  ("weight", "W", 1), ("bias", "Bias", 0)],
                 [("num_classes", 2)], ["Out", "PreOut"], False),
    "nce": ("nce", [("input", "Input", 1), ("label", "Label", 1),
                    ("weight", "Weight", 1), ("bias", "Bias", 0)],
            [("num_total_classes", 2), ("num_neg_samples", 10)],
            ["Cost", "SampleLogits", "SampleLabels"], False),
    "similarity_focus": ("similarity_focus", [("input", "X", 1)],
                         [("axis", 1), ("indexes", [0])], ["Out"], True),
    "filter_by_instag": ("filter_by_instag",
                         [("ins", "Ins", 1), ("ins_tag", "Ins_tag", 1),
                          ("filter_tag", "Filter_tag", 1)],
                         [("is_lod", True)],
                         ["Out", "LossWeight", "IndexMap"], False),
    "continuous_value_model": ("cvm", [("input", "X", 1),
                                       ("cvm", "CVM", 1)],
                               [("use_cvm", True)], ["Y"], False),
    "merge_selected_rows": ("merge_selected_rows", [("x", "X", 1)],
                            [], ["Out"], True),
    "get_tensor_from_selected_rows": ("get_tensor_from_selected_rows",
                                      [("x", "X", 1)], [], ["Out"], True),
    "lod_reset": ("lod_reset", [("x", "X", 1), ("y", "Y", 0)],
                  [("target_lod", [])], ["Out"], False),
    "reorder_lod_tensor_by_rank": ("reorder_lod_tensor_by_rank",
                                   [("x", "X", 1),
                                    ("rank_table", "RankTable", 1)],
                                   [], ["Out"], False),
    "tensor_array_to_tensor": ("tensor_array_to_tensor",
                               [("input", "X", 1)],
                               [("axis", 0), ("use_stack", False)],
                               ["Out", "OutIndex"], False),
    "sequence_conv": ("sequence_conv", [("input", "X", 1),
                                        ("filter", "Filter", 1),
                                        ("length", "Length", 0)],
                      [("contextLength", 3), ("contextStart", -1)],
                      ["Out"], False),
    "sequence_enumerate": ("sequence_enumerate", [("input", "X", 1)],
                           [("win_size", 2), ("pad_value", 0)],
                           ["Out"], True),
    "sequence_expand_as": ("sequence_expand_as",
                           [("x", "X", 1), ("y", "Y", 1)],
                           [], ["Out"], False),
    "sequence_scatter": ("sequence_scatter",
                         [("input", "X", 1), ("index", "Ids", 1),
                          ("updates", "Updates", 1)], [], ["Out"], False),
    "sequence_slice": ("sequence_slice",
                       [("input", "X", 1), ("offset", "Offset", 1),
                        ("length", "Length", 1)], [], ["Out"], False),
    # -- detection ---------------------------------------------------------
    "anchor_generator": ("anchor_generator", [("input", "Input", 1)],
                         [("anchor_sizes", [64.0]),
                          ("aspect_ratios", [1.0]),
                          ("stride", [16.0, 16.0]),
                          ("variances", [0.1, 0.1, 0.2, 0.2])],
                         ["Anchors", "Variances"], True),
    "bipartite_match": ("bipartite_match", [("dist_matrix", "DistMat", 1)],
                        [],
                        ["ColToRowMatchIndices", "ColToRowMatchDist"], True),
    "box_clip": ("box_clip", [("input", "Input", 1),
                              ("im_info", "ImInfo", 1)], [],
                 ["Output"], False),
    "box_decoder_and_assign": ("box_decoder_and_assign",
                               [("prior_box", "PriorBox", 1),
                                ("prior_box_var", "PriorBoxVar", 1),
                                ("target_box", "TargetBox", 1),
                                ("box_score", "BoxScore", 1)],
                               [("box_clip", 0.0)],
                               ["DecodeBox", "OutputAssignBox"], True),
    "density_prior_box": ("density_prior_box",
                          [("input", "Input", 1), ("image", "Image", 1)],
                          [("densities", [1]), ("fixed_sizes", [4.0]),
                           ("fixed_ratios", [1.0]),
                           ("variances", [0.1, 0.1, 0.2, 0.2])],
                          ["Boxes", "Variances"], True),
    "multiclass_nms": ("multiclass_nms", [("bboxes", "BBoxes", 1),
                                          ("scores", "Scores", 1)],
                       [("background_label", 0),
                        ("score_threshold", 0.01), ("nms_top_k", 100),
                        ("nms_threshold", 0.45), ("keep_top_k", 100)],
                       ["Out", "NmsRoisNum"], True),
    "locality_aware_nms": ("locality_aware_nms",
                           [("bboxes", "BBoxes", 1),
                            ("scores", "Scores", 1)],
                           [("background_label", -1),
                            ("score_threshold", 0.01), ("nms_top_k", 100),
                            ("nms_threshold", 0.45), ("keep_top_k", 100)],
                           ["Out"], True),
    "yolo_box": ("yolo_box", [("x", "X", 1), ("img_size", "ImgSize", 1)],
                 [("anchors", []), ("class_num", 1),
                  ("conf_thresh", 0.01), ("downsample_ratio", 32)],
                 ["Boxes", "Scores"], True),
    "yolov3_loss": ("yolov3_loss",
                    [("x", "X", 1), ("gt_box", "GTBox", 1),
                     ("gt_label", "GTLabel", 1), ("gt_score", "GTScore", 0)],
                    [("anchors", []), ("anchor_mask", []),
                     ("class_num", 1), ("ignore_thresh", 0.7),
                     ("downsample_ratio", 32)],
                    ["Loss", "ObjectnessMask", "GTMatchMask"], False),
    "target_assign": ("target_assign",
                      [("input", "X", 1),
                       ("matched_indices", "MatchIndices", 1),
                       ("negative_indices", "NegIndices", 0)],
                      [("mismatch_value", 0)],
                      ["Out", "OutWeight"], True),
    "rpn_target_assign": ("rpn_target_assign",
                          [("anchor_box", "Anchor", 1),
                           ("gt_boxes", "GtBoxes", 1),
                           ("is_crowd", "IsCrowd", 0),
                           ("im_info", "ImInfo", 0)],
                          [("rpn_batch_size_per_im", 256),
                           ("rpn_positive_overlap", 0.7),
                           ("rpn_negative_overlap", 0.3)],
                          ["LocationIndex", "ScoreIndex", "TargetBBox",
                           "TargetLabel", "BBoxInsideWeight"], True),
    "retinanet_target_assign": ("retinanet_target_assign",
                                [("anchor", "Anchor", 1),
                                 ("gt_boxes", "GtBoxes", 1),
                                 ("gt_labels", "GtLabels", 1),
                                 ("is_crowd", "IsCrowd", 0),
                                 ("im_info", "ImInfo", 0)],
                                [("positive_overlap", 0.5),
                                 ("negative_overlap", 0.4)],
                                ["LocationIndex", "ScoreIndex",
                                 "TargetLabel", "TargetBBox",
                                 "BBoxInsideWeight", "ForegroundNumber"],
                                True),
    "retinanet_detection_output": ("retinanet_detection_output",
                                   [("bboxes", "BBoxes", 1),
                                    ("scores", "Scores", 1),
                                    ("anchors", "Anchors", 1),
                                    ("im_info", "ImInfo", 1)],
                                   [("score_threshold", 0.05),
                                    ("nms_top_k", 1000),
                                    ("nms_threshold", 0.3),
                                    ("keep_top_k", 100)], ["Out"], True),
    "generate_proposals": ("generate_proposals",
                           [("scores", "Scores", 1),
                            ("bbox_deltas", "BboxDeltas", 1),
                            ("im_info", "ImInfo", 1),
                            ("anchors", "Anchors", 1),
                            ("variances", "Variances", 1)],
                           [("pre_nms_topN", 6000), ("post_nms_topN", 1000),
                            ("nms_thresh", 0.5), ("min_size", 0.1)],
                           ["RpnRois", "RpnRoiProbs"], True),
    "generate_proposal_labels": ("generate_proposal_labels",
                                 [("rpn_rois", "RpnRois", 1),
                                  ("gt_classes", "GtClasses", 1),
                                  ("is_crowd", "IsCrowd", 0),
                                  ("gt_boxes", "GtBoxes", 1),
                                  ("im_info", "ImInfo", 0)],
                                 [("batch_size_per_im", 256),
                                  ("fg_fraction", 0.25), ("fg_thresh", 0.5),
                                  ("bg_thresh_hi", 0.5),
                                  ("bg_thresh_lo", 0.0)],
                                 ["Rois", "LabelsInt32", "BboxTargets",
                                  "BboxInsideWeights",
                                  "BboxOutsideWeights"], True),
    "generate_mask_labels": ("generate_mask_labels",
                             [("im_info", "ImInfo", 0),
                              ("gt_classes", "GtClasses", 1),
                              ("is_crowd", "IsCrowd", 0),
                              ("gt_segms", "GtSegms", 1),
                              ("rois", "Rois", 1),
                              ("labels_int32", "LabelsInt32", 1)],
                             [("num_classes", 81), ("resolution", 14)],
                             ["MaskRois", "RoiHasMaskInt32", "MaskInt32"],
                             True),
    "collect_fpn_proposals": ("collect_fpn_proposals",
                              [("multi_rois", "MultiLevelRois", 1),
                               ("multi_scores", "MultiLevelScores", 1)],
                              [("post_nms_top_n", 100)],
                              ["FpnRois"], True),
    "distribute_fpn_proposals": ("distribute_fpn_proposals",
                                 [("fpn_rois", "FpnRois", 1)],
                                 [("min_level", 2), ("max_level", 5),
                                  ("refer_level", 4), ("refer_scale", 224)],
                                 ["MultiFpnRois", "RestoreIndex"], True),
    "polygon_box_transform": ("polygon_box_transform",
                              [("input", "Input", 1)], [],
                              ["Output"], True),
}


def _generate():
    import sys

    mod = sys.modules[__name__]
    for name, (op_type, inputs, attrs, outs, stop_grad) in _SPECS.items():
        args = [a for a, _, _ in inputs]
        kw = [f"{a}={d!r}" for a, d in attrs]
        req = [a for a, _, r in inputs if r]
        opt = [a for a, _, r in inputs if not r]
        sig = ", ".join(req + [f"{a}=None" for a in opt] + kw
                        + ["name=None"])
        slot_map = {a: s for a, s, _ in inputs}
        attr_names = [a for a, _ in attrs]
        body = (
            f"def {name}({sig}):\n"
            f"    _im = {{}}\n"
        )
        for a in args:
            body += f"    _im[{slot_map[a]!r}] = {a}\n"
        body += f"    _attrs = {{}}\n"
        for a in attr_names:
            body += (f"    if {a} is not None:\n"
                     f"        _attrs[{a!r}] = {a}\n")
        body += (f"    return _emit({op_type!r}, _im, _attrs, "
                 f"{outs!r}, {stop_grad!r})\n")
        ns = {"_emit": _emit}
        exec(body, ns)
        fn = ns[name]
        fn.__module__ = __name__
        fn.__doc__ = (f"Layer wrapper over the `{op_type}` op "
                      f"(auto-generated; see ops/ for the lowering and "
                      f"the reference layers/*.py for semantics).")
        setattr(mod, name, fn)
        __all__.append(name)


_generate()

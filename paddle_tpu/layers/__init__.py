"""Layer library: functions that append ops to the default main program.

Reference: python/paddle/fluid/layers/ (~32k LoC: nn.py,
control_flow.py, tensor.py, loss ops inside nn.py,
learning_rate_scheduler.py, collective.py, detection.py, io.py).
"""

from .io import data
from .nn import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .learning_rate_scheduler import *  # noqa: F401,F403
from .metric_op import accuracy, auc
from .collective import (
    _c_allreduce,
    _c_broadcast,
    _c_allgather,
    _c_reducescatter,
)
from .detection import iou_similarity, box_coder, prior_box
from .sequence import *  # noqa: F401,F403
from .py_func_registry import py_func
from .extras import *  # noqa: F401,F403

# auto-generated wrappers fill remaining reference layer names; hand-
# written layers above always win on name conflicts
from . import auto as _auto

for _n in _auto.__all__:
    if _n not in globals():
        globals()[_n] = getattr(_auto, _n)
del _auto, _n
from .rnn import (
    dynamic_lstm,
    dynamic_gru,
    lstm_unit,
    gru_unit,
    beam_search,
    beam_search_decode,
)
from . import ops  # noqa: F401
from . import distributions  # noqa: F401

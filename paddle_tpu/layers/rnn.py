"""RNN layers over dense padded batches.

Reference: layers/nn.py dynamic_lstm/dynamic_gru (LoD-driven) and
layers/rnn.py cells/decoders. Dense [batch, time, d] + optional length
tensor replaces LoD raggedness (see ops/rnn.py).
"""

from __future__ import annotations

from ..initializer import XavierInitializer
from ..layer_helper import LayerHelper
from .nn import _out

__all__ = ["dynamic_lstm", "dynamic_gru", "lstm_unit", "gru_unit"]


def dynamic_lstm(
    input,
    size,
    h_0=None,
    c_0=None,
    param_attr=None,
    bias_attr=None,
    is_reverse=False,
    length=None,
    name=None,
):
    """input: [B, T, D]; size = hidden width H (reference dynamic_lstm's
    `size` is 4H for LoD input proj; here H directly, documented
    divergence for the dense API)."""
    helper = LayerHelper("fused_lstm", param_attr=param_attr, bias_attr=bias_attr, name=name)
    B, T, D = input.shape
    H = size
    wx = helper.create_parameter(helper.param_attr, [D, 4 * H], input.dtype,
                                 default_initializer=XavierInitializer())
    wh = helper.create_parameter(helper.param_attr, [H, 4 * H], input.dtype,
                                 default_initializer=XavierInitializer())
    bias = helper.create_parameter(helper.bias_attr, [4 * H], input.dtype, is_bias=True)
    hidden = _out(helper, input, shape=(B, T, H))
    cell = _out(helper, input, shape=(B, T, H))
    last_h = _out(helper, input, shape=(B, H))
    last_c = _out(helper, input, shape=(B, H))
    inputs = {"X": [input], "WeightX": [wx], "WeightH": [wh], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="fused_lstm",
        inputs=inputs,
        outputs={"Hidden": [hidden], "Cell": [cell], "LastH": [last_h], "LastC": [last_c]},
        attrs={"is_reverse": is_reverse},
    )
    return hidden, cell


def dynamic_gru(
    input,
    size,
    h_0=None,
    param_attr=None,
    bias_attr=None,
    is_reverse=False,
    length=None,
    name=None,
):
    helper = LayerHelper("fused_gru", param_attr=param_attr, bias_attr=bias_attr, name=name)
    B, T, D = input.shape
    H = size
    wx = helper.create_parameter(helper.param_attr, [D, 3 * H], input.dtype,
                                 default_initializer=XavierInitializer())
    wh = helper.create_parameter(helper.param_attr, [H, 3 * H], input.dtype,
                                 default_initializer=XavierInitializer())
    bias = helper.create_parameter(helper.bias_attr, [3 * H], input.dtype, is_bias=True)
    hidden = _out(helper, input, shape=(B, T, H))
    last_h = _out(helper, input, shape=(B, H))
    inputs = {"X": [input], "WeightX": [wx], "WeightH": [wh], "Bias": [bias]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op(
        type="fused_gru",
        inputs=inputs,
        outputs={"Hidden": [hidden], "LastH": [last_h]},
        attrs={"is_reverse": is_reverse},
    )
    return hidden


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0, param_attr=None,
              bias_attr=None, name=None):
    """Single step (reference layers/nn.py lstm_unit): x_t [B,D],
    states [B,H]."""
    from .nn import concat, fc

    helper = LayerHelper("lstm_unit_layer", name=name)
    H = hidden_t_prev.shape[-1]
    gates = fc(
        concat([x_t, hidden_t_prev], axis=1), 4 * H,
        param_attr=param_attr, bias_attr=bias_attr,
    )
    c = _out(helper, cell_t_prev, shape=cell_t_prev.shape)
    h = _out(helper, hidden_t_prev, shape=hidden_t_prev.shape)
    helper.append_op(
        type="lstm_unit",
        inputs={"X": [gates], "C_prev": [cell_t_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": forget_bias},
    )
    return h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None, name=None):
    """Single step (reference layers/nn.py gru_unit): size = 3H."""
    helper = LayerHelper("gru_unit_layer", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    H = size // 3
    w = helper.create_parameter(helper.param_attr, [H, 3 * H], input.dtype)
    b = helper.create_parameter(helper.bias_attr, [3 * H], input.dtype, is_bias=True)
    gate = _out(helper, input, shape=(input.shape[0], 3 * H))
    rhp = _out(helper, hidden, shape=hidden.shape)
    h = _out(helper, hidden, shape=hidden.shape)
    helper.append_op(
        type="gru_unit",
        inputs={"Input": [input], "HiddenPrev": [hidden], "Weight": [w], "Bias": [b]},
        outputs={"Gate": [gate], "ResetHiddenPrev": [rhp], "Hidden": [h]},
    )
    return h, rhp, gate


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None, return_parent_idx=False):
    """One beam expansion step (reference layers API over
    beam_search_op.cc; see ops/beam.py for the dense [batch, beam]
    redesign). `ids` is accepted for API parity and unused — candidate
    ids are implicit [0, V)."""
    from ..layer_helper import LayerHelper
    from .nn import _out

    helper = LayerHelper("beam_search", name=name)
    sel_ids = _out(helper, pre_ids, shape=pre_ids.shape, dtype=pre_ids.dtype,
                   stop_gradient=True)
    sel_scores = _out(helper, pre_scores, shape=pre_scores.shape,
                      stop_gradient=True)
    parent = _out(helper, pre_ids, shape=pre_ids.shape, dtype="int32",
                  stop_gradient=True)
    inputs = {"pre_ids": [pre_ids], "pre_scores": [pre_scores], "scores": [scores]}
    if ids is not None:
        inputs["ids"] = [ids]
    helper.append_op(
        type="beam_search",
        inputs=inputs,
        outputs={"selected_ids": [sel_ids], "selected_scores": [sel_scores],
                 "parent_idx": [parent]},
        attrs={"beam_size": beam_size, "end_id": end_id, "level": level,
               "is_accumulated": is_accumulated},
    )
    if return_parent_idx:
        return sel_ids, sel_scores, parent
    return sel_ids, sel_scores


def beam_search_decode(ids, scores, beam_size, end_id, name=None,
                       parents=None, final_scores=None):
    """Backtrack stacked beam steps into sentences (reference
    beam_search_decode_op.cc). Dense form: `ids`/`parents` are the
    [T, B, beam] stacks of per-step beam_search outputs; `final_scores`
    the last step's [B, beam] scores (defaults to `scores`)."""
    from ..layer_helper import LayerHelper
    from .nn import _out

    helper = LayerHelper("beam_search_decode", name=name)
    if parents is None:
        raise ValueError(
            "beam_search_decode needs the stacked parent_idx steps: pass "
            "parents=<[T, B, beam] stack of beam_search parent_idx outputs> "
            "(the dense replacement for the reference's LoD parent levels)"
        )
    sent = _out(helper, ids, shape=None, dtype=ids.dtype, stop_gradient=True)
    sent_scores = _out(helper, scores, shape=None, stop_gradient=True)
    helper.append_op(
        type="beam_search_decode",
        inputs={"Ids": [ids], "Parents": [parents],
                "Scores": [final_scores if final_scores is not None else scores]},
        outputs={"SentenceIds": [sent], "SentenceScores": [sent_scores]},
        attrs={"beam_size": beam_size, "end_id": end_id},
    )
    return sent, sent_scores

"""Composition layers + control-flow sugar completing the reference
layer-name surface.

Reference: python/paddle/fluid/layers/{control_flow,detection,io,
nn,loss}.py — these names are python compositions there too (no
dedicated C++ op), so they are compositions here.
"""

from __future__ import annotations

import numpy as np

from ..layer_helper import LayerHelper
from ..core.framework import unique_name, default_main_program

__all__ = [
    "switch_moe",
    "Print", "autoincreased_step_counter", "case", "switch_case",
    "while_loop", "IfElse", "ctc_greedy_decoder", "dice_loss", "eye",
    "image_resize_short", "load", "lod_append", "scatter_nd",
    "sampled_softmax_with_cross_entropy", "sequence_first_step",
    "sequence_last_step", "rank", "reduce_all", "reduce_any", "crop", "py_reader", "create_py_reader_by_data",
    "double_buffer", "read_file",
]


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Reference layers/control_flow.py Print (the print op)."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="print", inputs={"In": [input]}, outputs={"Out": [out]},
        attrs={"message": message or ""},
    )
    return out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Reference layers/nn.py: persistable int64 counter incremented
    every step the program runs."""
    helper = LayerHelper("step_counter")
    name = counter_name or unique_name.generate("@STEP_COUNTER@")
    block = helper.main_program.global_block()
    counter = block.create_var(name=name, dtype="int64", shape=(1,),
                               persistable=True, stop_gradient=True)
    sblock = helper.startup_program.global_block()
    sv = sblock.create_var(name=name, dtype="int64", shape=(1,),
                           persistable=True)
    sblock.append_op(type="fill_constant", outputs={"Out": [sv]},
                     attrs={"shape": [1], "dtype": "int64",
                            "value": float(begin - step)})
    block.append_op(type="increment", inputs={"X": [counter]},
                    outputs={"Out": [counter]}, attrs={"step": float(step)})
    return counter


def rank(input):
    """Reference layers/nn.py rank: the (static) dimensionality as a
    0-d int constant — shapes are static here, so it is a literal."""
    from .tensor import fill_constant

    return fill_constant([1], "int32", float(len(input.shape or ())))


def _broadcast_bool(pred, template):
    helper = LayerHelper("bcast_pred")
    out = helper.create_variable_for_type_inference(
        dtype="bool", shape=template.shape, stop_gradient=True)
    helper.append_op(
        type="expand_pred_like", inputs={"X": [pred], "Y": [template]},
        outputs={"Out": [out]})
    return out


def case(pred_fn_pairs, default=None, name=None):
    """Functional exclusive cases (reference layers/control_flow.py
    case): first true predicate's branch value wins. All branches are
    traced (XLA select semantics — same stance as layers.cond)."""
    from .nn import where

    assert pred_fn_pairs, "case() needs at least one (pred, fn) pair"
    results = [(p, fn()) for p, fn in pred_fn_pairs]
    out = default() if default is not None else results[-1][1]
    for p, v in reversed(results):
        out = where(_broadcast_bool(p, v), v, out)
    return out


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Reference layers/control_flow.py switch_case: select a branch
    value by integer index."""
    from .tensor import fill_constant

    pairs = []
    items = (branch_fns.items() if isinstance(branch_fns, dict)
             else list(enumerate(branch_fns)))
    for idx, fn in items:
        helper = LayerHelper("switch_case")
        iv = fill_constant([1], "int64", float(idx))
        p = helper.create_variable_for_type_inference(
            dtype="bool", shape=(1,), stop_gradient=True)
        helper.append_op(type="equal",
                         inputs={"X": [branch_index], "Y": [iv]},
                         outputs={"Out": [p]})
        pairs.append((p, fn))
    return case(pairs, default=default)


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Functional while (reference layers/control_flow.py while_loop)
    over the While machinery: loop_vars are assigned in place each
    iteration; returns the final loop_vars."""
    from .control_flow import While
    from .tensor import assign

    helper = LayerHelper("while_loop")
    cond_var = cond(*loop_vars)
    loop = While(cond_var)
    with loop.block():
        new_vars = body(*loop_vars)
        if not isinstance(new_vars, (list, tuple)):
            new_vars = [new_vars]
        for old, new in zip(loop_vars, new_vars):
            assign(new, old)
        assign(cond(*loop_vars), cond_var)
    return list(loop_vars)


class IfElse:
    """Reference layers/control_flow.py IfElse. Dense XLA stance: both
    branches execute over the FULL batch; `output` merges rows by the
    condition (the reference splits/compacts rows instead — see
    split_lod_tensor; same numerics for row-wise programs)."""

    def __init__(self, cond, name=None):
        self._cond = cond
        self._true_outs = None
        self._false_outs = None
        self._phase = None

    def input(self, x):
        return x  # dense: both branches see the full batch

    def true_block(self):
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            self._phase = True
            yield
            self._phase = None

        return _ctx()

    def false_block(self):
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            self._phase = False
            yield
            self._phase = None

        return _ctx()

    def output(self, *outs):
        if self._phase is True:
            self._true_outs = list(outs)
        elif self._phase is False:
            self._false_outs = list(outs)
        else:
            raise ValueError("IfElse.output() must be called in a block")

    def __call__(self):
        from .nn import where

        assert self._true_outs is not None and self._false_outs is not None
        merged = [
            where(_broadcast_bool(self._cond, t), t, f)
            for t, f in zip(self._true_outs, self._false_outs)
        ]
        return merged if len(merged) > 1 else merged[0]


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """argmax -> collapse repeats -> drop blanks (reference
    layers/nn.py ctc_greedy_decoder over ctc_align). Dense output:
    [B, T] with padding_value tail."""
    from .nn import topk

    helper = LayerHelper("ctc_greedy_decoder")
    # argmax over classes
    idx = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    helper.append_op(type="arg_max", inputs={"X": [input]},
                     outputs={"Out": [idx]}, attrs={"axis": -1})
    out = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    out_len = helper.create_variable_for_type_inference(
        dtype="int64", stop_gradient=True)
    ins = {"Input": [idx]}
    if input_length is not None:
        ins["InputLength"] = [input_length]
    helper.append_op(type="ctc_align", inputs=ins,
                     outputs={"Output": [out], "OutputLength": [out_len]},
                     attrs={"blank": blank, "merge_repeated": True,
                            "padding_value": padding_value})
    if input_length is not None:
        return out, out_len
    return out


def dice_loss(input, label, epsilon=1e-5):
    """Reference layers/nn.py dice_loss: PER-SAMPLE intersection/union
    over the non-batch dims, then mean over samples (pure composition
    there too)."""
    from .nn import (reduce_sum, reduce_mean, cast, elementwise_mul,
                     elementwise_add, elementwise_div, scale)

    label_f = cast(label, input.dtype)
    dims = list(range(1, len(input.shape or (1, 1))))
    inter = reduce_sum(elementwise_mul(input, label_f), dim=dims)
    union = elementwise_add(reduce_sum(input, dim=dims),
                            reduce_sum(label_f, dim=dims))
    dice = elementwise_div(scale(inter, scale=2.0),
                           scale(union, scale=1.0, bias=epsilon))
    return reduce_mean(scale(dice, scale=-1.0, bias=1.0))


def reduce_all(input, dim=None, keep_dim=False, name=None):
    """dim=None reduces ALL elements (reference layers/nn.py sets the
    reduce_all attr in that case — generated wrappers could not)."""
    helper = LayerHelper("reduce_all")
    out = helper.create_variable_for_type_inference(
        dtype="bool", stop_gradient=True)
    attrs = ({"reduce_all": True, "keep_dim": keep_dim} if dim is None
             else {"dim": list(dim) if isinstance(dim, (list, tuple))
                   else [dim], "keep_dim": keep_dim})
    helper.append_op(type="reduce_all", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def reduce_any(input, dim=None, keep_dim=False, name=None):
    helper = LayerHelper("reduce_any")
    out = helper.create_variable_for_type_inference(
        dtype="bool", stop_gradient=True)
    attrs = ({"reduce_all": True, "keep_dim": keep_dim} if dim is None
             else {"dim": list(dim) if isinstance(dim, (list, tuple))
                   else [dim], "keep_dim": keep_dim})
    helper.append_op(type="reduce_any", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def crop(x, shape=None, offsets=None, name=None):
    """Reference layers/nn.py crop: shape may be a Variable (crop to
    its extent) or a list of ints."""
    helper = LayerHelper("crop")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    ins = {"X": [x]}
    attrs = {}
    if shape is not None and not isinstance(shape, (list, tuple)):
        ins["Y"] = [shape]
    elif shape is not None:
        attrs["shape"] = [int(s) for s in shape]
    if offsets is not None:
        attrs["offsets"] = [int(o) for o in offsets]
    helper.append_op(type="crop", inputs=ins, outputs={"Out": [out]},
                     attrs=attrs)
    return out


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32"):
    helper = LayerHelper("eye")
    nc = num_columns or num_rows
    out = helper.create_variable_for_type_inference(
        dtype=dtype, shape=(num_rows, nc), stop_gradient=True)
    helper.append_op(type="eye", inputs={}, outputs={"Out": [out]},
                     attrs={"num_rows": num_rows, "num_columns": nc,
                            "dtype": dtype})
    if batch_shape:
        # reference: leading batch dims replicate the identity
        cur = out
        for _ in batch_shape:
            helper2 = LayerHelper("eye_expand")
            u = helper2.create_variable_for_type_inference(
                dtype=dtype, stop_gradient=True)
            helper2.append_op(type="unsqueeze", inputs={"X": [cur]},
                              outputs={"Out": [u]}, attrs={"axes": [0]})
            cur = u
        times = list(batch_shape) + [1, 1]
        helper3 = LayerHelper("eye_tile")
        t = helper3.create_variable_for_type_inference(
            dtype=dtype,
            shape=tuple(batch_shape) + (num_rows, nc),
            stop_gradient=True)
        helper3.append_op(type="expand", inputs={"X": [cur]},
                          outputs={"Out": [t]},
                          attrs={"expand_times": times})
        return t
    return out


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORT side equals out_short_len (reference
    layers/nn.py image_resize_short). Static shapes: computed from the
    declared input shape."""
    from .nn import image_resize

    h, w = input.shape[2], input.shape[3]
    short, is_h = (h, True) if h <= w else (w, False)
    scale = out_short_len / float(short)
    oh = out_short_len if is_h else int(round(h * scale))
    ow = int(round(w * scale)) if is_h else out_short_len
    return image_resize(input, out_shape=[oh, ow], resample=resample)


def load(out, file_path, load_as_fp16=False):
    """Reference layers/io.py load: emit a load op restoring `out`."""
    helper = LayerHelper("load_layer")
    helper.append_op(
        type="load", inputs={}, outputs={"Out": [out]},
        attrs={"file_path": file_path,
               "shape": list(out.shape or (1,)),
               "dtype": str(out.dtype)})
    return out


def lod_append(x, level):
    """Reference layers/lod_append: add one LoD level. Dense carrier:
    identity on data (lengths live host-side in LoDTensor)."""
    helper = LayerHelper("lod_append")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="lod_reset", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"target_lod": list(level)
                            if isinstance(level, (list, tuple)) else []})
    return out


def scatter_nd(index, updates, shape, name=None):
    """scatter_nd_add onto zeros (reference layers/nn.py scatter_nd)."""
    from .tensor import fill_constant

    zeros = fill_constant(list(shape), updates.dtype, 0.0)
    zeros.stop_gradient = False
    helper = LayerHelper("scatter_nd")
    out = helper.create_variable_for_type_inference(dtype=updates.dtype)
    helper.append_op(type="scatter_nd_add",
                     inputs={"X": [zeros], "Index": [index],
                             "Updates": [updates]},
                     outputs={"Out": [out]})
    return out


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, seed=0):
    """sample_logits -> softmax CE on the sampled subset (reference
    layers/nn.py composition over the same ops)."""
    helper = LayerHelper("sampled_softmax")
    outs = {n: [helper.create_variable_for_type_inference(
        stop_gradient=(n not in ("SampledLogits",)))]
        for n in ("Samples", "Probabilities", "LogitsDim", "LabelsDim",
                  "SampledLogits", "SampledLabels")}
    helper.append_op(
        type="sample_logits",
        inputs={"Logits": [logits], "Labels": [label]},
        outputs=outs, attrs={"num_samples": num_samples, "seed": seed})
    loss = helper.create_variable_for_type_inference()
    sm = helper.create_variable_for_type_inference()
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": outs["SampledLogits"],
                "Label": outs["SampledLabels"]},
        outputs={"Loss": [loss], "Softmax": [sm]},
        attrs={"soft_label": False})
    return loss


def sequence_first_step(input, length=None):
    from .sequence import sequence_pool

    return sequence_pool(input, "first", length=length)


def sequence_last_step(input, length=None):
    from .sequence import sequence_pool

    return sequence_pool(input, "last", length=length)


# -- io sugar over the reader machinery -----------------------------------

def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Reference layers/io.py py_reader: queue-fed reader. Adapter over
    reader.GeneratorLoader (which already device-put-prefetches, i.e.
    the double buffer is built in): data vars are created from
    shapes/dtypes and become the loader's feed_list."""
    from .io import data as data_layer
    from ..reader import GeneratorLoader

    feed_vars = []
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        feed_vars.append(data_layer(
            unique_name.generate(f"{name or 'py_reader'}_slot{i}"),
            list(shape[1:]), dtype=dtype))
    return GeneratorLoader(feed_vars, capacity=capacity,
                           use_double_buffer=use_double_buffer)


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    from ..reader import GeneratorLoader

    return GeneratorLoader(feed_list, capacity=capacity,
                           use_double_buffer=use_double_buffer)


def double_buffer(reader, place=None, name=None):
    """The GeneratorLoader prefetches to device already (async double
    buffer per reader.py); passthrough for API parity."""
    return reader


def read_file(reader):
    """The feed vars a py_reader batches into (reference layers/io.py
    read_file returns the reader's output vars)."""
    if hasattr(reader, "feed_list"):
        fl = reader.feed_list
        return list(fl) if len(fl) > 1 else fl[0]
    raise TypeError("read_file expects a py_reader/GeneratorLoader")


# -- SSD layer API (delegates to models.ssd; imported lazily to avoid a
# layers <-> models import cycle) ------------------------------------------

def multi_box_head(inputs, image, num_classes=None, min_sizes=None,
                   max_sizes=None, aspect_ratios=None, base_size=None,
                   **kw):
    from ..models.ssd import multi_box_head as impl

    return impl(inputs, image, num_classes, min_sizes,
                max_sizes=max_sizes, aspect_ratios=aspect_ratios)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, overlap_threshold=0.5, neg_pos_ratio=3.0,
             loc_loss_weight=1.0, conf_loss_weight=1.0, **kw):
    from ..models.ssd import ssd_loss as impl

    return impl(location, confidence, gt_box, gt_label, prior_box,
                prior_box_var, overlap_threshold=overlap_threshold,
                neg_pos_ratio=neg_pos_ratio, loc_weight=loc_loss_weight,
                conf_weight=conf_loss_weight)


def detection_output(loc, scores, prior_box, prior_box_var=None,
                     background_label=0, nms_threshold=0.3,
                     nms_top_k=400, keep_top_k=200, score_threshold=0.01,
                     **kw):
    from ..models.ssd import detection_output as impl

    return impl(loc, scores, prior_box, prior_box_var,
                nms_threshold=nms_threshold,
                score_threshold=score_threshold, keep_top_k=keep_top_k,
                background_label=background_label)


__all__ += ["multi_box_head", "ssd_loss", "detection_output"]


def switch_moe(input, num_experts, expert_hidden, capacity_factor=1.25,
               act="gelu", param_attr=None, bias_attr=None, name=None):
    """Switch-transformer MoE FFN layer (top-1 routing, capacity-bound
    dispatch). Returns (out, aux_loss): add `aux_coeff * aux_loss` to
    the training loss for load balancing. Expert weights are tagged so
    CompiledProgram.with_expert_parallel can shard them over the `ep`
    mesh axis (ops/moe.py). Beyond the reference (no MoE in the
    snapshot); API mirrors the layers.fc conventions."""
    from ..layer_helper import LayerHelper
    from ..initializer import XavierInitializer, ConstantInitializer
    from ..param_attr import ParamAttr
    from .nn import _out

    helper = LayerHelper("switch_moe", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)

    def _slot(base, suffix):
        """Per-slot copy of a user attr: this layer owns FIVE params, a
        single shared ParamAttr (whose name the first create_parameter
        fills in) would alias them all through weight sharing."""
        # bias_attr=False means "no bias" elsewhere; the moe op's
        # biases are structural, so fall back to the default attr
        a = ParamAttr._to_attr(base if base is not False else None)
        a = ParamAttr(**a.__dict__.copy())
        if a.name is not None:
            a.name = f"{a.name}.{suffix}"
        return a

    d = int(input.shape[-1])
    e, f = int(num_experts), int(expert_hidden)
    wg = helper.create_parameter(
        _slot(helper.param_attr, "gate"), [d, e], input.dtype,
        default_initializer=XavierInitializer())
    w1 = helper.create_parameter(
        _slot(helper.param_attr, "w1"), [e, d, f], input.dtype,
        default_initializer=XavierInitializer())
    b1 = helper.create_parameter(
        _slot(helper.bias_attr, "b1"), [e, f], input.dtype, is_bias=True,
        default_initializer=ConstantInitializer(0.0))
    w2 = helper.create_parameter(
        _slot(helper.param_attr, "w2"), [e, f, d], input.dtype,
        default_initializer=XavierInitializer())
    b2 = helper.create_parameter(
        _slot(helper.bias_attr, "b2"), [e, d], input.dtype, is_bias=True,
        default_initializer=ConstantInitializer(0.0))
    # with_expert_parallel shards every tagged var's dim 0 over `ep`
    for v in (w1, b1, w2, b2):
        v._moe_expert_param = True
    out = _out(helper, input, shape=input.shape)
    aux = _out(helper, input, shape=(1,))
    helper.append_op(
        type="switch_moe",
        inputs={"X": [input], "GateW": [wg], "ExpertW1": [w1],
                "ExpertB1": [b1], "ExpertW2": [w2], "ExpertB2": [b2]},
        outputs={"Out": [out], "AuxLoss": [aux]},
        attrs={"capacity_factor": float(capacity_factor), "act": act},
    )
    return out, aux

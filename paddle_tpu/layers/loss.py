"""Loss layers. Reference: python/paddle/fluid/layers/nn.py loss section
+ layers/loss.py in later versions."""

from __future__ import annotations

from ..core.framework import Variable
from ..layer_helper import LayerHelper
from .nn import _out

__all__ = [
    "cross_entropy",
    "softmax_with_cross_entropy",
    "square_error_cost",
    "sigmoid_cross_entropy_with_logits",
    "log_loss",
    "huber_loss",
    "smooth_l1",
    "kldiv_loss",
    "mse_loss",
]


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    shp = tuple(input.shape[:-1] or ()) + (1,)
    out = _out(helper, input, shape=shp)
    helper.append_op(
        type="cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(
    logits,
    label,
    soft_label=False,
    ignore_index=-100,
    numeric_stable_mode=True,
    return_softmax=False,
    axis=-1,
):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax = _out(helper, logits, shape=logits.shape)
    loss_shape = list(logits.shape or ())
    if loss_shape:
        loss_shape[axis] = 1
    loss = _out(helper, logits, shape=tuple(loss_shape))
    helper.append_op(
        type="softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax], "Loss": [loss]},
        attrs={
            "soft_label": soft_label,
            "ignore_index": ignore_index,
            "numeric_stable_mode": numeric_stable_mode,
            "axis": axis,
        },
    )
    if return_softmax:
        return loss, softmax
    return loss


def square_error_cost(input, label):
    """(input - label)^2, reference layers/nn.py square_error_cost"""
    from .nn import elementwise_sub, square

    return square(elementwise_sub(input, label))


def mse_loss(input, label):
    from .nn import mean

    return mean(square_error_cost(input, label))


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, normalize=False):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits")
    out = _out(helper, x, shape=x.shape)
    helper.append_op(
        type="sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def log_loss(input, label, epsilon=1e-4):
    helper = LayerHelper("log_loss")
    out = _out(helper, input, shape=input.shape)
    helper.append_op(
        type="log_loss",
        inputs={"Predicted": [input], "Labels": [label]},
        outputs={"Loss": [out]},
        attrs={"epsilon": epsilon},
    )
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    out = _out(helper, input, shape=input.shape)
    residual = _out(helper, input, shape=input.shape, stop_gradient=True)
    helper.append_op(
        type="huber_loss",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [out], "Residual": [residual]},
        attrs={"delta": delta},
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    out = _out(helper, x, shape=(x.shape[0] if x.shape else -1, 1))
    diff = _out(helper, x, shape=x.shape, stop_gradient=True)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(
        type="smooth_l1_loss",
        inputs=inputs,
        outputs={"Out": [out], "Diff": [diff]},
        attrs={"sigma": sigma or 1.0},
    )
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    shp = () if reduction in ("mean", "sum", "batchmean") else x.shape
    out = _out(helper, x, shape=shp)
    helper.append_op(
        type="kldiv_loss",
        inputs={"X": [x], "Target": [target]},
        outputs={"Loss": [out]},
        attrs={"reduction": reduction},
    )
    return out

"""Collective layer wrappers.

Reference: python/paddle/fluid/layers/collective.py:20-172 —
_c_allreduce / _c_broadcast / _c_allgather / _c_reducescatter append
`c_*` ops with a ring_id attr. Here ring_id names a mesh axis at
execution time (parallel/ring registry).
"""

from __future__ import annotations

from ..layer_helper import LayerHelper
from .nn import _out

__all__ = ["_c_allreduce", "_c_broadcast", "_c_allgather", "_c_reducescatter"]


def _c_allreduce(x, out=None, reduce_type="sum", ring_id=0, use_calc_stream=False):
    helper = LayerHelper("c_allreduce_" + reduce_type)
    if out is None:
        out = _out(helper, x, shape=x.shape)
    helper.append_op(
        type="c_allreduce_" + reduce_type,
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"ring_id": ring_id, "use_calc_stream": use_calc_stream},
    )
    return out


def _c_broadcast(x, root=0, ring_id=0, use_calc_stream=False):
    helper = LayerHelper("c_broadcast")
    out = _out(helper, x, shape=x.shape)
    helper.append_op(
        type="c_broadcast",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"root": root, "ring_id": ring_id, "use_calc_stream": use_calc_stream},
    )
    return out


def _c_allgather(x, nranks, ring_id=0, use_calc_stream=False):
    helper = LayerHelper("c_allgather")
    shp = list(x.shape or ())
    if shp and shp[0] and shp[0] > 0:
        shp[0] *= nranks
    out = _out(helper, x, shape=tuple(shp))
    helper.append_op(
        type="c_allgather",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"nranks": nranks, "ring_id": ring_id, "use_calc_stream": use_calc_stream},
    )
    return out


def _c_reducescatter(x, nranks, ring_id=0, use_calc_stream=False):
    helper = LayerHelper("c_reducescatter")
    shp = list(x.shape or ())
    if shp and shp[0] and shp[0] > 0:
        shp[0] //= nranks
    out = _out(helper, x, shape=tuple(shp))
    helper.append_op(
        type="c_reducescatter",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"nranks": nranks, "ring_id": ring_id, "use_calc_stream": use_calc_stream},
    )
    return out

"""Detection layer wrappers (subset). Reference:
python/paddle/fluid/layers/detection.py."""

from __future__ import annotations

from ..layer_helper import LayerHelper
from .nn import _out

__all__ = ["iou_similarity", "box_coder", "prior_box"]


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    n = x.shape[0] if x.shape else -1
    m = y.shape[0] if y.shape else -1
    out = _out(helper, x, shape=(n, m))
    helper.append_op(
        type="iou_similarity", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}
    )
    return out


def box_coder(
    prior_box,
    prior_box_var,
    target_box,
    code_type="encode_center_size",
    box_normalized=True,
    name=None,
    axis=0,
):
    helper = LayerHelper("box_coder", name=name)
    out = _out(helper, target_box, shape=target_box.shape)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None and hasattr(prior_box_var, "name"):
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        type="box_coder",
        inputs=inputs,
        outputs={"OutputBox": [out]},
        attrs={"code_type": code_type, "box_normalized": box_normalized, "axis": axis},
    )
    return out


def prior_box(
    input,
    image,
    min_sizes,
    max_sizes=None,
    aspect_ratios=[1.0],
    variance=[0.1, 0.1, 0.2, 0.2],
    flip=False,
    clip=False,
    steps=[0.0, 0.0],
    offset=0.5,
    name=None,
):
    helper = LayerHelper("prior_box", name=name)
    boxes = _out(helper, input, shape=None, stop_gradient=True)
    variances = _out(helper, input, shape=None, stop_gradient=True)
    helper.append_op(
        type="prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "flip": flip,
            "clip": clip,
            "offset": offset,
        },
    )
    return boxes, variances

"""ParamAttr — per-parameter configuration.

Reference: python/paddle/fluid/param_attr.py.
"""

from __future__ import annotations

from typing import Optional

from .initializer import Initializer, XavierInitializer, ConstantInitializer


class ParamAttr:
    def __init__(
        self,
        name: Optional[str] = None,
        initializer: Optional[Initializer] = None,
        learning_rate: float = 1.0,
        regularizer=None,
        trainable: bool = True,
        gradient_clip=None,
        do_model_average: bool = False,
        logical_axes=None,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average
        # logical axis names per dim ("embed", "mlp", ...) — the
        # partition subsystem's rules table maps them to mesh axes
        # (partition/rules.py); None = untagged (replicated unless a
        # PartitionConfig var_rules pattern matches the name)
        self.logical_axes = tuple(logical_axes) if logical_axes else None

    @staticmethod
    def _to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, (list, tuple)):
            return [ParamAttr._to_attr(a) for a in arg]
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else ParamAttr(trainable=False)
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")


class WeightNormParamAttr(ParamAttr):
    """API-parity stub for weight normalization (reference
    param_attr.py WeightNormParamAttr)."""

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim

"""Disaggregated serving roles: the prefill tier, the decode pool,
and the service facade that hands requests between them.

Prefill is compute-bound (a prompt's worth of matmul per request);
decode is bandwidth-bound (one token's worth per step, every step).
Co-locating them on one engine makes every decode step pay for
whatever prefill happens to share the batch — chunked prefill (PR 12)
bounds the stall but cannot remove it. Splitting the phases does:

* ``PrefillWorker`` — a GenerationEngine pinned to chunked prefill
  (every request runs at ``max_new_tokens=1``); the finished prompt
  pages publish into its local trie as chunks complete, then
  ``spill_run`` streams them to the page store (blockwise-int8 on the
  wire — pagestore.py).
* ``DecodeWorker`` — a GenerationEngine whose admission consults the
  store BEFORE cold prefill (engine ``_consult_store``): matched runs
  splice into the local pool (``PagedKVCache.ingest_run``) and the
  sequence resumes at ``lengths=matched``. A freshly spawned or
  restarted decode worker on a populated store starts WARM — ROADMAP
  2(a) cross-engine prefix persistence.
* ``DisaggService`` — the engine-shaped facade the traffic tier
  drives unchanged: ``submit`` admits once, a dispatcher thread runs
  the prompt on the least-loaded prefill worker, spills, then hands
  the ticket to the decode worker chosen by the
  ``paddle_generation_*`` gauges (queue depth + active lanes). The
  decode worker re-derives the first output token from the spliced
  prefix (greedy — token-identical to co-located serving), so the
  handoff loses zero tokens by construction.

Token identity: with int8 KV pools the pages ship verbatim and the
split topology is BIT-identical to the co-located int8 engine; with
fp32 pools use ``disagg_wire_encoding="raw"`` for bitwise fidelity or
accept the blockwise-int8 error bound (kernels/quant.py) on the
streamed prefix.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..generation.engine import GenerationEngine, GenerationStream
from ..observability import tracing
from ..serving.engine import (EngineClosed, Overloaded, RequestCancelled,
                              ServingError)
from ..serving.metrics import StreamingHistogram

__all__ = ["PrefillWorker", "DecodeWorker", "DisaggService",
           "DisaggStream"]


class PrefillWorker:
    """A GenerationEngine pinned to the prefill phase: requests run
    chunked prefill to completion (one emitted token — the step that
    samples it IS the final prefill chunk) and their pages stream to
    the page store instead of staying for decode."""

    def __init__(self, predictor, config, store, **engine_kwargs):
        engine_kwargs.setdefault("mode", "ragged")
        engine_kwargs.setdefault("prefix_cache", True)
        self.store = store
        self.engine = GenerationEngine(predictor, config,
                                       page_store=store, phase="prefill",
                                       **engine_kwargs)

    def prefill(self, prompt, deadline_ms: Optional[float] = None,
                tenant: Optional[str] = None,
                timeout: Optional[float] = None) -> int:
        """Run ``prompt`` through chunked prefill and spill its full
        pages to the store. Returns pages spilled. Raises what the
        engine raises (Overloaded / EngineClosed / deadline)."""
        stream = self.engine.submit(prompt, max_new_tokens=1,
                                    eos_id=None, deadline_ms=deadline_ms,
                                    tenant=tenant)
        stream.result(timeout)
        return self.engine.spill_run(prompt)

    def queue_depth(self) -> int:
        return self.engine.queue_depth()

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def close(self, drain: bool = True) -> None:
        self.engine.close(drain=drain)


class DecodeWorker:
    """A GenerationEngine pinned to the decode phase, warm-started
    from the page store: queued prompts consult the store before cold
    prefill, splice any matched run, and resume at the fork point."""

    def __init__(self, predictor, config, store, **engine_kwargs):
        engine_kwargs.setdefault("mode", "ragged")
        engine_kwargs.setdefault("prefix_cache", True)
        self.store = store
        self.engine = GenerationEngine(predictor, config,
                                       page_store=store, phase="decode",
                                       **engine_kwargs)

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_id="default", deadline_ms: Optional[float] = None,
               on_token=None, tenant: Optional[str] = None
               ) -> GenerationStream:
        return self.engine.submit(prompt, max_new_tokens=max_new_tokens,
                                  eos_id=eos_id, deadline_ms=deadline_ms,
                                  on_token=on_token, tenant=tenant)

    def queue_depth(self) -> int:
        return self.engine.queue_depth()

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def close(self, drain: bool = True) -> None:
        self.engine.close(drain=drain)


class DisaggStream(GenerationStream):
    """The caller-facing stream for a disaggregated request: tokens
    relay from the decode worker's inner stream; cancel propagates to
    whichever phase currently owns the request (mid-handoff included
    — the dispatcher checks between prefill and decode submit)."""

    def __init__(self, service, on_token=None):
        super().__init__(service, on_token=on_token)
        self._inner: Optional[GenerationStream] = None

    def cancel(self) -> bool:
        ok = super().cancel()
        inner = self._inner
        if inner is not None:
            inner.cancel()
        return ok


class _HandoffJob:
    __slots__ = ("prompt", "max_new", "eos", "deadline", "stream",
                 "tenant", "enqueue_t", "ctx")

    def __init__(self, prompt, max_new, eos, deadline, stream, tenant):
        self.prompt = prompt
        self.max_new = max_new
        self.eos = eos
        self.deadline = deadline        # absolute monotonic or None
        self.stream = stream
        self.tenant = tenant
        self.enqueue_t = time.monotonic()
        # the submitter's ambient trace context rides the job across
        # the queue: the dispatcher thread re-attaches it, so the
        # handoff/prefill/decode spans stay in the REQUEST's trace
        # instead of rooting a fresh one per dispatcher thread
        self.ctx = tracing.current()


class _ServiceMetrics:
    """The engine-metrics duck the traffic estimator prices from:
    service-level TTFT (submit -> first decode token, handoff
    included), decode-pool ITL/step medians, request counters."""

    def __init__(self, service: "DisaggService"):
        self._svc = service
        self._lock = threading.Lock()
        self.ttft_ms = StreamingHistogram()
        self.handoff_ms = StreamingHistogram()
        self.prefill_ms = StreamingHistogram()
        self._c = {"requests_total": 0, "responses_total": 0,
                   "rejected_total": 0, "handoffs_total": 0,
                   "handoff_failures_total": 0, "cancelled_total": 0}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._c[name] += n

    def observe(self, hist: str, v: float) -> None:
        with self._lock:
            getattr(self, hist).record(v)

    def snapshot(self) -> Dict[str, Any]:
        decode = [w.engine.metrics.snapshot()
                  for w in self._svc._decode]
        busiest = max(decode, key=lambda s: s["itl_ms"]["count"])
        with self._lock:
            out: Dict[str, Any] = dict(self._c)
            out["ttft_ms"] = self.ttft_ms.snapshot()
            out["handoff_ms"] = self.handoff_ms.snapshot()
            out["prefill_ms"] = self.prefill_ms.snapshot()
        # decode-side medians come from the busiest decode worker (a
        # merged histogram would mix workers with different loads);
        # queue depth aggregates across the whole topology
        out["itl_ms"] = busiest["itl_ms"]
        out["decode_step_ms"] = busiest["decode_step_ms"]
        out["queue_depth"] = self._svc.queue_depth()
        out["active_seqs"] = sum(s["active_seqs"] for s in decode)
        return out


class DisaggService:
    """The split topology behind one engine-shaped surface.

        store = pagestore.PageStoreServer(page_size=16)
        svc = DisaggService(
            prefill=[PrefillWorker(pred, cfg, client_for(store))],
            decode=[DecodeWorker(pred, cfg, client_for(store))])
        stream = svc.submit(prompt, max_new_tokens=64)   # engine duck
        ctl = TrafficController(eng, generation_engine=svc)

    ``submit`` admits once (Overloaded before any work, same contract
    as the engine); dispatcher threads run prefill -> spill -> decode
    handoff; ``/healthz`` reads ``phase_health()`` through the
    traffic controller's fragment. Registers ``paddle_disagg_*``
    gauges (handoff latency, store traffic via the workers' engines).
    """

    def __init__(self, prefill: List[PrefillWorker],
                 decode: List[DecodeWorker], *,
                 handoff_threads: Optional[int] = None,
                 queue_capacity: Optional[int] = None):
        if not prefill or not decode:
            raise ValueError("DisaggService needs >= 1 prefill and >= 1 "
                             "decode worker")
        from ..flags import flag

        self._prefill = list(prefill)
        self._decode = list(decode)
        d0 = self._decode[0].engine
        # the engine-duck attributes the traffic tier reads
        self.mode = d0.mode
        self.chunk_tokens = d0.chunk_tokens
        self.prefix_cache = True
        self.default_max_new = d0.default_max_new
        self.default_eos = d0.default_eos
        self.lanes = sum(w.engine.lanes for w in self._decode)
        self.config = d0.config
        self.cache = d0.cache           # feasibility duck (can_fit_ever)
        self.queue_capacity = int(
            queue_capacity or self._prefill[0].engine.queue_capacity)
        self.phase = "disagg"
        self.metrics = _ServiceMetrics(self)
        self._cond = threading.Condition()
        self._jobs: List[_HandoffJob] = []
        self._closed = False
        self._handoff_hook = None       # test seam: between phases
        n = int(handoff_threads or flag("disagg_handoff_threads"))
        self._threads = [
            threading.Thread(target=self._dispatch_loop,
                             name=f"pt-disagg-handoff-{i}", daemon=True)
            for i in range(max(1, n))]
        for t in self._threads:
            t.start()
        from ..observability import watch_disagg

        watch_disagg(self)

    # -- the engine duck ------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_id="default", deadline_ms: Optional[float] = None,
               on_token=None, tenant: Optional[str] = None
               ) -> DisaggStream:
        prompt = np.asarray(prompt, dtype=np.int64).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.default_max_new)
        eos = self.default_eos if eos_id == "default" else eos_id
        total = int(prompt.size) + max_new
        if total > self.config.max_position:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new}) "
                f"exceeds max_position {self.config.max_position}")
        if not self.cache.can_fit_ever(total):
            self.metrics.inc("rejected_total")
            raise Overloaded(
                f"request needs {self.cache.pages_needed(total)} pages; "
                "no decode pool can ever hold it")
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        stream = DisaggStream(self, on_token=on_token)
        job = _HandoffJob(prompt, max_new, eos, deadline, stream, tenant)
        with self._cond:
            if self._closed:
                raise EngineClosed("DisaggService is closed")
            if len(self._jobs) >= self.queue_capacity:
                self.metrics.inc("rejected_total")
                raise Overloaded(
                    f"disagg handoff queue full ({self.queue_capacity} "
                    "pending)")
            self._jobs.append(job)
            self.metrics.inc("requests_total")
            self._cond.notify()
        return stream

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 eos_id="default", deadline_ms: Optional[float] = None,
                 timeout: Optional[float] = None) -> List[int]:
        return self.submit(prompt, max_new_tokens, eos_id,
                           deadline_ms).result(timeout)

    def queue_depth(self) -> int:
        return (len(self._jobs)
                + sum(w.engine.queue_depth() for w in self._prefill))

    def prefix_probe(self, tokens) -> int:
        """Longest warm prefix across the decode pool AND the page
        store — the traffic tier's store-hit TTFT pricing."""
        best = max(w.engine.prefix_probe(tokens) for w in self._decode)
        store = self._decode[0].store
        try:
            ps = self._decode[0].engine.page_size
            best = max(best, store.match_pages(tokens) * ps)
        except Exception:  # noqa: BLE001 — a dead store prices as cold
            pass
        return best

    def handoff_overhead_ms(self) -> float:
        """Median prefill->decode handoff wall time — the estimator's
        extra TTFT term for the split topology."""
        h = self.metrics.handoff_ms
        return float(h.quantile(0.5)) if h.count else 0.0

    def _kick(self) -> None:
        with self._cond:
            self._cond.notify_all()

    # -- handoff dispatch -----------------------------------------------------
    def _pick_prefill(self) -> PrefillWorker:
        return min(self._prefill, key=lambda w: w.engine.queue_depth())

    def _pick_decode(self) -> DecodeWorker:
        """The decode worker the paddle_generation_* gauges call
        least loaded: queued + active sequences, per worker."""
        def load(w: DecodeWorker):
            snap = w.engine.metrics.snapshot()
            return snap["queue_depth"] + snap["active_seqs"]

        return min(self._decode, key=load)

    def _dispatch_loop(self) -> None:
        while True:
            with self._cond:
                while not self._jobs and not self._closed:
                    self._cond.wait(0.05)
                if not self._jobs:
                    if self._closed:
                        return
                    continue
                job = self._jobs.pop(0)
            try:
                with tracing.attach(job.ctx), \
                     tracing.span("disagg/handoff", {
                         "queue_ms": round(
                             (time.monotonic() - job.enqueue_t) * 1e3, 3),
                         "prompt_tokens": int(job.prompt.size)}):
                    self._handoff(job)
            except Exception as e:  # noqa: BLE001 — one bad job must not kill the lane
                self.metrics.inc("handoff_failures_total")
                job.stream._finish("error", ServingError(
                    f"disagg handoff failed: {e!r}"))

    def _remaining_ms(self, job: _HandoffJob) -> Optional[float]:
        if job.deadline is None:
            return None
        return max(1.0, (job.deadline - time.monotonic()) * 1e3)

    def _handoff(self, job: _HandoffJob) -> None:
        stream = job.stream
        if stream._cancelled:
            self.metrics.inc("cancelled_total")
            stream._finish("cancelled", RequestCancelled(
                "cancelled before prefill"))
            return
        t0 = time.monotonic()
        pf = self._pick_prefill()
        try:
            with tracing.span("disagg/prefill_phase"):
                pf.prefill(job.prompt,
                           deadline_ms=self._remaining_ms(job),
                           tenant=job.tenant)
        except (Overloaded, EngineClosed) as e:
            self.metrics.inc("handoff_failures_total")
            stream._finish("error", e)
            return
        except Exception as e:  # noqa: BLE001 — deadline/cancel surface here
            self.metrics.inc("handoff_failures_total")
            stream._finish("error", ServingError(
                f"prefill phase failed: {e!r}"))
            return
        t_prefilled = time.monotonic()
        self.metrics.observe("prefill_ms", (t_prefilled - t0) * 1e3)
        if self._handoff_hook is not None:
            self._handoff_hook(job)
        if stream._cancelled:
            # slow-client cancel mid-handoff: the prompt's pages stay
            # in the store (refcounted, reusable by siblings); no
            # decode lane is ever spent
            self.metrics.inc("cancelled_total")
            stream._finish("cancelled", RequestCancelled(
                "cancelled between prefill and decode"))
            return
        dw = self._pick_decode()
        try:
            with tracing.span("disagg/decode_submit"):
                inner = dw.submit(job.prompt, max_new_tokens=job.max_new,
                                  eos_id=job.eos,
                                  deadline_ms=self._remaining_ms(job),
                                  on_token=stream._push, tenant=job.tenant)
        except (Overloaded, EngineClosed) as e:
            self.metrics.inc("handoff_failures_total")
            stream._finish("error", e)
            return
        stream._inner = inner
        if stream._cancelled:
            inner.cancel()
        self.metrics.inc("handoffs_total")
        self.metrics.observe(
            "handoff_ms", (time.monotonic() - t_prefilled) * 1e3)
        inner.add_done_callback(
            lambda s, outer=stream, t=job.enqueue_t: self._relay_done(
                outer, s, t))

    def _relay_done(self, outer: DisaggStream, inner: GenerationStream,
                    enqueue_t: float) -> None:
        outer.verified_tokens = inner.verified_tokens
        outer.accepted_draft_tokens = inner.accepted_draft_tokens
        if inner.first_token_at is not None:
            self.metrics.observe(
                "ttft_ms", (inner.first_token_at - enqueue_t) * 1e3)
        if inner.error is None and inner.finish_reason in (
                "eos", "length", "capacity"):
            self.metrics.inc("responses_total")
        outer._finish(inner.finish_reason or "error", inner.error)

    # -- introspection / lifecycle -------------------------------------------
    def phase_health(self) -> List[Dict[str, Any]]:
        """The /healthz per-worker phase fragment."""
        out = []
        for kind, workers in (("prefill", self._prefill),
                              ("decode", self._decode)):
            for i, w in enumerate(workers):
                snap = w.engine.metrics.snapshot()
                out.append({
                    "worker": f"{kind}-{i}",
                    "phase": w.engine.phase,
                    "queue_depth": snap["queue_depth"],
                    "active_seqs": snap["active_seqs"],
                })
        return out

    def stats(self) -> Dict[str, Any]:
        return {
            "service": self.metrics.snapshot(),
            "phases": self.phase_health(),
            "prefill": [w.stats() for w in self._prefill],
            "decode": [w.stats() for w in self._decode],
        }

    def stats_numeric(self) -> Dict[str, Any]:
        """The paddle_disagg_* gauge family for this service: handoff
        volume + latency, pages shipped/pulled and wire bytes summed
        over the workers' engines and the store."""
        snap = self.metrics.snapshot()
        out: Dict[str, Any] = {
            "requests_total": snap["requests_total"],
            "responses_total": snap["responses_total"],
            "rejected_total": snap["rejected_total"],
            "handoffs_total": snap["handoffs_total"],
            "handoff_failures_total": snap["handoff_failures_total"],
            "cancelled_total": snap["cancelled_total"],
            "handoff_ms": snap["handoff_ms"],
            "ttft_ms": snap["ttft_ms"],
            "queue_depth": snap["queue_depth"],
            "prefill_workers": len(self._prefill),
            "decode_workers": len(self._decode),
            "pages_shipped_total": sum(
                w.engine.store_pages_spilled_total for w in self._prefill),
            "pages_pulled_total": sum(
                w.engine.store_pages_pulled_total for w in self._decode),
            "store_lookups_total": sum(
                w.engine.store_lookups_total for w in self._decode),
            "store_hits_total": sum(
                w.engine.store_hits_total for w in self._decode),
        }
        lk = out["store_lookups_total"]
        out["store_hit_rate"] = (round(out["store_hits_total"] / lk, 4)
                                 if lk else 0.0)
        try:
            st = self._decode[0].store.stats()
            out["store_pages"] = st["pages"]
            out["wire_bytes_total"] = st.get("wire_bytes_total", 0)
            out["fp32_bytes_total"] = st.get("fp32_bytes_total", 0)
            out["wire_ratio"] = st.get("wire_ratio", 0.0)
        except Exception:  # noqa: BLE001 — gauges must never raise
            pass
        return out

    def close(self, drain: bool = True,
              timeout: Optional[float] = 60.0) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        deadline = (time.monotonic() + timeout) if timeout else None
        for t in self._threads:
            left = (max(0.1, deadline - time.monotonic())
                    if deadline else None)
            t.join(left)
        for w in self._prefill + self._decode:
            w.close(drain=drain)

    def __enter__(self) -> "DisaggService":
        return self

    def __exit__(self, *exc):
        self.close(drain=exc[0] is None)

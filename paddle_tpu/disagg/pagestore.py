"""Host-RAM KV page store + the length-prefixed TCP wire between
prefill and decode workers.

The store is a trie of serialized PAGE RUNS keyed exactly like the
radix cache's trie (``PagedKVCache._page_key``): each edge is one full
page identified by the page_size-token tuple it holds. A prefill
worker ``put_run``s the finished pages of a prompt; a decode worker
``match``es its queued prompt and pulls back the longest stored
prefix, splices it into its own pool (``PagedKVCache.ingest_run``)
and resumes at ``lengths=matched`` — cross-engine prefix persistence
(ROADMAP 2(a)) with the store as the rendezvous.

Wire encoding (``encode_page``/``decode_page``): the blockwise-int8
unit is ``block = head_dim`` — one fp32 scale per (head, token slot),
which is EXACTLY the int8 KV pool's scale-plane layout
(kernels/quant.py semantics, kvcache.py int8 pools). Consequences:

* int8 pool pages + their scale planes ship VERBATIM in both
  directions — the split topology is bit-identical to co-located
  int8 serving (the token-identity gate);
* fp32 pool pages quantize on encode at ``(hd + 4) / (4 * hd)`` of
  the fp32 bytes (0.281x at head_dim 32 — the <= 0.3x wire gate),
  with the round-trip error bounded by ``blockwise_error_bound``;
* ``encoding="raw"`` ships fp32 pages untouched when bitwise fidelity
  matters more than bytes.

The TCP wire (``PageStoreServer`` / ``PageStoreClient``) is stdlib
socket + struct, CPU-CI-runnable like the PR-11 coordination-service
wire: every frame is ``!I`` length + JSON header + binary payload;
the client is a drop-in for ``HostPageStore`` (duck-typed put_run /
match / match_pages / stats), so engines and roles never care whether
the store is in-process or remote. ``discover_store`` resolves the
store endpoint from the coordinator env contract
(``PADDLE_PAGESTORE_ENDPOINT``, falling back to the first
``PADDLE_TRAINER_ENDPOINTS`` host + the ``disagg_store_port`` flag).
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "encode_page", "decode_page", "run_for_pool", "fp32_page_bytes",
    "HostPageStore", "PageStoreServer", "PageStoreClient",
    "discover_store", "store_endpoint_from_env",
]

_HDR = struct.Struct("!I")


# -- page wire encoding ------------------------------------------------------

def fp32_page_bytes(num_layers: int, num_kv_heads: int, page_size: int,
                    head_dim: int) -> int:
    """fp32 bytes of one K+V page across layers — the denominator of
    the wire-bytes-vs-fp32 gauge/gate."""
    return 2 * num_layers * num_kv_heads * page_size * head_dim * 4


def _quantize_body(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """fp32 [L, KVH, ps, hd] -> (int8 same shape, fp32 scales
    [L, KVH, ps]) with block = head_dim — kernels/quant.py blockwise
    semantics, evaluated through the real kernel so the wire and the
    int8 pool can never drift apart."""
    from ..kernels.quant import blockwise_quantize

    shape = x.shape
    q, s = blockwise_quantize(x.reshape(-1, shape[-1]).astype(np.float32))
    return (np.asarray(q).reshape(shape),
            np.asarray(s).reshape(shape[:-1]).astype(np.float32))


def _dequantize_body(q: np.ndarray, s: np.ndarray) -> np.ndarray:
    from ..kernels.quant import blockwise_dequantize

    shape = q.shape
    out = blockwise_dequantize(q.reshape(-1, shape[-1]),
                               np.asarray(s, np.float32).reshape(-1))
    return np.asarray(out, np.float32).reshape(shape)


def encode_page(k, v, k_scales=None, v_scales=None, *,
                encoding: str = "int8_block") -> bytes:
    """Serialize ONE page (k/v ``[L, KVH, ps, hd]``, pool dtype) into
    a self-describing blob. int8 inputs (+ scale planes ``[L, KVH,
    ps]``) ship verbatim regardless of ``encoding``; fp32 inputs
    quantize blockwise (``int8_block``) or ship raw (``raw``)."""
    k = np.asarray(k)
    v = np.asarray(v)
    L, kvh, ps, hd = k.shape
    if k.dtype == np.int8:
        if k_scales is None or v_scales is None:
            raise ValueError("encode_page: int8 pages need scale planes")
        enc = "int8_block"
        kq, ks = k, np.asarray(k_scales, np.float32)
        vq, vs = v, np.asarray(v_scales, np.float32)
    elif encoding == "raw":
        enc = "raw"
        kq, ks = k.astype(np.float32), np.zeros(0, np.float32)
        vq, vs = v.astype(np.float32), np.zeros(0, np.float32)
    elif encoding == "int8_block":
        enc = "int8_block"
        kq, ks = _quantize_body(k)
        vq, vs = _quantize_body(v)
    else:
        raise ValueError(f"unknown wire encoding {encoding!r}")
    parts = [np.ascontiguousarray(a).tobytes() for a in (kq, vq, ks, vs)]
    head = json.dumps({
        "enc": enc, "L": L, "kvh": kvh, "ps": ps, "hd": hd,
        "sizes": [len(p) for p in parts],
    }).encode("utf-8")
    return b"".join([_HDR.pack(len(head)), head] + parts)


def decode_page(blob: bytes) -> Dict[str, Any]:
    """Inverse of ``encode_page``: blob -> dict with ``enc``, dims and
    the k/v (+ scale) arrays in their WIRE dtype."""
    (hlen,) = _HDR.unpack_from(blob, 0)
    head = json.loads(blob[_HDR.size:_HDR.size + hlen].decode("utf-8"))
    L, kvh, ps, hd = head["L"], head["kvh"], head["ps"], head["hd"]
    off = _HDR.size + hlen
    parts = []
    for n in head["sizes"]:
        parts.append(blob[off:off + n])
        off += n
    body = (L, kvh, ps, hd)
    if head["enc"] == "raw":
        k = np.frombuffer(parts[0], np.float32).reshape(body)
        v = np.frombuffer(parts[1], np.float32).reshape(body)
        ks = vs = None
    else:
        k = np.frombuffer(parts[0], np.int8).reshape(body)
        v = np.frombuffer(parts[1], np.int8).reshape(body)
        ks = np.frombuffer(parts[2], np.float32).reshape(body[:3])
        vs = np.frombuffer(parts[3], np.float32).reshape(body[:3])
    return {"enc": head["enc"], "L": L, "kvh": kvh, "ps": ps, "hd": hd,
            "k": k, "v": v, "k_scales": ks, "v_scales": vs}


def run_for_pool(blobs: List[bytes], pool_dtype: str):
    """Decode a matched run of page blobs into the arrays
    ``PagedKVCache.ingest_run`` wants for a pool of ``pool_dtype``:
    ``(n, k_run, v_run, k_scales, v_scales)``. int8 blobs splice into
    int8 pools verbatim (bit-identical handoff); the mixed cases
    convert through the blockwise codec (raw->int8 quantizes,
    int8->fp32 dequantizes — bounded, not bitwise)."""
    if not blobs:
        return 0, None, None, None, None
    int8_pool = np.dtype(pool_dtype) == np.int8
    pages = [decode_page(b) for b in blobs]
    ks, vs, ksc, vsc = [], [], [], []
    for pg in pages:
        if int8_pool:
            if pg["enc"] == "raw":
                kq, kb = _quantize_body(pg["k"])
                vq, vb = _quantize_body(pg["v"])
            else:
                kq, kb = pg["k"], pg["k_scales"]
                vq, vb = pg["v"], pg["v_scales"]
            ks.append(kq), vs.append(vq), ksc.append(kb), vsc.append(vb)
        else:
            if pg["enc"] == "raw":
                ks.append(pg["k"]), vs.append(pg["v"])
            else:
                ks.append(_dequantize_body(pg["k"], pg["k_scales"]))
                vs.append(_dequantize_body(pg["v"], pg["v_scales"]))
    k_run = np.stack(ks)
    v_run = np.stack(vs)
    if int8_pool:
        return len(pages), k_run, v_run, np.stack(ksc), np.stack(vsc)
    return len(pages), k_run, v_run, None, None


# -- the host-RAM store ------------------------------------------------------

class _StoreNode:
    __slots__ = ("key", "blob", "parent", "children", "last_used")

    def __init__(self, key, blob, parent):
        self.key = key
        self.blob = blob
        self.parent = parent
        self.children: Dict[tuple, "_StoreNode"] = {}
        self.last_used = 0


class HostPageStore:
    """The in-process store: a trie of page blobs keyed by exact
    page_size-token tuples, LRU-leaf-evicted against ``max_bytes``.
    Thread-safe; also the backing object behind ``PageStoreServer``."""

    def __init__(self, page_size: int, *, max_bytes: int = 0):
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.page_size = int(page_size)
        self.max_bytes = max(0, int(max_bytes))
        self._lock = threading.Lock()
        self._root = _StoreNode(None, None, None)
        self._tick = 0
        self._pages = 0
        self._bytes = 0
        # counters behind the paddle_disagg_* store gauges
        self.put_runs_total = 0
        self.put_pages_total = 0
        self.dup_pages_total = 0
        self.lookups_total = 0
        self.hits_total = 0
        self.served_pages_total = 0
        self.evictions_total = 0
        self.wire_bytes_total = 0       # actual blob bytes accepted
        self.fp32_bytes_total = 0       # what the same pages cost in fp32
        self.served_wire_bytes_total = 0
        from ..observability import watch_disagg

        watch_disagg(self)

    def _keys(self, tokens) -> List[tuple]:
        toks = np.asarray(tokens).reshape(-1)
        ps = self.page_size
        return [tuple(int(t) for t in toks[i * ps:(i + 1) * ps])
                for i in range(int(toks.size) // ps)]

    def _touch(self, node: _StoreNode) -> None:
        self._tick += 1
        node.last_used = self._tick

    def _evict_lru_leaf_locked(self) -> bool:
        best: Optional[_StoreNode] = None
        stack = [self._root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                if child.children:
                    stack.append(child)
                elif best is None or child.last_used < best.last_used:
                    best = child
        if best is None:
            return False
        del best.parent.children[best.key]
        self._pages -= 1
        self._bytes -= len(best.blob)
        self.evictions_total += 1
        return True

    def put_run(self, tokens, blobs: List[bytes]) -> int:
        """Store ``blobs`` (one encoded page each) along ``tokens``'
        page-aligned path; pages already present are touched, not
        rewritten (the first publisher wins, like the radix trie).
        Returns newly stored pages."""
        keys = self._keys(tokens)
        with self._lock:
            node = self._root
            new = 0
            for key, blob in zip(keys, blobs):
                child = node.children.get(key)
                if child is None:
                    child = _StoreNode(key, bytes(blob), node)
                    node.children[key] = child
                    self._pages += 1
                    self._bytes += len(blob)
                    self.wire_bytes_total += len(blob)
                    try:
                        hd = decode_page(blob)
                        self.fp32_bytes_total += fp32_page_bytes(
                            hd["L"], hd["kvh"], hd["ps"], hd["hd"])
                    except Exception:
                        pass
                    new += 1
                else:
                    self.dup_pages_total += 1
                self._touch(child)
                node = child
            self.put_runs_total += 1
            self.put_pages_total += new
            while (self.max_bytes and self._bytes > self.max_bytes
                   and self._evict_lru_leaf_locked()):
                pass
            return new

    def match_pages(self, tokens) -> int:
        """Pure peek: pages the store would serve for this prompt.
        No counters, no LRU touch — the traffic tier's pricing probe."""
        keys = self._keys(tokens)
        with self._lock:
            node, n = self._root, 0
            for key in keys:
                node = node.children.get(key)
                if node is None:
                    break
                n += 1
            return n

    def match(self, tokens, max_pages: int = 0) -> List[bytes]:
        """Longest stored page run along ``tokens``; returns the blobs
        in order (empty list = miss)."""
        keys = self._keys(tokens)
        if max_pages:
            keys = keys[:max_pages]
        with self._lock:
            self.lookups_total += 1
            node = self._root
            out: List[bytes] = []
            for key in keys:
                child = node.children.get(key)
                if child is None:
                    break
                self._touch(child)
                out.append(child.blob)
                node = child
            if out:
                self.hits_total += 1
                self.served_pages_total += len(out)
                self.served_wire_bytes_total += sum(len(b) for b in out)
            return out

    def clear(self) -> None:
        with self._lock:
            self._root.children.clear()
            self._pages = 0
            self._bytes = 0

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            lk = self.lookups_total
            fp = self.fp32_bytes_total
            return {
                "pages": self._pages,
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "put_runs_total": self.put_runs_total,
                "put_pages_total": self.put_pages_total,
                "dup_pages_total": self.dup_pages_total,
                "lookups_total": lk,
                "hits_total": self.hits_total,
                "hit_rate": round(self.hits_total / lk, 4) if lk else 0.0,
                "served_pages_total": self.served_pages_total,
                "served_wire_bytes_total": self.served_wire_bytes_total,
                "evictions_total": self.evictions_total,
                "wire_bytes_total": self.wire_bytes_total,
                "fp32_bytes_total": fp,
                "wire_ratio": (round(self.wire_bytes_total / fp, 4)
                               if fp else 0.0),
            }

    def stats_numeric(self) -> Dict[str, Any]:
        return self.stats()


# -- the TCP wire ------------------------------------------------------------

def _recv_exact(conn: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("page store peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _send_frame(conn: socket.socket, head: Dict[str, Any],
                payload: bytes = b"") -> None:
    hb = json.dumps(head).encode("utf-8")
    conn.sendall(_HDR.pack(len(hb) + len(payload) + _HDR.size)
                 + _HDR.pack(len(hb)) + hb + payload)


def _recv_frame(conn: socket.socket) -> Tuple[Dict[str, Any], bytes]:
    (total,) = _HDR.unpack(_recv_exact(conn, _HDR.size))
    body = _recv_exact(conn, total)
    (hlen,) = _HDR.unpack_from(body, 0)
    head = json.loads(body[_HDR.size:_HDR.size + hlen].decode("utf-8"))
    return head, body[_HDR.size + hlen:]


class PageStoreServer:
    """Serve a ``HostPageStore`` over the length-prefixed TCP wire.
    One thread per connection (workers hold one connection each);
    ops: put / match / probe / stats / clear."""

    def __init__(self, store: Optional[HostPageStore] = None, *,
                 page_size: int = 0, host: str = "127.0.0.1",
                 port: int = 0, max_bytes: int = 0, start: bool = True):
        if store is None:
            if page_size < 1:
                raise ValueError("PageStoreServer needs a store or a "
                                 "page_size")
            store = HostPageStore(page_size, max_bytes=max_bytes)
        self.store = store
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(32)
        self.host, self.port = self._sock.getsockname()[:2]
        self._closed = False
        self._conns: List[socket.socket] = []
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="pagestore-accept",
                                        daemon=True)
        if start:
            self._thread.start()

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             name="pagestore-conn", daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        from ..observability import propagate, tracing

        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while not self._closed:
                head, payload = _recv_frame(conn)
                # the caller's trace context rides the frame head
                # ("trace": traceparent, stamped by PageStoreClient) —
                # the RPC's span joins the caller's trace across the
                # TCP hop instead of starting an orphan root
                ctx = propagate.parse_traceparent(head.pop("trace", None))
                try:
                    with tracing.attach(ctx), \
                         tracing.span(
                             f"pagestore/{head.get('op', 'unknown')}",
                             {"payload_bytes": len(payload)}):
                        self._dispatch(conn, head, payload)
                except Exception as exc:   # noqa: BLE001 — wire-reported
                    _send_frame(conn, {"ok": 0, "error": str(exc)})
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn, head, payload) -> None:
        op = head.get("op")
        if op == "put":
            blobs, off = [], 0
            for n in head["sizes"]:
                blobs.append(payload[off:off + n])
                off += n
            new = self.store.put_run(head["tokens"], blobs)
            _send_frame(conn, {"ok": 1, "new": new})
        elif op == "match":
            blobs = self.store.match(head["tokens"],
                                     int(head.get("max_pages", 0)))
            _send_frame(conn, {"ok": 1, "sizes": [len(b) for b in blobs]},
                        b"".join(blobs))
        elif op == "probe":
            _send_frame(conn, {"ok": 1,
                               "pages": self.store.match_pages(
                                   head["tokens"])})
        elif op == "stats":
            _send_frame(conn, {"ok": 1, "stats": self.store.stats()})
        elif op == "clear":
            self.store.clear()
            _send_frame(conn, {"ok": 1})
        else:
            _send_frame(conn, {"ok": 0, "error": f"unknown op {op!r}"})

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)


class PageStoreClient:
    """One persistent connection to a ``PageStoreServer`` — the same
    duck surface as ``HostPageStore`` (put_run / match / match_pages /
    stats / clear), plus client-side wire-byte counters so a worker's
    gauges report ITS traffic, not the whole store's."""

    def __init__(self, host: str, port: int, *, timeout_s: float = 5.0,
                 page_size: int = 0):
        self.host, self.port = host, int(port)
        self.page_size = int(page_size)
        self._timeout = float(timeout_s)
        self._lock = threading.Lock()
        self._conn: Optional[socket.socket] = None
        self.bytes_sent_total = 0
        self.bytes_received_total = 0
        self.rpc_errors_total = 0
        from ..observability import watch_disagg

        watch_disagg(self)

    def _ensure_conn(self) -> socket.socket:
        if self._conn is None:
            conn = socket.create_connection((self.host, self.port),
                                            timeout=self._timeout)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conn = conn
        return self._conn

    def _rpc(self, head: Dict[str, Any],
             payload: bytes = b"") -> Tuple[Dict[str, Any], bytes]:
        from ..observability import propagate

        tp = propagate.current_traceparent()
        if tp is not None:
            # propagate the ambient trace over the wire: the server
            # side attaches it, so its pagestore/<op> span parents
            # under the prefill/decode worker's span
            head.setdefault("trace", tp)
        with self._lock:
            try:
                conn = self._ensure_conn()
                _send_frame(conn, head, payload)
                self.bytes_sent_total += len(payload)
                resp, body = _recv_frame(conn)
                self.bytes_received_total += len(body)
            except (ConnectionError, OSError):
                self.rpc_errors_total += 1
                if self._conn is not None:
                    try:
                        self._conn.close()
                    except OSError:
                        pass
                    self._conn = None
                raise
        if not resp.get("ok"):
            raise RuntimeError(
                f"page store error: {resp.get('error', 'unknown')}")
        return resp, body

    @staticmethod
    def _token_list(tokens) -> List[int]:
        return [int(t) for t in np.asarray(tokens).reshape(-1)]

    def put_run(self, tokens, blobs: List[bytes]) -> int:
        resp, _ = self._rpc({"op": "put",
                             "tokens": self._token_list(tokens),
                             "sizes": [len(b) for b in blobs]},
                            b"".join(blobs))
        return int(resp["new"])

    def match(self, tokens, max_pages: int = 0) -> List[bytes]:
        resp, body = self._rpc({"op": "match",
                                "tokens": self._token_list(tokens),
                                "max_pages": int(max_pages)})
        blobs, off = [], 0
        for n in resp["sizes"]:
            blobs.append(body[off:off + n])
            off += n
        return blobs

    def match_pages(self, tokens) -> int:
        resp, _ = self._rpc({"op": "probe",
                             "tokens": self._token_list(tokens)})
        return int(resp["pages"])

    def stats(self) -> Dict[str, Any]:
        resp, _ = self._rpc({"op": "stats"})
        return resp["stats"]

    def clear(self) -> None:
        self._rpc({"op": "clear"})

    def stats_numeric(self) -> Dict[str, Any]:
        return {
            "client_bytes_sent_total": self.bytes_sent_total,
            "client_bytes_received_total": self.bytes_received_total,
            "client_rpc_errors_total": self.rpc_errors_total,
        }

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except OSError:
                    pass
                self._conn = None


# -- discovery (coordinator env contract) ------------------------------------

def store_endpoint_from_env() -> Optional[str]:
    """Resolve the page store endpoint the way distributed workers
    resolve each other (distributed/coordinator.py env contract):
    ``PADDLE_PAGESTORE_ENDPOINT`` wins; otherwise the store is assumed
    co-located with trainer 0 (first ``PADDLE_TRAINER_ENDPOINTS``
    host) on the ``disagg_store_port`` flag; otherwise the
    ``disagg_store_endpoint`` flag."""
    ep = os.environ.get("PADDLE_PAGESTORE_ENDPOINT", "").strip()
    if ep:
        return ep
    peers = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").strip()
    if peers:
        from ..flags import flag

        host = peers.split(",")[0].rsplit(":", 1)[0]
        return f"{host}:{int(flag('disagg_store_port'))}"
    from ..flags import flag

    ep = str(flag("disagg_store_endpoint")).strip()
    return ep or None


def discover_store(*, page_size: int = 0,
                   timeout_s: Optional[float] = None
                   ) -> Optional[PageStoreClient]:
    """Connect to the env-discovered page store; None when the env
    names no store (co-located deployment — disagg stays off)."""
    ep = store_endpoint_from_env()
    if not ep:
        return None
    host, port = ep.rsplit(":", 1)
    if timeout_s is None:
        from ..flags import flag

        timeout_s = float(flag("disagg_fetch_timeout_s"))
    return PageStoreClient(host, int(port), timeout_s=timeout_s,
                           page_size=page_size)

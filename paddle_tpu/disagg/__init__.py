"""paddle_tpu.disagg — disaggregated prefill/decode serving.

The package splits the two inference phases onto separate engines and
streams finished KV pages between them through a host-RAM page store:

* ``pagestore`` — the store itself (radix-keyed page runs), the
  blockwise-int8 wire encoding (int8-KV pool pages ship VERBATIM;
  fp32 pages quantize one scale per (head, token-slot) — exactly the
  pool's scale-plane layout), the length-prefixed TCP server/client,
  and coordinator-env store discovery.
* ``roles`` — ``PrefillWorker`` (engine pinned to chunked prefill,
  publishes pages to the store), ``DecodeWorker`` (admission consults
  the store before cold prefill and resumes at the fork point), and
  ``DisaggService`` (the engine-shaped facade the traffic tier drives
  unchanged: admit once, prefill on the prefill pool, hand the ticket
  to the decode worker the ``paddle_generation_*`` gauges pick).

Because the decode worker re-derives the first output token from the
spliced prefix, the split topology is token-identical to co-located
greedy serving — bit-identical with int8 KV pools or
``disagg_wire_encoding="raw"`` (tests/test_disagg.py gates this).
``tools/disagg_bench.py --smoke`` gates decode ITL flat under
prefill-saturating load, wire bytes <= 0.3x fp32, and warm-start TTFT
<= 0.5x cold.
"""

from __future__ import annotations

from .pagestore import (HostPageStore, PageStoreClient, PageStoreServer,
                        decode_page, discover_store, encode_page,
                        fp32_page_bytes, run_for_pool,
                        store_endpoint_from_env)
from .roles import DecodeWorker, DisaggService, DisaggStream, PrefillWorker

__all__ = [
    "HostPageStore", "PageStoreServer", "PageStoreClient",
    "encode_page", "decode_page", "run_for_pool", "fp32_page_bytes",
    "store_endpoint_from_env", "discover_store",
    "PrefillWorker", "DecodeWorker", "DisaggService", "DisaggStream",
]

"""Downpour-style distributed training config (pslib surface).

Reference: framework/fleet/fleet_wrapper.h:84-121 (pull/push sparse by
table id), framework/device_worker.h:203 (DownpourWorker's per-table
slot maps), python/paddle/fluid/incubate/fleet/parameter_server/pslib/
optimizer_factory.py (DistributedAdam builds DownpourServer/
DownpourWorker descs from the program) and node.py (DownpourServer
add_sparse_table/add_dense_table).

TPU-native: the descs configure the SAME socket PS runtime (ps/server
applies per-shard update rules; sparse rows ride SelectedRows pushes) —
a table id groups params under one accessor (update rule + lr), the
reference's per-slot accessor config, without protobuf."""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from ..core.framework import Variable


_SPARSE_ACCESSORS = {
    # accessor name -> server-side update rule (ps/server.py _ShardState)
    "DownpourSparseValueAccessor": "sgd",
    "sparse_sgd": "sgd",
    "sparse_adagrad": "adagrad",
    "DownpourCtrAccessor": "adagrad",
}


@dataclasses.dataclass
class TableConfig:
    table_id: int
    type: str  # "sparse" | "dense"
    accessor: str
    learning_rate: float
    param_names: List[str]
    grad_names: List[str]
    slot_key_names: List[str] = dataclasses.field(default_factory=list)
    fea_dim: int = 0


class DownpourServer:
    """Reference pslib/node.py DownpourServer."""

    def __init__(self):
        self.tables: Dict[int, TableConfig] = {}

    def add_sparse_table(self, table_id, learning_rate, slot_key_vars,
                         slot_value_vars, accessor="sparse_adagrad"):
        if accessor not in _SPARSE_ACCESSORS:
            raise ValueError(
                f"unknown sparse accessor {accessor!r}; "
                f"one of {sorted(_SPARSE_ACCESSORS)}"
            )
        self.tables[table_id] = TableConfig(
            table_id=table_id, type="sparse", accessor=accessor,
            learning_rate=float(learning_rate),
            param_names=[v.name if isinstance(v, Variable) else str(v)
                         for v in slot_value_vars],
            grad_names=[],
            slot_key_names=[v.name if isinstance(v, Variable) else str(v)
                            for v in slot_key_vars],
            fea_dim=int(slot_value_vars[0].shape[-1]) if slot_value_vars else 0,
        )

    def add_dense_table(self, table_id, learning_rate, param_vars, grad_vars,
                        accessor="DownpourDenseValueAccessor"):
        self.tables[table_id] = TableConfig(
            table_id=table_id, type="dense", accessor=accessor,
            learning_rate=float(learning_rate),
            param_names=[v.name if isinstance(v, Variable) else str(v)
                         for v in param_vars],
            grad_names=[v.name if isinstance(v, Variable) else str(v)
                        for v in grad_vars],
        )


class DownpourWorker:
    """Reference pslib/node.py DownpourWorker: the trainer-side mirror
    of the server tables (which vars to pull/push per table id)."""

    def __init__(self, window=1):
        self.window = window
        self.tables: Dict[int, TableConfig] = {}

    def add_table(self, cfg: TableConfig):
        self.tables[cfg.table_id] = cfg


class DownpourSGD:
    """Reference pslib/optimizer_factory.py DistributedAdam-style
    factory: walks the program, assigns each is_sparse embedding its
    own sparse table (server-side accessor update) and all dense params
    one dense table, then produces the PS artifacts with per-table
    optimizer specs."""

    def __init__(self, learning_rate=0.001, window=1,
                 sparse_accessor="sparse_adagrad", dense_rule="sgd"):
        self.learning_rate = float(learning_rate)
        self.window = window
        self.sparse_accessor = sparse_accessor
        self.dense_rule = dense_rule
        self.server = DownpourServer()
        self.worker = DownpourWorker(window)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ..optimizer import SGDOptimizer

        # in-program update ops give build_ps_programs its spec source;
        # the server-side rules below override them per table
        inner = SGDOptimizer(self.learning_rate)
        opt_ops, params_grads = inner.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )
        program = loss.block.program
        block = program.global_block()

        sparse_params = []
        for op in block.ops:
            if op.type in ("lookup_table", "lookup_table_v2") and op.attrs.get(
                    "is_sparse"):
                w = block.var(op.inputs["W"][0])
                ids = op.inputs["Ids"][0]
                sparse_params.append((w, ids))
        table_id = 0
        for w, ids in sparse_params:
            self.server.add_sparse_table(
                table_id, self.learning_rate, [block.var(ids)], [w],
                accessor=self.sparse_accessor,
            )
            self.worker.add_table(self.server.tables[table_id])
            table_id += 1
        sparse_names = {w.name for w, _ in sparse_params}
        dense = [(p, g) for p, g in params_grads if p.name not in sparse_names]
        if dense:
            self.server.add_dense_table(
                table_id, self.learning_rate,
                [p for p, _ in dense], [g for _, g in dense],
            )
            self.worker.add_table(self.server.tables[table_id])
        program._downpour_tables = self.server.tables
        return opt_ops, params_grads

    def apply_to_artifacts(self, artifacts):
        """Override the PS artifacts' per-param optimizer specs with
        the table accessors (reference: the server desc, not the
        trainer program, owns sparse update rules)."""
        for cfg in self.server.tables.values():
            rule = (
                _SPARSE_ACCESSORS[cfg.accessor]
                if cfg.type == "sparse" else self.dense_rule
            )
            for pname in cfg.param_names:
                artifacts.optimizer_specs[pname] = {
                    "type": rule, "lr": cfg.learning_rate,
                }
        return artifacts

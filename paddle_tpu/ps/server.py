"""Parameter server: holds param shards, applies updates.

Reference: operators/distributed_ops/listen_and_serv_op.cc (event loop,
RunSyncLoop barrier semantics / RunAsyncLoop per-grad), request
handlers (distributed/request_handler_impl.cc), heartbeat monitor
(distributed/heart_beat_monitor.h:54).

Implementation: a threaded TCP server. Each shard var has an optimizer
closure built from its optimizer op spec (same op lowerings as the
trainer, run via numpy on host — pservers are CPU machines in the
reference too). Sync mode: grads accumulate per barrier round and the
update applies when all trainers reported. A heartbeat monitor flags
trainers silent for > 2x the expected interval (reference behavior).
"""

from __future__ import annotations

import threading
import time
import socketserver
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import protocol as P


class _ShardState:
    def __init__(self, name: str, value: np.ndarray, optimizer_spec: Dict[str, Any]):
        self.name = name
        self.value = value.astype(np.float32)
        self.spec = optimizer_spec
        self.state: Dict[str, np.ndarray] = {}
        # sync rounds: trainer_id -> pending grad (dict keying makes
        # client retries idempotent)
        self.pending: Dict[int, Any] = {}

    def apply(self, grad: np.ndarray):
        kind = self.spec.get("type", "sgd")
        lr = float(self.spec.get("lr", 0.01))
        if kind == "sgd":
            self.value -= lr * grad
        elif kind == "adam":
            beta1 = self.spec.get("beta1", 0.9)
            beta2 = self.spec.get("beta2", 0.999)
            eps = self.spec.get("epsilon", 1e-8)
            m1 = self.state.setdefault("m1", np.zeros_like(self.value))
            m2 = self.state.setdefault("m2", np.zeros_like(self.value))
            b1p = self.state.setdefault("b1p", np.array(beta1, np.float64))
            b2p = self.state.setdefault("b2p", np.array(beta2, np.float64))
            m1[:] = beta1 * m1 + (1 - beta1) * grad
            m2[:] = beta2 * m2 + (1 - beta2) * grad * grad
            lr_t = lr * np.sqrt(1 - b2p) / (1 - b1p)
            self.value -= (lr_t * m1 / (np.sqrt(m2) + eps)).astype(np.float32)
            self.state["b1p"] = b1p * beta1
            self.state["b2p"] = b2p * beta2
        elif kind == "momentum":
            mu = self.spec.get("mu", 0.9)
            v = self.state.setdefault("v", np.zeros_like(self.value))
            v[:] = mu * v + grad
            self.value -= lr * v
        elif kind == "adagrad":
            eps = self.spec.get("epsilon", 1e-6)
            acc = self.state.setdefault("acc", np.zeros_like(self.value))
            acc += grad * grad
            self.value -= lr * grad / (np.sqrt(acc) + eps)
        else:
            raise NotImplementedError(f"pserver optimizer {kind!r}")

    def apply_sparse(self, rows: np.ndarray, grad: np.ndarray):
        """Row-sliced update (reference sparse optimizer kernels,
        operators/optimizers/*_op.cc SelectedRows specializations; adam
        uses lazy_mode semantics — untouched rows' moments stay put)."""
        kind = self.spec.get("type", "sgd")
        lr = float(self.spec.get("lr", 0.01))
        # dedup rows so stateful updates see each row once
        uniq, inv = np.unique(rows, return_inverse=True)
        merged = np.zeros((len(uniq),) + grad.shape[1:], grad.dtype)
        np.add.at(merged, inv, grad)
        if kind == "sgd":
            self.value[uniq] -= lr * merged
        elif kind == "adam":
            beta1 = self.spec.get("beta1", 0.9)
            beta2 = self.spec.get("beta2", 0.999)
            eps = self.spec.get("epsilon", 1e-8)
            m1 = self.state.setdefault("m1", np.zeros_like(self.value))
            m2 = self.state.setdefault("m2", np.zeros_like(self.value))
            b1p = self.state.setdefault("b1p", np.array(beta1, np.float64))
            b2p = self.state.setdefault("b2p", np.array(beta2, np.float64))
            m1[uniq] = beta1 * m1[uniq] + (1 - beta1) * merged
            m2[uniq] = beta2 * m2[uniq] + (1 - beta2) * merged * merged
            lr_t = lr * np.sqrt(1 - b2p) / (1 - b1p)
            self.value[uniq] -= (lr_t * m1[uniq] / (np.sqrt(m2[uniq]) + eps)).astype(
                np.float32
            )
            self.state["b1p"] = b1p * beta1
            self.state["b2p"] = b2p * beta2
        elif kind == "momentum":
            mu = self.spec.get("mu", 0.9)
            v = self.state.setdefault("v", np.zeros_like(self.value))
            v[uniq] = mu * v[uniq] + merged
            self.value[uniq] -= lr * v[uniq]
        elif kind == "adagrad":
            eps = self.spec.get("epsilon", 1e-6)
            acc = self.state.setdefault("acc", np.zeros_like(self.value))
            acc[uniq] += merged * merged
            self.value[uniq] -= lr * merged / (np.sqrt(acc[uniq]) + eps)
        else:
            raise NotImplementedError(f"pserver sparse optimizer {kind!r}")


class ParameterServer:
    def __init__(self, endpoint: str, shards: Dict[str, np.ndarray],
                 optimizer_specs: Dict[str, Dict[str, Any]], trainers: int = 1,
                 sync_mode: bool = True):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self._shards = {
            name: _ShardState(name, val, optimizer_specs.get(name, {"type": "sgd"}))
            for name, val in shards.items()
        }
        self._trainers = trainers
        self._sync = sync_mode
        self._lock = threading.Lock()
        self._barrier_arrived: set = set()
        self._barrier_generation = 0
        self._barrier_cond = threading.Condition(self._lock)
        self._last_seen: Dict[int, float] = {}
        self._shutdown = threading.Event()
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._checkpoint_dir: Optional[str] = None
        # idempotency table for the sync-sensitive verbs (barrier /
        # send_grad / push_sparse): at-least-once retries mean a reply
        # lost AFTER a round completed resends the request into the
        # NEXT round — per-round tid-keying alone can't catch that
        # (the retry would register in, and possibly release, a round
        # the trainer never reached). Clients stamp each such request
        # with a unique seq; completed ok-responses are cached per
        # (trainer_id, seq) and replayed verbatim on a duplicate.
        self._idem: Dict[Tuple[int, int], Dict[str, Any]] = {}
        self._idem_order: deque = deque()
        # own lock: _idem_put is called while holding _lock (the
        # barrier releases under _barrier_cond, which wraps _lock)
        self._idem_lock = threading.Lock()

    def _idem_get(self, msg):
        if "seq" not in msg:
            return None, None
        key = (int(msg.get("trainer_id", 0)), int(msg["seq"]))
        with self._idem_lock:
            return key, self._idem.get(key)

    def _idem_put(self, key, resp):
        # only successful responses are replayable; an error (e.g.
        # barrier timeout) must stay retryable
        if key is not None and resp.get("ok"):
            with self._idem_lock:
                if key not in self._idem:
                    self._idem[key] = resp
                    self._idem_order.append(key)
                    while len(self._idem_order) > 4096:
                        self._idem.pop(self._idem_order.popleft(), None)
        return resp

    # -- request handling -----------------------------------------------------
    def _handle(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        verb = msg["verb"]
        idem_key, cached = self._idem_get(msg)
        if cached is not None:
            return cached
        if verb == P.GET_PARAM:
            with self._lock:
                sh = self._shards[msg["name"]]
                # copy under the lock: serialization happens after the
                # lock is released and must not race in-place updates
                return {"ok": True, "value": sh.value.copy()}
        if verb == P.SEND_GRAD:
            tid = int(msg.get("trainer_id", 0))
            self._last_seen[tid] = time.time()
            name = msg["name"]
            grad = msg["grad"]
            with self._lock:
                sh = self._shards[name]
                if self._sync:
                    # keyed by trainer_id, not arrival-counted: a
                    # client RETRY (ps/protocol.py request backoff)
                    # replaces the same trainer's entry instead of
                    # double-counting it
                    sh.pending[tid] = grad
                    if len(sh.pending) >= self._trainers:
                        mean_grad = np.mean(list(sh.pending.values()),
                                            axis=0)
                        sh.apply(mean_grad)
                        sh.pending.clear()
                else:
                    sh.apply(grad)
            return self._idem_put(idem_key, {"ok": True})
        if verb == P.PREFETCH:
            # sparse row lookup (reference parameter_prefetch.cc)
            with self._lock:
                sh = self._shards[msg["name"]]
                rows = msg["rows"].astype(np.int64)
                return {"ok": True, "value": sh.value[rows]}
        if verb == P.PUSH_SPARSE:
            tid = int(msg.get("trainer_id", 0))
            self._last_seen[tid] = time.time()
            with self._lock:
                sh = self._shards[msg["name"]]
                rows = msg["rows"].astype(np.int64)
                grad = msg["grad"]
                if self._sync and self._trainers > 1:
                    # per-trainer (rows, grad) for the round, keyed by
                    # trainer_id so a client retry replaces rather than
                    # double-counts; apply once all trainers reported
                    # (mean semantics, matching the dense sync path)
                    sh.pending[tid] = (rows, grad / self._trainers)
                    if len(sh.pending) >= self._trainers:
                        all_rows = np.concatenate(
                            [r for r, _ in sh.pending.values()])
                        all_grads = np.concatenate(
                            [g for _, g in sh.pending.values()])
                        sh.apply_sparse(all_rows, all_grads)
                        sh.pending.clear()
                else:
                    sh.apply_sparse(rows, grad)
            return self._idem_put(idem_key, {"ok": True})
        if verb == P.BARRIER:
            tid = int(msg.get("trainer_id", 0))
            deadline = time.time() + 300.0
            with self._barrier_cond:
                # arrivals tracked per trainer_id: a retried barrier
                # request from the same trainer must not release the
                # round early (ps/protocol.py request backoff)
                self._barrier_arrived.add(tid)
                my_gen = self._barrier_generation
                if len(self._barrier_arrived) >= self._trainers:
                    self._barrier_arrived.clear()
                    self._barrier_generation += 1
                    self._barrier_cond.notify_all()
                    return self._idem_put(idem_key, {"ok": True})
                # wait on a generation predicate: spurious wakeups and
                # timeouts must not release the barrier early
                while self._barrier_generation == my_gen:
                    remaining = deadline - time.time()
                    if remaining <= 0:
                        return {"ok": False, "error": "barrier timeout"}
                    self._barrier_cond.wait(timeout=remaining)
            return self._idem_put(idem_key, {"ok": True})
        if verb == P.CHECKPOINT:
            self.save(msg["dirname"])
            return {"ok": True}
        if verb == P.SHUTDOWN:
            self._shutdown.set()

            def _stop(server=self._server):
                server.shutdown()
                server.server_close()  # release the LISTEN socket — a
                # leaked listener makes later binds EADDRINUSE and
                # clients hang against the dead port

            threading.Thread(target=_stop, daemon=True).start()
            return {"ok": True}
        return {"ok": False, "error": f"unknown verb {verb}"}

    # -- checkpoint (reference checkpoint_notify_op.cc:28) --------------------
    def save(self, dirname: str):
        import os

        os.makedirs(dirname, exist_ok=True)
        with self._lock:
            np.savez(
                os.path.join(dirname, f"pserver_{self._addr[1]}.npz"),
                **{n: s.value for n, s in self._shards.items()},
            )

    # -- lifecycle ------------------------------------------------------------
    def serve_forever(self):
        ps = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    msg = P.recv_msg(self.request)
                    resp = ps._handle(msg)
                    P.send_msg(self.request, resp)
                except (ConnectionError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server(self._addr, Handler)
        self._monitor = threading.Thread(target=self._heartbeat_loop, daemon=True)
        self._monitor.start()
        self._server.serve_forever()

    def start_background(self):
        t = threading.Thread(target=self.serve_forever, daemon=True)
        t.start()
        time.sleep(0.1)
        return t

    def _heartbeat_loop(self, interval: float = 10.0):
        # reference HeartBeatMonitor::LostWorkerMonitor (.cc:57): warn on
        # workers silent > 2x interval
        while not self._shutdown.wait(interval):
            now = time.time()
            for tid, ts in list(self._last_seen.items()):
                if now - ts > 2 * interval:
                    print(f"[pserver {self._addr}] trainer {tid} silent "
                          f"{now - ts:.0f}s (possible failure)")


def run_pserver(endpoint, shards, optimizer_specs, trainers=1, sync_mode=True):
    ps = ParameterServer(endpoint, shards, optimizer_specs, trainers, sync_mode)
    ps.serve_forever()
    return ps

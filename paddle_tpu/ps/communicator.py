"""Async communicator: background grad-send / param-recv threads.

Reference: operators/distributed/communicator.h:176 (AsyncCommunicator
:237 — per-var send queues merged by batch, independent recv thread),
HalfAsync :299, Sync :365, GeoCommunicator :383 (delta sync every K
steps). python wrapper fluid/communicator.py.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Optional

import numpy as np


class Communicator:
    def __init__(self, artifacts, scope, mode: str = "async",
                 send_queue_size: int = 20, merge_batch: int = 4,
                 geo_need_push_nums: int = 100):
        from .client import PSClient

        self.art = artifacts
        self.scope = scope
        self.mode = mode  # async | half_async | sync | geo
        self.client = PSClient(artifacts.endpoints)
        self._queues: Dict[str, "queue.Queue"] = {
            g: queue.Queue(maxsize=send_queue_size) for g in artifacts.grad_to_param
        }
        self._merge_batch = merge_batch
        self._running = False
        self._threads = []
        # per-var counters (reference GeoSgdCommunicator keeps per-var
        # push queues; a shared counter would starve some params)
        self._geo_counters: Dict[str, int] = {}
        self._geo_push_nums = geo_need_push_nums
        self._geo_anchor: Dict[str, np.ndarray] = {}

    # -- reference API: start/stop/send ---------------------------------------
    def start(self):
        self._running = True
        for gname in self._queues:
            t = threading.Thread(target=self._send_loop, args=(gname,), daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._recv_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self):
        self._running = False

    def send(self, grad_name: str, value: np.ndarray):
        if self.mode == "geo":
            self._geo_send(grad_name)
            return
        try:
            self._queues[grad_name].put_nowait(np.asarray(value))
        except queue.Full:
            pass  # async mode drops when saturated (backpressure)

    # -- internals ------------------------------------------------------------
    def _send_loop(self, gname: str):
        pname = self.art.grad_to_param[gname]
        q = self._queues[gname]
        while self._running:
            try:
                first = q.get(timeout=0.2)
            except queue.Empty:
                continue
            merged = [first]
            while len(merged) < self._merge_batch:
                try:
                    merged.append(q.get_nowait())
                except queue.Empty:
                    break
            grad = np.mean(merged, axis=0) if len(merged) > 1 else merged[0]
            self.client.send_grad(self.art.shard_map, pname, grad)

    def _recv_loop(self, interval: float = 0.2):
        import jax.numpy as jnp

        while self._running:
            for pname in self.art.shard_map:
                try:
                    fresh = self.client.get_param(self.art.shard_map, pname)
                    self.scope.set_var(pname, jnp.asarray(fresh))
                except ConnectionError:
                    pass
            time.sleep(interval)

    def _geo_send(self, gname: str):
        """Geo-SGD: every K local steps push the param DELTA since the
        last sync (reference GeoSgdCommunicator)."""
        import jax.numpy as jnp

        pname = self.art.grad_to_param[gname]
        cnt = self._geo_counters.get(gname, 0) + 1
        self._geo_counters[gname] = cnt
        if cnt % self._geo_push_nums:
            return
        cur = np.asarray(self.scope.find_var(pname))
        anchor = self._geo_anchor.get(pname)
        if anchor is not None:
            delta = anchor - cur  # pserver applies p -= lr*grad; lr=1 delta
            self.client.send_grad(self.art.shard_map, pname, delta)
            fresh = self.client.get_param(self.art.shard_map, pname)
            self.scope.set_var(pname, jnp.asarray(fresh))
            cur = fresh
        self._geo_anchor[pname] = np.array(cur, copy=True)

"""PS client: trainer-side send/recv.

Reference: operators/distributed/ RPCClient (grpc_client.cc async
completion queue), parameter_send.cc / parameter_recv.cc (split a
param's slices across endpoints and scatter/gather them).
"""

from __future__ import annotations

import concurrent.futures
import itertools
from typing import Dict, List, Sequence, Tuple

import numpy as np

from . import protocol as P


def _addr(endpoint: str) -> Tuple[str, int]:
    h, p = endpoint.rsplit(":", 1)
    return (h, int(p))


class PSClient:
    def __init__(self, endpoints: Sequence[str], trainer_id: int = 0):
        self.endpoints = list(endpoints)
        self.trainer_id = trainer_id
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(4, len(self.endpoints))
        )
        # unique per sync-sensitive REQUEST (not per step): the server
        # replays its cached response when a lost-reply retry resends a
        # (trainer_id, seq) it already completed — without this, a
        # retry landing after its barrier/grad round released would
        # register into the NEXT round and break the sync fence.
        # Seeded with time_ns so a RESTARTED trainer's fresh requests
        # can never collide with its previous incarnation's entries in
        # the server's TTL-less cache. itertools.count: atomic under
        # CPython, shared by pool threads
        import time

        self._seq = itertools.count(time.time_ns())

    # shard_map: var name -> list of (endpoint, row_begin, row_end)
    def send_grad(self, shard_map, name: str, grad: np.ndarray):
        futs = []
        for ep, lo, hi in shard_map[name]:
            piece = grad[lo:hi]
            futs.append(
                self._pool.submit(
                    P.request,
                    _addr(ep),
                    {"verb": P.SEND_GRAD, "name": f"{name}@{lo}",
                     "grad": piece, "trainer_id": self.trainer_id,
                     "seq": next(self._seq)},
                )
            )
        for f in futs:
            resp = f.result()
            assert resp.get("ok"), resp

    def get_param(self, shard_map, name: str) -> np.ndarray:
        futs = [
            self._pool.submit(
                P.request, _addr(ep), {"verb": P.GET_PARAM, "name": f"{name}@{lo}"}
            )
            for ep, lo, hi in shard_map[name]
        ]
        pieces = [f.result()["value"] for f in futs]
        return np.concatenate(pieces, axis=0) if len(pieces) > 1 else pieces[0]

    def prefetch_rows(self, shard_map, name: str, rows: np.ndarray) -> np.ndarray:
        """Sparse row fetch for distributed embedding lookup (reference
        parameter_prefetch.cc + distributed_lookup_table_op)."""
        segs = shard_map[name]
        out = None
        for ep, lo, hi in segs:
            mask = (rows >= lo) & (rows < hi)
            if not mask.any():
                continue
            local = rows[mask] - lo
            resp = P.request(
                _addr(ep), {"verb": P.PREFETCH, "name": f"{name}@{lo}", "rows": local}
            )
            vals = resp["value"]
            if out is None:
                out = np.zeros((rows.shape[0], vals.shape[1]), vals.dtype)
            out[mask] = vals
        return out

    def push_sparse(self, shard_map, name: str, rows: np.ndarray, grad: np.ndarray):
        for ep, lo, hi in shard_map[name]:
            mask = (rows >= lo) & (rows < hi)
            if not mask.any():
                continue
            P.request(
                _addr(ep),
                {"verb": P.PUSH_SPARSE, "name": f"{name}@{lo}",
                 "rows": rows[mask] - lo, "grad": grad[mask],
                 "trainer_id": self.trainer_id, "seq": next(self._seq)},
            )

    def barrier(self):
        for ep in self.endpoints:
            resp = P.request(_addr(ep), {"verb": P.BARRIER, "trainer_id": self.trainer_id,
                                        "seq": next(self._seq)})
            if not resp.get("ok"):
                raise RuntimeError(f"barrier failed at {ep}: {resp.get('error')}")

    def checkpoint_notify(self, dirname: str):
        for ep in self.endpoints:
            P.request(_addr(ep), {"verb": P.CHECKPOINT, "dirname": dirname})

    def shutdown_servers(self):
        for ep in self.endpoints:
            try:
                # no retry: an already-gone server IS a shutdown
                P.request(_addr(ep), {"verb": P.SHUTDOWN}, retries=0)
            except ConnectionError:
                pass

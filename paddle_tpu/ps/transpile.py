"""Program splitting for PS mode.

Reference: transpiler/distribute_transpiler.py:540 — slice_var_up
splits params into blocks round-robin across pservers; the trainer
program gets send/recv around its grads; each pserver program holds the
optimizer sub-blocks for its shard.

TPU-native shape: the trainer keeps ONE compiled XLA step that
computes gradients (optimizer ops stripped); a PSTrainer wrapper ships
grads to the servers and writes refreshed params into the scope. The
"pserver program" here is the (shards, optimizer_specs) pair consumed
by ps.server — host numpy update loops, like the reference's CPU
pserver blocks.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import numpy as np

from ..core.framework import OpRole, Program


_OPT_OPS = {
    "sgd", "momentum", "adam", "adamw", "adagrad", "adamax", "adadelta",
    "rmsprop", "ftrl", "lamb", "lars_momentum", "decayed_adagrad", "dpsgd",
}


@dataclasses.dataclass
class PSArtifacts:
    trainer_program: Program
    grad_to_param: Dict[str, str]
    shard_map: Dict[str, List[Tuple[str, int, int]]]  # param -> [(ep, lo, hi)]
    optimizer_specs: Dict[str, Dict]
    endpoints: List[str]
    sync_mode: bool
    trainers: int
    # sparse embedding params (is_sparse lookup_table): param -> ids
    # feed-var name; their grads travel as SelectedRows row pushes and
    # only touched rows are prefetched (reference
    # distributed_lookup_table_op + parameter_prefetch.cc)
    sparse_params: Dict[str, str] = dataclasses.field(default_factory=dict)
    # pserver_* kept for reference API parity (get_pserver_program)
    pserver_programs: Dict[str, Dict] = dataclasses.field(default_factory=dict)
    pserver_startups: Dict[str, Dict] = dataclasses.field(default_factory=dict)


def _slice_rows(n_rows: int, n_shards: int, min_rows: int = 1):
    """Split [0, n_rows) into <= n_shards contiguous row ranges."""
    n_shards = max(1, min(n_shards, max(n_rows // max(min_rows, 1), 1)))
    per = (n_rows + n_shards - 1) // n_shards
    out = []
    lo = 0
    while lo < n_rows:
        hi = min(lo + per, n_rows)
        out.append((lo, hi))
        lo = hi
    return out


def build_ps_programs(
    main: Program,
    startup: Program,
    endpoints: List[str],
    trainer_id: int,
    trainers: int,
    sync_mode: bool,
    slice_var_up: bool = True,
    min_block_size: int = 8192,
):
    # 1) strip optimizer ops from a trainer copy; collect specs
    trainer = Program.from_dict(main.to_dict())
    block = trainer.global_block()
    kept = []
    grad_to_param: Dict[str, str] = {}
    optimizer_specs: Dict[str, Dict] = {}
    for op in block.ops:
        if op.type in _OPT_OPS:
            pname = op.inputs["Param"][0]
            gname = op.inputs["Grad"][0]
            grad_to_param[gname] = pname
            spec = {"type": op.type, "lr": 0.01}
            lr_inputs = op.inputs.get("LearningRate", [])
            if lr_inputs:
                spec["lr_var"] = lr_inputs[0]  # resolved from scope at launch
            spec.update({k: v for k, v in op.attrs.items()
                         if k in ("beta1", "beta2", "epsilon", "mu", "use_nesterov")})
            optimizer_specs[pname] = spec
            continue
        kept.append(op)
    block.ops = kept
    trainer._bump()

    # 1b) record sparse embedding params: is_sparse lookup_tables whose
    # ids come STRAIGHT from a feed var — only then can the trainer
    # prefetch the batch's rows before the step. Ids that are computed
    # mid-program fall back to the dense param pull (still correct,
    # just not row-sliced).
    sparse_params: Dict[str, str] = {}
    for op in kept:
        if op.type in ("lookup_table", "lookup_table_v2") and op.attrs.get("is_sparse"):
            pname = op.inputs["W"][0]
            ids_name = op.inputs["Ids"][0]
            ids_var = block.var(ids_name) if block.has_var(ids_name) else None
            if pname in grad_to_param.values() and ids_var is not None and ids_var.is_data:
                sparse_params[pname] = ids_name

    # 2) shard params across endpoints by rows (reference slice_var_up)
    shard_map: Dict[str, List[Tuple[str, int, int]]] = {}
    params = sorted(grad_to_param.values())
    for i, pname in enumerate(params):
        var = main.global_block().var(pname)
        n_rows = int(var.shape[0]) if var.shape else 1
        if slice_var_up and len(endpoints) > 1:
            ranges = _slice_rows(n_rows, len(endpoints))
        else:
            ranges = [(0, n_rows)]
        segs = []
        for j, (lo, hi) in enumerate(ranges):
            ep = endpoints[(i + j) % len(endpoints)]
            segs.append((ep, lo, hi))
        shard_map[pname] = segs

    # 3) per-endpoint shard tables (the "pserver program")
    pserver_programs: Dict[str, Dict] = {ep: {} for ep in endpoints}
    for pname, segs in shard_map.items():
        for ep, lo, hi in segs:
            pserver_programs[ep][f"{pname}@{lo}"] = (pname, lo, hi)

    return PSArtifacts(
        trainer_program=trainer,
        grad_to_param=grad_to_param,
        shard_map=shard_map,
        optimizer_specs=optimizer_specs,
        endpoints=list(endpoints),
        sync_mode=sync_mode,
        trainers=trainers,
        pserver_programs=pserver_programs,
        pserver_startups={ep: {} for ep in endpoints},
        sparse_params=sparse_params,
    )


def launch_pservers(artifacts: PSArtifacts, scope) -> List:
    """Start the pservers for this artifact set in background threads
    (tests / single-host); real deployments run ps.server per node."""
    from .server import ParameterServer

    servers = []
    for ep in artifacts.endpoints:
        shards = {}
        specs = {}
        for shard_name, (pname, lo, hi) in artifacts.pserver_programs[ep].items():
            val = scope.find_var(pname)
            assert val is not None, f"run startup before launching pservers ({pname})"
            shards[shard_name] = np.asarray(val)[lo:hi].copy()
            spec = dict(artifacts.optimizer_specs.get(pname, {"type": "sgd"}))
            lr_var = spec.pop("lr_var", None)
            if lr_var is not None:
                lr_val = scope.find_var(lr_var)
                if lr_val is not None:
                    spec["lr"] = float(np.asarray(lr_val).reshape(-1)[0])
            specs[shard_name] = spec
        ps = ParameterServer(ep, shards, specs, artifacts.trainers, artifacts.sync_mode)
        ps.start_background()
        servers.append(ps)
    return servers


class PSTrainer:
    """Trainer-side driver: run the compiled grad step, send grads,
    pull fresh params (reference Communicator sync path +
    send_op/recv_op insertion)."""

    def __init__(self, artifacts: PSArtifacts, executor, scope, trainer_id: int = 0):
        from .client import PSClient

        self.art = artifacts
        self.exe = executor
        self.scope = scope
        self.client = PSClient(artifacts.endpoints, trainer_id)

    def _refresh_sparse_rows(self, feed):
        """Prefetch only the embedding rows this batch will touch
        (reference parameter_prefetch.cc): comm volume scales with the
        batch, not the vocab."""
        import jax.numpy as jnp

        for pname, ids_name in self.art.sparse_params.items():
            if ids_name not in feed:
                continue
            rows = np.unique(np.asarray(feed[ids_name]).reshape(-1)).astype(np.int64)
            fresh = self.client.prefetch_rows(self.art.shard_map, pname, rows)
            if fresh is None:
                continue
            cur = self.scope.find_var(pname)
            # row-sliced device update — no vocab-sized host round-trip
            self.scope.set_var(
                pname,
                jnp.asarray(cur).at[jnp.asarray(rows)].set(jnp.asarray(fresh)),
            )

    def run_step(self, feed, fetch_list):
        import jax.numpy as jnp

        from ..core.selected_rows import SelectedRows

        self._refresh_sparse_rows(feed)
        grads = [g for g in self.art.grad_to_param]
        outs = self.exe.run(
            self.art.trainer_program,
            feed=feed,
            fetch_list=list(fetch_list) + grads,
            scope=self.scope,
        )
        n = len(fetch_list)
        fetched, grad_vals = outs[:n], outs[n:]
        for gname, gval in zip(grads, grad_vals):
            pname = self.art.grad_to_param[gname]
            if isinstance(gval, SelectedRows):
                # dedup host-side so the wire carries each row once
                rows = np.asarray(gval.rows)
                vals = np.asarray(gval.values)
                uniq, inv = np.unique(rows, return_inverse=True)
                merged = np.zeros((len(uniq),) + vals.shape[1:], vals.dtype)
                np.add.at(merged, inv, vals)
                self.client.push_sparse(self.art.shard_map, pname, uniq, merged)
            else:
                self.client.send_grad(self.art.shard_map, pname, np.asarray(gval))
        if self.art.sync_mode and self.art.trainers > 1:
            # all trainers' grads must land before the update is visible
            self.client.barrier()
        for pname in self.art.shard_map:
            if pname in self.art.sparse_params:
                continue  # refreshed rows-only at the top of each step
            fresh = self.client.get_param(self.art.shard_map, pname)
            self.scope.set_var(pname, jnp.asarray(fresh))
        return fetched

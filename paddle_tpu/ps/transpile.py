"""Program splitting for PS mode.

Reference: transpiler/distribute_transpiler.py:540 — slice_var_up
splits params into blocks round-robin across pservers; the trainer
program gets send/recv around its grads; each pserver program holds the
optimizer sub-blocks for its shard.

TPU-native shape: the trainer keeps ONE compiled XLA step that
computes gradients (optimizer ops stripped); a PSTrainer wrapper ships
grads to the servers and writes refreshed params into the scope. The
"pserver program" here is the (shards, optimizer_specs) pair consumed
by ps.server — host numpy update loops, like the reference's CPU
pserver blocks.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import numpy as np

from ..core.framework import OpRole, Program


_OPT_OPS = {
    "sgd", "momentum", "adam", "adamw", "adagrad", "adamax", "adadelta",
    "rmsprop", "ftrl", "lamb", "lars_momentum", "decayed_adagrad", "dpsgd",
}


@dataclasses.dataclass
class PSArtifacts:
    trainer_program: Program
    grad_to_param: Dict[str, str]
    shard_map: Dict[str, List[Tuple[str, int, int]]]  # param -> [(ep, lo, hi)]
    optimizer_specs: Dict[str, Dict]
    endpoints: List[str]
    sync_mode: bool
    trainers: int
    # pserver_* kept for reference API parity (get_pserver_program)
    pserver_programs: Dict[str, Dict] = dataclasses.field(default_factory=dict)
    pserver_startups: Dict[str, Dict] = dataclasses.field(default_factory=dict)


def _slice_rows(n_rows: int, n_shards: int, min_rows: int = 1):
    """Split [0, n_rows) into <= n_shards contiguous row ranges."""
    n_shards = max(1, min(n_shards, max(n_rows // max(min_rows, 1), 1)))
    per = (n_rows + n_shards - 1) // n_shards
    out = []
    lo = 0
    while lo < n_rows:
        hi = min(lo + per, n_rows)
        out.append((lo, hi))
        lo = hi
    return out


def build_ps_programs(
    main: Program,
    startup: Program,
    endpoints: List[str],
    trainer_id: int,
    trainers: int,
    sync_mode: bool,
    slice_var_up: bool = True,
    min_block_size: int = 8192,
):
    # 1) strip optimizer ops from a trainer copy; collect specs
    trainer = Program.from_dict(main.to_dict())
    block = trainer.global_block()
    kept = []
    grad_to_param: Dict[str, str] = {}
    optimizer_specs: Dict[str, Dict] = {}
    for op in block.ops:
        if op.type in _OPT_OPS:
            pname = op.inputs["Param"][0]
            gname = op.inputs["Grad"][0]
            grad_to_param[gname] = pname
            spec = {"type": op.type, "lr": 0.01}
            lr_inputs = op.inputs.get("LearningRate", [])
            if lr_inputs:
                spec["lr_var"] = lr_inputs[0]  # resolved from scope at launch
            spec.update({k: v for k, v in op.attrs.items()
                         if k in ("beta1", "beta2", "epsilon", "mu", "use_nesterov")})
            optimizer_specs[pname] = spec
            continue
        kept.append(op)
    block.ops = kept
    trainer._bump()

    # 2) shard params across endpoints by rows (reference slice_var_up)
    shard_map: Dict[str, List[Tuple[str, int, int]]] = {}
    params = sorted(grad_to_param.values())
    for i, pname in enumerate(params):
        var = main.global_block().var(pname)
        n_rows = int(var.shape[0]) if var.shape else 1
        if slice_var_up and len(endpoints) > 1:
            ranges = _slice_rows(n_rows, len(endpoints))
        else:
            ranges = [(0, n_rows)]
        segs = []
        for j, (lo, hi) in enumerate(ranges):
            ep = endpoints[(i + j) % len(endpoints)]
            segs.append((ep, lo, hi))
        shard_map[pname] = segs

    # 3) per-endpoint shard tables (the "pserver program")
    pserver_programs: Dict[str, Dict] = {ep: {} for ep in endpoints}
    for pname, segs in shard_map.items():
        for ep, lo, hi in segs:
            pserver_programs[ep][f"{pname}@{lo}"] = (pname, lo, hi)

    return PSArtifacts(
        trainer_program=trainer,
        grad_to_param=grad_to_param,
        shard_map=shard_map,
        optimizer_specs=optimizer_specs,
        endpoints=list(endpoints),
        sync_mode=sync_mode,
        trainers=trainers,
        pserver_programs=pserver_programs,
        pserver_startups={ep: {} for ep in endpoints},
    )


def launch_pservers(artifacts: PSArtifacts, scope) -> List:
    """Start the pservers for this artifact set in background threads
    (tests / single-host); real deployments run ps.server per node."""
    from .server import ParameterServer

    servers = []
    for ep in artifacts.endpoints:
        shards = {}
        specs = {}
        for shard_name, (pname, lo, hi) in artifacts.pserver_programs[ep].items():
            val = scope.find_var(pname)
            assert val is not None, f"run startup before launching pservers ({pname})"
            shards[shard_name] = np.asarray(val)[lo:hi].copy()
            spec = dict(artifacts.optimizer_specs.get(pname, {"type": "sgd"}))
            lr_var = spec.pop("lr_var", None)
            if lr_var is not None:
                lr_val = scope.find_var(lr_var)
                if lr_val is not None:
                    spec["lr"] = float(np.asarray(lr_val).reshape(-1)[0])
            specs[shard_name] = spec
        ps = ParameterServer(ep, shards, specs, artifacts.trainers, artifacts.sync_mode)
        ps.start_background()
        servers.append(ps)
    return servers


class PSTrainer:
    """Trainer-side driver: run the compiled grad step, send grads,
    pull fresh params (reference Communicator sync path +
    send_op/recv_op insertion)."""

    def __init__(self, artifacts: PSArtifacts, executor, scope, trainer_id: int = 0):
        from .client import PSClient

        self.art = artifacts
        self.exe = executor
        self.scope = scope
        self.client = PSClient(artifacts.endpoints, trainer_id)

    def run_step(self, feed, fetch_list):
        import jax.numpy as jnp

        grads = [g for g in self.art.grad_to_param]
        outs = self.exe.run(
            self.art.trainer_program,
            feed=feed,
            fetch_list=list(fetch_list) + grads,
            scope=self.scope,
        )
        n = len(fetch_list)
        fetched, grad_vals = outs[:n], outs[n:]
        for gname, gval in zip(grads, grad_vals):
            self.client.send_grad(self.art.shard_map, self.art.grad_to_param[gname],
                                  np.asarray(gval))
        if self.art.sync_mode and self.art.trainers > 1:
            # all trainers' grads must land before the update is visible
            self.client.barrier()
        for pname in self.art.shard_map:
            fresh = self.client.get_param(self.art.shard_map, pname)
            self.scope.set_var(pname, jnp.asarray(fresh))
        return fetched

"""Host parameter-server runtime.

Reference: operators/distributed/ (gRPC/BRPC RPC stack, Communicator
send/recv threads communicator.h:176-383), distributed_ops/
listen_and_serv_op.cc (pserver event loop), transpiler param slicing
(distribute_transpiler.py slice_var_up).

TPU-native role: dense params live on-device (sharded by GSPMD) — the
PS path exists for host-RAM-resident giant embedding tables and
CTR-style async training over DCN. Implementation is a compact
length-prefixed-msgpack-over-TCP protocol (no gRPC dependency) with
the same verbs as the reference's send_recv.proto:19-34
(SendVariable / GetVariable / Barrier / CheckpointNotify).
"""

from .server import ParameterServer, run_pserver
from .client import PSClient
from .transpile import build_ps_programs, PSArtifacts
from .communicator import Communicator

"""Wire protocol: length-prefixed pickled messages over TCP.

Verbs mirror reference send_recv.proto:19-34 (SendVariable,
GetVariable, Prefetch, Barrier, CheckpointNotify) plus Shutdown.
numpy arrays are sent raw (dtype/shape header + buffer) to avoid
pickle overhead on tensors.
"""

from __future__ import annotations

import pickle
import socket
import struct
from typing import Any, Dict, Optional, Tuple

import numpy as np

_HDR = struct.Struct("!Q")

# verbs
SEND_GRAD = "send_grad"
GET_PARAM = "get_param"
BARRIER = "barrier"
CHECKPOINT = "checkpoint"
SHUTDOWN = "shutdown"
PREFETCH = "prefetch"  # sparse row lookup
PUSH_SPARSE = "push_sparse"


def _encode(msg: Dict[str, Any]) -> bytes:
    arrays = {}
    clean = {}
    for k, v in msg.items():
        if isinstance(v, np.ndarray):
            arrays[k] = v
        else:
            clean[k] = v
    header = pickle.dumps(
        {
            "msg": clean,
            "arrays": {
                k: (str(a.dtype), a.shape) for k, a in arrays.items()
            },
        },
        protocol=4,
    )
    parts = [_HDR.pack(len(header)), header]
    for k in sorted(arrays):
        buf = np.ascontiguousarray(arrays[k]).tobytes()
        parts.append(_HDR.pack(len(buf)))
        parts.append(buf)
    return b"".join(parts)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("peer closed")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _decode(sock: socket.socket) -> Dict[str, Any]:
    (hlen,) = _HDR.unpack(_read_exact(sock, _HDR.size))
    meta = pickle.loads(_read_exact(sock, hlen))
    msg = dict(meta["msg"])
    for k in sorted(meta["arrays"]):
        dtype, shape = meta["arrays"][k]
        (blen,) = _HDR.unpack(_read_exact(sock, _HDR.size))
        buf = _read_exact(sock, blen)
        msg[k] = np.frombuffer(buf, dtype=dtype).reshape(shape).copy()
    return msg


def send_msg(sock: socket.socket, msg: Dict[str, Any]):
    sock.sendall(_encode(msg))


def recv_msg(sock: socket.socket) -> Dict[str, Any]:
    return _decode(sock)


def request(addr: Tuple[str, int], msg: Dict[str, Any]) -> Dict[str, Any]:
    with socket.create_connection(addr, timeout=60) as s:
        send_msg(s, msg)
        return recv_msg(s)

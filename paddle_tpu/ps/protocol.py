"""Wire protocol: length-prefixed pickled messages over TCP.

Verbs mirror reference send_recv.proto:19-34 (SendVariable,
GetVariable, Prefetch, Barrier, CheckpointNotify) plus Shutdown.
numpy arrays are sent raw (dtype/shape header + buffer) to avoid
pickle overhead on tensors.
"""

from __future__ import annotations

import pickle
import socket
import struct
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

_HDR = struct.Struct("!Q")

# verbs
SEND_GRAD = "send_grad"
GET_PARAM = "get_param"
BARRIER = "barrier"
CHECKPOINT = "checkpoint"
SHUTDOWN = "shutdown"
PREFETCH = "prefetch"  # sparse row lookup
PUSH_SPARSE = "push_sparse"


def _encode(msg: Dict[str, Any]) -> bytes:
    arrays = {}
    clean = {}
    for k, v in msg.items():
        if isinstance(v, np.ndarray):
            arrays[k] = v
        else:
            clean[k] = v
    header = pickle.dumps(
        {
            "msg": clean,
            "arrays": {
                k: (str(a.dtype), a.shape) for k, a in arrays.items()
            },
        },
        protocol=4,
    )
    parts = [_HDR.pack(len(header)), header]
    for k in sorted(arrays):
        buf = np.ascontiguousarray(arrays[k]).tobytes()
        parts.append(_HDR.pack(len(buf)))
        parts.append(buf)
    return b"".join(parts)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("peer closed")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _decode(sock: socket.socket) -> Dict[str, Any]:
    (hlen,) = _HDR.unpack(_read_exact(sock, _HDR.size))
    meta = pickle.loads(_read_exact(sock, hlen))
    msg = dict(meta["msg"])
    for k in sorted(meta["arrays"]):
        dtype, shape = meta["arrays"][k]
        (blen,) = _HDR.unpack(_read_exact(sock, _HDR.size))
        buf = _read_exact(sock, blen)
        msg[k] = np.frombuffer(buf, dtype=dtype).reshape(shape).copy()
    return msg


def send_msg(sock: socket.socket, msg: Dict[str, Any]):
    sock.sendall(_encode(msg))


def recv_msg(sock: socket.socket) -> Dict[str, Any]:
    return _decode(sock)


def request(addr: Tuple[str, int], msg: Dict[str, Any], retries: int = 5,
            backoff: float = 0.2, timeout: float = 60.0) -> Dict[str, Any]:
    """One request/response with bounded reconnect-and-backoff
    (round-3 verdict weak #7; reference grpc_client.cc retries through
    its completion queue). Connection-per-request makes a retry a
    clean resend; like the reference this is at-least-once — a reply
    lost AFTER the server applied a send_grad re-applies it, the same
    async-SGD noise the PS design already tolerates."""
    last: Optional[Exception] = None
    for attempt in range(retries + 1):
        try:
            with socket.create_connection(addr, timeout=timeout) as s:
                send_msg(s, msg)
                return recv_msg(s)
        except (ConnectionError, socket.timeout, OSError) as e:
            last = e
            if attempt < retries:
                time.sleep(backoff * (2 ** attempt))
    raise ConnectionError(
        f"PS request to {addr} failed after {retries + 1} attempts: {last!r}")

"""Weight-decay regularizers. Reference:
python/paddle/fluid/regularizer.py — append_regularization_ops adds
grad += coeff * penalty'(param) ops before the optimizer update."""

from __future__ import annotations

from .core.framework import OpRole


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = float(regularization_coeff)

    def __call__(self, param, grad, block):
        # decay = coeff * param ; grad = grad + decay
        from .core.framework import unique_name

        decay = block.create_var(
            name=unique_name.generate(f"{param.name}.l2decay"), stop_gradient=True
        )
        block.append_op(
            type="scale",
            inputs={"X": [param]},
            outputs={"Out": [decay]},
            attrs={"scale": self._coeff, "op_role": OpRole.Backward},
        )
        new_grad = block.create_var(
            name=unique_name.generate(f"{param.name}.grad_reg"), stop_gradient=True
        )
        block.append_op(
            type="sum",
            inputs={"X": [grad, decay]},
            outputs={"Out": [new_grad]},
            attrs={"op_role": OpRole.Backward},
        )
        return new_grad


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = float(regularization_coeff)

    def __call__(self, param, grad, block):
        from .core.framework import unique_name

        sign = block.create_var(
            name=unique_name.generate(f"{param.name}.sign"), stop_gradient=True
        )
        # sign(x) = x / (|x| + eps) avoids adding a dedicated sign op
        absx = block.create_var(
            name=unique_name.generate(f"{param.name}.abs"), stop_gradient=True
        )
        block.append_op(
            type="abs", inputs={"X": [param]}, outputs={"Out": [absx]},
            attrs={"op_role": OpRole.Backward},
        )
        shifted = block.create_var(
            name=unique_name.generate(f"{param.name}.abs_eps"), stop_gradient=True
        )
        block.append_op(
            type="scale", inputs={"X": [absx]}, outputs={"Out": [shifted]},
            attrs={"scale": 1.0, "bias": 1e-12, "op_role": OpRole.Backward},
        )
        block.append_op(
            type="elementwise_div", inputs={"X": [param], "Y": [shifted]},
            outputs={"Out": [sign]}, attrs={"op_role": OpRole.Backward},
        )
        decay = block.create_var(
            name=unique_name.generate(f"{param.name}.l1decay"), stop_gradient=True
        )
        block.append_op(
            type="scale", inputs={"X": [sign]}, outputs={"Out": [decay]},
            attrs={"scale": self._coeff, "op_role": OpRole.Backward},
        )
        new_grad = block.create_var(
            name=unique_name.generate(f"{param.name}.grad_reg"), stop_gradient=True
        )
        block.append_op(
            type="sum", inputs={"X": [grad, decay]}, outputs={"Out": [new_grad]},
            attrs={"op_role": OpRole.Backward},
        )
        return new_grad


# reference aliases
L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer


def append_regularization_ops(params_grads, regularization=None):
    out = []
    for param, grad in params_grads:
        reg = getattr(param, "regularizer", None) or regularization
        if reg is None:
            out.append((param, grad))
            continue
        block = param.block.program.global_block()
        out.append((param, reg(param, grad, block)))
    return out

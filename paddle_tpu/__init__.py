"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle Fluid (v1.7 era).

The user-facing programming model mirrors the reference
(/root/reference/python/paddle/fluid/__init__.py): build a declarative
``Program`` of blocks/ops/vars, then hand it to an ``Executor(place)``.
The execution substrate is completely different: instead of a per-op
interpreter dispatching CUDA kernels (reference
paddle/fluid/framework/executor.cc:195), whole blocks are lowered to a
single JAX function, compiled once by XLA, and run on TPU.  Distribution
is expressed as named mesh axes + GSPMD sharding instead of NCCL rings
and graph-rewriting transpilers.
"""

from . import core
from . import ops  # registers all op lowerings
from . import kernels  # registers Pallas-backed fused ops
from .core import framework
from .core.framework import (
    Program,
    Block,
    Operator,
    Variable,
    Parameter,
    program_guard,
    default_main_program,
    default_startup_program,
    unique_name,
    name_scope,
    in_dygraph_mode,
)
from .core.executor import Executor, Scope, global_scope, scope_guard
from .core.places import CPUPlace, TPUPlace, CUDAPlace, Place, is_compiled_with_tpu
from .core.backward import append_backward, gradients
from .core.compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from . import layers
from . import nets
from . import initializer
from . import optimizer
from . import regularizer
from . import clip
from . import metrics
from . import io
from . import profiler
from . import average
from . import evaluator
from . import install_check
from .param_attr import ParamAttr, WeightNormParamAttr
from .initializer import (
    Constant,
    Uniform,
    Normal,
    TruncatedNormal,
    Xavier,
    MSRA,
    Bilinear,
    NumpyArrayInitializer,
)
from .data_feeder import DataFeeder
from .reader import DataLoader
from .lod_tensor import LoDTensor, create_lod_tensor, create_random_int_lodtensor
from .io import save, load, save_params, load_params, save_persistables, load_persistables
from .core import dygraph
from .core.dygraph import dygraph_guard as _dg
from .flags import get_flags, set_flags
from . import debugger
from . import flags

# ``fluid``-style alias so reference user code reads naturally:
#   import paddle_tpu as fluid
#   fluid.layers.fc(...)

__version__ = "0.1.0"

__all__ = [
    "Program",
    "Block",
    "Operator",
    "Variable",
    "Parameter",
    "program_guard",
    "default_main_program",
    "default_startup_program",
    "Executor",
    "Scope",
    "global_scope",
    "scope_guard",
    "CPUPlace",
    "TPUPlace",
    "CUDAPlace",
    "append_backward",
    "gradients",
    "CompiledProgram",
    "BuildStrategy",
    "ExecutionStrategy",
    "layers",
    "nets",
    "initializer",
    "optimizer",
    "regularizer",
    "clip",
    "metrics",
    "io",
    "profiler",
    "ParamAttr",
    "DataFeeder",
    "DataLoader",
]

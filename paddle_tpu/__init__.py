"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle Fluid (v1.7 era).

The user-facing programming model mirrors the reference
(/root/reference/python/paddle/fluid/__init__.py): build a declarative
``Program`` of blocks/ops/vars, then hand it to an ``Executor(place)``.
The execution substrate is completely different: instead of a per-op
interpreter dispatching CUDA kernels (reference
paddle/fluid/framework/executor.cc:195), whole blocks are lowered to a
single JAX function, compiled once by XLA, and run on TPU.  Distribution
is expressed as named mesh axes + GSPMD sharding instead of NCCL rings
and graph-rewriting transpilers.
"""

from . import core
from . import ops  # registers all op lowerings
from . import kernels  # registers Pallas-backed fused ops
from .core import framework
from .core.framework import (
    Program,
    Block,
    Operator,
    Variable,
    Parameter,
    program_guard,
    default_main_program,
    default_startup_program,
    unique_name,
    name_scope,
    in_dygraph_mode,
)
from .core.executor import Executor, Scope, global_scope, scope_guard
from .core.places import CPUPlace, TPUPlace, CUDAPlace, Place, is_compiled_with_tpu
from .core.backward import append_backward, gradients
from .core.compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from . import layers
from . import nets
from . import initializer
from . import optimizer
from . import regularizer
from . import clip
from . import metrics
from . import io
from . import profiler
from . import average
from . import evaluator
from . import install_check
from .param_attr import ParamAttr, WeightNormParamAttr
from .initializer import (
    Constant,
    Uniform,
    Normal,
    TruncatedNormal,
    Xavier,
    MSRA,
    Bilinear,
    NumpyArrayInitializer,
)
from .data_feeder import DataFeeder
from .reader import DataLoader
from .lod_tensor import LoDTensor, create_lod_tensor, create_random_int_lodtensor
from .io import save, load, save_params, load_params, save_persistables, load_persistables
from .core.dygraph import dygraph_guard as _dg
# the user-facing fluid.dygraph is the full package (Layer, nn classes,
# schedulers, guard/enabled from base.py)
from . import dygraph
from .flags import get_flags, set_flags
from . import debugger
from . import flags
from . import analysis  # static Program-IR verifier / lint (proglint)
from . import serving  # dynamic-batching inference serving (engine/server)
from . import generation  # paged KV-cache + continuous-batching decode
from . import resilience  # fault-tolerant training supervisor (chaos-tested)
from . import partition  # logical-axis-rules partitioner (sharded execution)
from . import observability  # unified telemetry: metrics/tracing/flight
from . import traffic  # SLO-aware admission + multi-tenant scheduling
from . import quantize  # post-training weight quantization (inference)

# ``fluid``-style alias so reference user code reads naturally:
#   import paddle_tpu as fluid
#   fluid.layers.fc(...)

from .version import full_version as __version__  # noqa: E402
from .version import commit as __git_commit__  # noqa: E402

__all__ = [
    "Program",
    "Block",
    "Operator",
    "Variable",
    "Parameter",
    "program_guard",
    "default_main_program",
    "default_startup_program",
    "Executor",
    "Scope",
    "global_scope",
    "scope_guard",
    "CPUPlace",
    "TPUPlace",
    "CUDAPlace",
    "append_backward",
    "gradients",
    "CompiledProgram",
    "BuildStrategy",
    "ExecutionStrategy",
    "layers",
    "nets",
    "initializer",
    "optimizer",
    "regularizer",
    "clip",
    "metrics",
    "io",
    "profiler",
    "ParamAttr",
    "DataFeeder",
    "DataLoader",
    "analysis",
    "serving",
    "generation",
    "resilience",
    "observability",
    "traffic",
]


# -- reference framework.py helpers ---------------------------------------

def cpu_places(device_count=None):
    """Reference framework.py cpu_places."""
    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """Reference cuda_places: accelerator places — TPU devices here."""
    import jax

    ids = device_ids if device_ids is not None else range(len(jax.devices()))
    return [TPUPlace(i) for i in ids]


def cuda_pinned_places(device_count=None):
    # pinned host memory is a CUDA notion; host places stand in
    return cpu_places(device_count)


def is_compiled_with_cuda():
    return False


def device_guard(device=None):
    """Reference device_guard: pin following ops to a device. XLA owns
    placement (whole-block compilation); accepted for parity, no-op."""
    import contextlib

    @contextlib.contextmanager
    def _g():
        yield

    return _g()


def require_version(min_version, max_version=None):
    """Reference framework.py require_version."""
    from . import __version__ as _v

    def parse(s):
        parts = []
        for x in str(s).split(".")[:3]:
            digits = "".join(ch for ch in x if ch.isdigit())
            parts.append(int(digits or 0))
        while len(parts) < 3:
            parts.append(0)  # pad: '0.1' allows any 0.1.x (reference)
        return tuple(parts)

    cur = parse(_v)
    if parse(min_version) > cur:
        raise Exception(
            f"paddle_tpu version {_v} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"paddle_tpu version {_v} > allowed {max_version}")


def load_op_library(lib_path):
    """Reference framework.py load_op_library (custom C++ op .so).
    Custom ops here are python modules calling
    core.registry.register_op; a path to a .py registers its ops, and a
    native .so is loaded via ctypes for host kernels used by py_func."""
    import ctypes
    import runpy

    if str(lib_path).endswith(".py"):
        runpy.run_path(str(lib_path))
        return None
    return ctypes.CDLL(str(lib_path))


class ParallelExecutor:
    """Reference parallel_executor.py ParallelExecutor — thin shim over
    CompiledProgram.with_data_parallel + Executor (the reference's own
    newer API does the same internally)."""

    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from .core.framework import default_main_program

        self._program = main_program or default_main_program()
        self._compiled = CompiledProgram(self._program).with_data_parallel(
            loss_name=loss_name, build_strategy=build_strategy,
            exec_strategy=exec_strategy)
        self._exe = Executor(TPUPlace())
        self._scope = scope

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        import contextlib

        feed = feed if feed is not None else feed_dict
        cm = (scope_guard(self._scope) if self._scope is not None
              else contextlib.nullcontext())
        with cm:
            return self._exe.run(self._compiled, feed=feed,
                                 fetch_list=fetch_list,
                                 return_numpy=return_numpy)

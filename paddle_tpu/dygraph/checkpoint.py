"""Dygraph checkpoint save/load. Reference: fluid/dygraph/checkpoint.py
(save_dygraph/load_dygraph state dicts -> .pdparams)."""

from __future__ import annotations

import os

import numpy as np


def save_dygraph(state_dict, model_path: str):
    arrays = {}
    for k, v in state_dict.items():
        arrays[k] = v.numpy() if hasattr(v, "numpy") else np.asarray(v)
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    np.savez(model_path + ".pdparams.npz", **arrays)


def load_dygraph(model_path: str):
    data = np.load(model_path + ".pdparams.npz")
    state = {k: data[k] for k in data.files}
    return state, None  # (param_dict, optimizer_dict)

"""Dygraph nn module classes. Reference:
python/paddle/fluid/dygraph/nn.py (Linear/FC, Conv2D, Pool2D,
BatchNorm, Embedding, LayerNorm, ...)."""

from __future__ import annotations

import numpy as np

from ..initializer import ConstantInitializer, NormalInitializer
from .base import VarBase, _trace
from .layers import Layer


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__()
        self.weight = self.create_parameter([input_dim, output_dim], param_attr, dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([output_dim], bias_attr, dtype, is_bias=True)
        self._act = act

    def forward(self, x):
        (out,) = _trace(
            "mul", {"X": [x], "Y": [self.weight]}, ["Out"],
            {"x_num_col_dims": len(x.shape) - 1, "y_num_col_dims": 1},
        )
        if self.bias is not None:
            (out,) = _trace(
                "elementwise_add", {"X": [out], "Y": [self.bias]}, ["Out"],
                {"axis": len(out.shape) - 1},
            )
        if self._act:
            (out,) = _trace(self._act, {"X": [out]}, ["Out"], {})
        return out


# reference dygraph/nn.py FC alias
FC = Linear


class Conv2D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1, padding=0,
                 dilation=1, groups=1, param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__()
        fs = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
        std = (2.0 / (fs[0] * fs[1] * num_channels)) ** 0.5
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups, fs[0], fs[1]], param_attr, dtype,
            default_initializer=NormalInitializer(0.0, std),
        )
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_filters], bias_attr, dtype, is_bias=True)
        self._attrs = {
            "strides": [stride] * 2 if isinstance(stride, int) else list(stride),
            "paddings": [padding] * 2 if isinstance(padding, int) else list(padding),
            "dilations": [dilation] * 2 if isinstance(dilation, int) else list(dilation),
            "groups": groups,
        }
        self._act = act

    def forward(self, x):
        ins = {"Input": [x], "Filter": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        (out,) = _trace("conv2d", ins, ["Output"], dict(self._attrs))
        if self._act:
            (out,) = _trace(self._act, {"X": [out]}, ["Out"], {})
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
                 global_pooling=False, ceil_mode=False, exclusive=True):
        super().__init__()
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": [pool_size] * 2 if isinstance(pool_size, int) else list(pool_size),
            "strides": [pool_stride] * 2 if isinstance(pool_stride, int) else list(pool_stride),
            "paddings": [pool_padding] * 2 if isinstance(pool_padding, int) else list(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        }

    def forward(self, x):
        (out,) = _trace("pool2d", {"X": [x]}, ["Out"], dict(self._attrs))
        return out


class BatchNorm(Layer):
    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", use_global_stats=False):
        super().__init__()
        self.weight = self.create_parameter(
            [num_channels], param_attr, dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        self.bias = self.create_parameter([num_channels], bias_attr, dtype, is_bias=True)
        self._mean = VarBase(np.zeros(num_channels, dtype), persistable=True,
                             stop_gradient=True)
        self._variance = VarBase(np.ones(num_channels, dtype), persistable=True,
                                 stop_gradient=True)
        self._buffers["_mean"] = self._mean
        self._buffers["_variance"] = self._variance
        self._attrs = {
            "momentum": momentum,
            "epsilon": epsilon,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        }
        self._act = act

    def forward(self, x):
        attrs = dict(self._attrs)
        attrs["is_test"] = not self.training
        outs = _trace(
            "batch_norm",
            {
                "X": [x], "Scale": [self.weight], "Bias": [self.bias],
                "Mean": [self._mean], "Variance": [self._variance],
            },
            ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
            attrs,
        )
        y, mean_out, var_out = outs[0], outs[1], outs[2]
        self._mean.set_value(mean_out)
        self._variance.set_value(var_out)
        if self._act:
            (y,) = _trace(self._act, {"X": [y]}, ["Out"], {})
        return y


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, padding_idx=None, param_attr=None,
                 dtype="float32"):
        super().__init__()
        self.weight = self.create_parameter(list(size), param_attr, dtype)
        self._padding_idx = -1 if padding_idx is None else padding_idx

    def forward(self, ids):
        (out,) = _trace(
            "lookup_table_v2", {"W": [self.weight], "Ids": [ids]}, ["Out"],
            {"padding_idx": self._padding_idx},
        )
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True, epsilon=1e-5,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        n = int(np.prod(normalized_shape))
        self.weight = self.create_parameter(
            [n], param_attr, dtype, default_initializer=ConstantInitializer(1.0)
        ) if scale else None
        self.bias = self.create_parameter([n], bias_attr, dtype, is_bias=True) if shift else None
        self._epsilon = epsilon
        self._act = act
        self._norm_ndim = len(normalized_shape)

    def forward(self, x):
        ins = {"X": [x]}
        if self.weight is not None:
            ins["Scale"] = [self.weight]
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        outs = _trace(
            "layer_norm", ins, ["Y", "Mean", "Variance"],
            {"begin_norm_axis": len(x.shape) - self._norm_ndim, "epsilon": self._epsilon},
        )
        y = outs[0]
        if self._act:
            (y,) = _trace(self._act, {"X": [y]}, ["Out"], {})
        return y


class Dropout(Layer):
    def __init__(self, p=0.5, dropout_implementation="downgrade_in_infer"):
        super().__init__()
        self._p = p
        self._impl = dropout_implementation

    def forward(self, x):
        outs = _trace(
            "dropout", {"X": [x]}, ["Out", "Mask"],
            {"dropout_prob": self._p, "is_test": not self.training,
             "dropout_implementation": self._impl},
        )
        return outs[0]

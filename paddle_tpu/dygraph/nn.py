"""Dygraph nn module classes. Reference:
python/paddle/fluid/dygraph/nn.py (Linear/FC, Conv2D, Pool2D,
BatchNorm, Embedding, LayerNorm, ...)."""

from __future__ import annotations

import numpy as np

from ..initializer import ConstantInitializer, NormalInitializer
from .base import VarBase, _trace
from .layers import Layer


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__()
        self.weight = self.create_parameter([input_dim, output_dim], param_attr, dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([output_dim], bias_attr, dtype, is_bias=True)
        self._act = act

    def forward(self, x):
        (out,) = _trace(
            "mul", {"X": [x], "Y": [self.weight]}, ["Out"],
            {"x_num_col_dims": len(x.shape) - 1, "y_num_col_dims": 1},
        )
        if self.bias is not None:
            (out,) = _trace(
                "elementwise_add", {"X": [out], "Y": [self.bias]}, ["Out"],
                {"axis": len(out.shape) - 1},
            )
        if self._act:
            (out,) = _trace(self._act, {"X": [out]}, ["Out"], {})
        return out


# reference dygraph/nn.py FC alias
FC = Linear


class Conv2D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1, padding=0,
                 dilation=1, groups=1, param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__()
        fs = filter_size if isinstance(filter_size, (list, tuple)) else [filter_size] * 2
        std = (2.0 / (fs[0] * fs[1] * num_channels)) ** 0.5
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups, fs[0], fs[1]], param_attr, dtype,
            default_initializer=NormalInitializer(0.0, std),
        )
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_filters], bias_attr, dtype, is_bias=True)
        self._attrs = {
            "strides": [stride] * 2 if isinstance(stride, int) else list(stride),
            "paddings": [padding] * 2 if isinstance(padding, int) else list(padding),
            "dilations": [dilation] * 2 if isinstance(dilation, int) else list(dilation),
            "groups": groups,
        }
        self._act = act

    def forward(self, x):
        ins = {"Input": [x], "Filter": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        (out,) = _trace("conv2d", ins, ["Output"], dict(self._attrs))
        if self._act:
            (out,) = _trace(self._act, {"X": [out]}, ["Out"], {})
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
                 global_pooling=False, ceil_mode=False, exclusive=True):
        super().__init__()
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": [pool_size] * 2 if isinstance(pool_size, int) else list(pool_size),
            "strides": [pool_stride] * 2 if isinstance(pool_stride, int) else list(pool_stride),
            "paddings": [pool_padding] * 2 if isinstance(pool_padding, int) else list(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        }

    def forward(self, x):
        (out,) = _trace("pool2d", {"X": [x]}, ["Out"], dict(self._attrs))
        return out


class BatchNorm(Layer):
    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", use_global_stats=False):
        super().__init__()
        self.weight = self.create_parameter(
            [num_channels], param_attr, dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        self.bias = self.create_parameter([num_channels], bias_attr, dtype, is_bias=True)
        self._mean = VarBase(np.zeros(num_channels, dtype), persistable=True,
                             stop_gradient=True)
        self._variance = VarBase(np.ones(num_channels, dtype), persistable=True,
                                 stop_gradient=True)
        self._buffers["_mean"] = self._mean
        self._buffers["_variance"] = self._variance
        self._attrs = {
            "momentum": momentum,
            "epsilon": epsilon,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        }
        self._act = act

    def forward(self, x):
        attrs = dict(self._attrs)
        attrs["is_test"] = not self.training
        outs = _trace(
            "batch_norm",
            {
                "X": [x], "Scale": [self.weight], "Bias": [self.bias],
                "Mean": [self._mean], "Variance": [self._variance],
            },
            ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
            attrs,
        )
        y, mean_out, var_out = outs[0], outs[1], outs[2]
        self._mean.set_value(mean_out)
        self._variance.set_value(var_out)
        if self._act:
            (y,) = _trace(self._act, {"X": [y]}, ["Out"], {})
        return y


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, padding_idx=None, param_attr=None,
                 dtype="float32"):
        super().__init__()
        self.weight = self.create_parameter(list(size), param_attr, dtype)
        self._padding_idx = -1 if padding_idx is None else padding_idx

    def forward(self, ids):
        (out,) = _trace(
            "lookup_table_v2", {"W": [self.weight], "Ids": [ids]}, ["Out"],
            {"padding_idx": self._padding_idx},
        )
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True, epsilon=1e-5,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        n = int(np.prod(normalized_shape))
        self.weight = self.create_parameter(
            [n], param_attr, dtype, default_initializer=ConstantInitializer(1.0)
        ) if scale else None
        self.bias = self.create_parameter([n], bias_attr, dtype, is_bias=True) if shift else None
        self._epsilon = epsilon
        self._act = act
        self._norm_ndim = len(normalized_shape)

    def forward(self, x):
        ins = {"X": [x]}
        if self.weight is not None:
            ins["Scale"] = [self.weight]
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        outs = _trace(
            "layer_norm", ins, ["Y", "Mean", "Variance"],
            {"begin_norm_axis": len(x.shape) - self._norm_ndim, "epsilon": self._epsilon},
        )
        y = outs[0]
        if self._act:
            (y,) = _trace(self._act, {"X": [y]}, ["Out"], {})
        return y


class Dropout(Layer):
    def __init__(self, p=0.5, dropout_implementation="downgrade_in_infer"):
        super().__init__()
        self._p = p
        self._impl = dropout_implementation

    def forward(self, x):
        outs = _trace(
            "dropout", {"X": [x]}, ["Out", "Mask"],
            {"dropout_prob": self._p, "is_test": not self.training,
             "dropout_implementation": self._impl},
        )
        return outs[0]


def _pair(v, n=2):
    return [v] * n if isinstance(v, int) else list(v)


class Conv2DTranspose(Layer):
    """Reference dygraph/nn.py:2128."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        fs = _pair(filter_size)
        self.weight = self.create_parameter(
            [num_channels, num_filters // groups, fs[0], fs[1]], param_attr,
            dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_filters], bias_attr, dtype,
                                              is_bias=True)
        self._attrs = {"strides": _pair(stride), "paddings": _pair(padding),
                       "dilations": _pair(dilation), "groups": groups}
        self._act = act

    def forward(self, x):
        ins = {"Input": [x], "Filter": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        (out,) = _trace("conv2d_transpose", ins, ["Output"], dict(self._attrs))
        if self._act:
            (out,) = _trace(self._act, {"X": [out]}, ["Out"], {})
        return out


class Conv3D(Layer):
    """Reference dygraph/nn.py:272."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        fs = _pair(filter_size, 3)
        std = (2.0 / (fs[0] * fs[1] * fs[2] * num_channels)) ** 0.5
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups, fs[0], fs[1], fs[2]],
            param_attr, dtype,
            default_initializer=NormalInitializer(0.0, std))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_filters], bias_attr, dtype,
                                              is_bias=True)
        self._attrs = {"strides": _pair(stride, 3),
                       "paddings": _pair(padding, 3),
                       "dilations": _pair(dilation, 3), "groups": groups}
        self._act = act

    def forward(self, x):
        ins = {"Input": [x], "Filter": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        (out,) = _trace("conv3d", ins, ["Output"], dict(self._attrs))
        if self._act:
            (out,) = _trace(self._act, {"X": [out]}, ["Out"], {})
        return out


class Conv3DTranspose(Layer):
    """Reference dygraph/nn.py:474."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        if groups != 1:
            # same stance as the 2D lowering (ops/nn.py): running
            # ungrouped would silently compute full connectivity
            raise NotImplementedError(
                "conv3d_transpose with groups != 1 is not lowered yet")
        fs = _pair(filter_size, 3)
        self.weight = self.create_parameter(
            [num_channels, num_filters, fs[0], fs[1], fs[2]],
            param_attr, dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_filters], bias_attr, dtype,
                                              is_bias=True)
        self._attrs = {"strides": _pair(stride, 3),
                       "paddings": _pair(padding, 3),
                       "dilations": _pair(dilation, 3)}
        self._act = act

    def forward(self, x):
        ins = {"Input": [x], "Filter": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        (out,) = _trace("conv3d_transpose", ins, ["Output"], dict(self._attrs))
        if self._act:
            (out,) = _trace(self._act, {"X": [out]}, ["Out"], {})
        return out


class GRUUnit(Layer):
    """Reference dygraph/nn.py:1505 (single-step GRU cell)."""

    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 dtype="float32"):
        super().__init__()
        # size = 3 * hidden
        self._hidden = size // 3
        self._acts = {"activation": activation,
                      "gate_activation": gate_activation}
        self.weight = self.create_parameter(
            [self._hidden, size], param_attr, dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([size], bias_attr, dtype,
                                              is_bias=True)

    def forward(self, input, hidden):
        ins = {"Input": [input], "HiddenPrev": [hidden],
               "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        outs = _trace("gru_unit", ins,
                      ["Gate", "ResetHiddenPrev", "Hidden"],
                      dict(self._acts))
        return outs[2], outs[1], outs[0]  # hidden, reset_hidden_prev, gate


class PRelu(Layer):
    """Reference dygraph/nn.py:1917."""

    def __init__(self, mode="all", channel=None, input_shape=None,
                 param_attr=None, dtype="float32"):
        super().__init__()
        self._mode = mode
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [channel or 1]
        else:  # element: one alpha per feature cell, batch-free
            # (reference PRelu uses [1] + input_shape[1:])
            shape = [1] + list(input_shape or [1, 1])[1:]
        self.weight = self.create_parameter(
            shape, param_attr, dtype,
            default_initializer=ConstantInitializer(0.25))

    def forward(self, x):
        (out,) = _trace("prelu", {"X": [x], "Alpha": [self.weight]},
                        ["Out"], {"mode": self._mode})
        return out


class BilinearTensorProduct(Layer):
    """Reference dygraph/nn.py:2020."""

    def __init__(self, input1_dim, input2_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        self.weight = self.create_parameter(
            [output_dim, input1_dim, input2_dim], param_attr, dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([1, output_dim], bias_attr,
                                              dtype, is_bias=True)
        self._act = act

    def forward(self, x, y):
        ins = {"X": [x], "Y": [y], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        (out,) = _trace("bilinear_tensor_product", ins, ["Out"], {})
        if self._act:
            (out,) = _trace(self._act, {"X": [out]}, ["Out"], {})
        return out


class SequenceConv(Layer):
    """Reference dygraph/nn.py:2356 (context-window conv over time)."""

    def __init__(self, name_scope=None, num_filters=1, filter_size=3,
                 context_start=None, input_dim=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        self._filter_size = filter_size
        self._context_start = (-((filter_size - 1) // 2)
                               if context_start is None else context_start)
        self.weight = self.create_parameter(
            [filter_size * input_dim, num_filters], param_attr, dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_filters], bias_attr,
                                              dtype, is_bias=True)
        self._act = act

    def forward(self, x, length=None):
        ins = {"X": [x], "Filter": [self.weight]}
        if length is not None:
            ins["Length"] = [length]
        (out,) = _trace("sequence_conv", ins, ["Out"],
                        {"contextLength": self._filter_size,
                         "contextStart": self._context_start})
        if self.bias is not None:
            (out,) = _trace("elementwise_add", {"X": [out], "Y": [self.bias]},
                            ["Out"], {"axis": len(out.shape) - 1})
        if self._act:
            (out,) = _trace(self._act, {"X": [out]}, ["Out"], {})
        return out


class RowConv(Layer):
    """Reference dygraph/nn.py:2450 (lookahead row convolution)."""

    def __init__(self, input_dim, future_context_size=2, param_attr=None,
                 act=None, dtype="float32"):
        super().__init__()
        self.weight = self.create_parameter(
            [future_context_size + 1, input_dim], param_attr, dtype)
        self._act = act

    def forward(self, x):
        (out,) = _trace("row_conv", {"X": [x], "Filter": [self.weight]},
                        ["Out"], {})
        if self._act:
            (out,) = _trace(self._act, {"X": [out]}, ["Out"], {})
        return out


class GroupNorm(Layer):
    """Reference dygraph/nn.py:2529."""

    def __init__(self, channels, groups, epsilon=1e-5, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        self._attrs = {"groups": groups, "epsilon": epsilon}
        self.weight = self.create_parameter(
            [channels], param_attr, dtype,
            default_initializer=ConstantInitializer(1.0))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([channels], bias_attr, dtype,
                                              is_bias=True)
        self._act = act

    def forward(self, x):
        ins = {"X": [x], "Scale": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        outs = _trace(
            "group_norm", ins,
            ["Y", "Mean", "Variance"], dict(self._attrs))
        y = outs[0]
        if self._act:
            (y,) = _trace(self._act, {"X": [y]}, ["Out"], {})
        return y


class SpectralNorm(Layer):
    """Reference dygraph/nn.py:2629."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        self._attrs = {"dim": dim, "power_iters": power_iters, "eps": eps}
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = VarBase(
            np.random.RandomState(0).randn(h).astype(dtype),
            persistable=True, stop_gradient=True)
        self.weight_v = VarBase(
            np.random.RandomState(1).randn(w).astype(dtype),
            persistable=True, stop_gradient=True)
        self._buffers["weight_u"] = self.weight_u
        self._buffers["weight_v"] = self.weight_v

    def forward(self, weight):
        (out,) = _trace(
            "spectral_norm",
            {"Weight": [weight], "U": [self.weight_u], "V": [self.weight_v]},
            ["Out"], dict(self._attrs))
        return out


class TreeConv(Layer):
    """Reference dygraph/nn.py:2734 (TBCNN over ops/misc tree_conv)."""

    def __init__(self, feature_size, output_size, num_filters=1,
                 max_depth=8, act="tanh", param_attr=None, bias_attr=None,
                 dtype="float32"):
        super().__init__()
        self.weight = self.create_parameter(
            [feature_size, output_size, 3], param_attr, dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([output_size], bias_attr,
                                              dtype, is_bias=True)
        self._attrs = {"max_depth": max_depth}
        self._act = act

    def forward(self, nodes_vector, edge_set):
        (out,) = _trace(
            "tree_conv",
            {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
             "Filter": [self.weight]},
            ["Out"], dict(self._attrs))
        if self.bias is not None:
            (out,) = _trace("elementwise_add", {"X": [out], "Y": [self.bias]},
                            ["Out"], {"axis": len(out.shape) - 1})
        if self._act:
            (out,) = _trace(self._act, {"X": [out]}, ["Out"], {})
        return out


class NCE(Layer):
    """Reference dygraph/nn.py:1683 (noise-contrastive estimation)."""

    def __init__(self, num_total_classes, dim, num_neg_samples=10,
                 sampler="uniform", param_attr=None, bias_attr=None,
                 dtype="float32"):
        super().__init__()
        self.weight = self.create_parameter(
            [num_total_classes, dim], param_attr, dtype)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([num_total_classes, 1],
                                              bias_attr, dtype, is_bias=True)
        self._attrs = {"num_total_classes": num_total_classes,
                       "num_neg_samples": num_neg_samples,
                       "sampler": 0 if sampler == "uniform" else 1}

    def forward(self, input, label, sample_weight=None):
        ins = {"Input": [input], "Label": [label], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        outs = _trace("nce", ins, ["Cost", "SampleLogits", "SampleLabels"],
                      dict(self._attrs))
        return outs[0]

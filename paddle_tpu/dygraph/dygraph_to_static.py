"""dygraph_to_static: AST transform of python control flow.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/
(ast_transformer.py rewrites a dygraph function's source — IfElse/loop
transformers — so data-dependent python `if`/`while` over Variables
become cond/while ops in a Program; cache_program.py caches the
converted function).

TPU-native redesign: the same source-to-source rewrite, but the
converted control flow targets lax.cond / lax.while_loop directly, so
the converted function is fully jax.jit-able (python `if tracer:` would
throw a TracerBoolConversionError). Dispatch is at runtime: with
concrete (eager) values the original python branch executes, so one
converted function serves both dygraph eagerness and the compiled
static path — the dual-mode contract of the reference's
@declarative."""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any

import jax
import jax.numpy as jnp


# -- runtime helpers (the `_jst` namespace the rewritten code calls) --------


def _unwrap(v):
    from .base import VarBase

    return v.value if isinstance(v, VarBase) else v


def _is_traced(v) -> bool:
    return isinstance(_unwrap(v), jax.core.Tracer)


def _to_pred(v):
    return jnp.reshape(jnp.asarray(_unwrap(v)), ()).astype(bool)


class _Undef:
    """Placeholder for names not yet bound before a converted block.
    Use-site traps make it behave like an unbound name: mere presence
    in a carry is fine (an if-without-else that assigns a new name is
    legal python when the branch is untaken), USING it raises."""

    def __repr__(self):
        return "<to_static undefined>"

    def _raise(self, *a, **k):
        raise NameError(
            "to_static: variable was only assigned in an untaken branch"
        )

    __bool__ = __call__ = __getattr__ = __add__ = __radd__ = _raise
    __sub__ = __mul__ = __truediv__ = __iter__ = __array__ = _raise
    __eq__ = __ne__ = __lt__ = __le__ = __gt__ = __ge__ = _raise
    __getitem__ = __len__ = __str__ = __format__ = __hash__ = _raise


UNDEF = _Undef()


def grab(lcls, names):
    return tuple(lcls.get(n, UNDEF) for n in names)


def _wrap_like(new_vals, templates):
    from .base import VarBase

    out = []
    for nv, t in zip(new_vals, templates):
        if isinstance(t, VarBase):
            out.append(VarBase(nv, stop_gradient=True))
        else:
            out.append(nv)
    return tuple(out)


def _is_missing(v):
    return v is None or isinstance(v, _Undef)


def _tree_zeros_like(v):
    return jax.tree_util.tree_map(
        lambda a: jnp.zeros_like(jnp.asarray(_unwrap(a))), v)


def _reconcile(t_vals, f_vals, allow_substitute):
    """Align branch outputs for select. With allow_substitute (this if
    participates in the early-return transform — its assigned names
    include the done flag), a position defined in only one branch gets
    zeros_like of the defined side: sound because the done-flag gating
    guarantees the undefined side is never the FINAL value along any
    consistent path. For ORDINARY user ifs a mismatch raises — a
    silent zeros substitute would make `y = None; if c: y = ...`
    return 0.0 instead of None under jit. Both-missing positions stay
    None (the name remains undefined)."""
    t2, f2 = list(t_vals), list(f_vals)
    for i, (a, b) in enumerate(zip(t_vals, f_vals)):
        am, bm = _is_missing(a), _is_missing(b)
        if am and bm:
            t2[i] = f2[i] = None
        elif am or bm:
            if not allow_substitute:
                raise NotImplementedError(
                    "to_static: a variable assigned in only one branch "
                    "of a traced if/else must be defined before it"
                )
            if am:
                t2[i] = _tree_zeros_like(b)
            else:
                f2[i] = _tree_zeros_like(a)
    return tuple(t2), tuple(f2)


def convert_ifelse(pred, true_fn, false_fn, init, names=()):
    """Branch fns take the tuple of assigned names' CURRENT values (a
    branch that reads a name it also assigns would otherwise hit
    UnboundLocalError — python makes assigned names function-local) and
    return the updated tuple. `names` lets the traced paths tell the
    early-return transform's generated ifs (which assign the done
    flag) from ordinary user ifs."""
    from .base import VarBase

    allow_substitute = _DONE in names
    if not _is_traced(pred):
        p = _unwrap(pred)
        p = bool(np.asarray(p).reshape(())) if hasattr(p, "reshape") or hasattr(
            p, "__array__") else bool(p)
        # an untaken branch may leave a fresh name as UNDEF — legal
        # until used (the sentinel's use-site traps raise then)
        return true_fn(init) if p else false_fn(init)
    if any(isinstance(v, VarBase) for v in init):
        # VarBase-under-trace: evaluate both branches, select (the
        # rewrap bookkeeping through a lazy cond is not worth it for
        # the eager-API-under-jit corner)
        template = true_fn(init)
        f_template = false_fn(init)
        t_vals, f_vals = _reconcile(
            tuple(_unwrap(v) for v in template),
            tuple(_unwrap(v) for v in f_template), allow_substitute)
        out = jax.lax.cond(_to_pred(pred), lambda: t_vals, lambda: f_vals)
        # wrap positions by whichever branch defined them
        merged = tuple(
            t if not _is_missing(t) else f
            for t, f in zip(template, f_template))
        return _wrap_like(out, merged)
    # pure-array path: a REAL lazy cond — XLA executes only the taken
    # branch, so `if use_aux: big_network(x)` costs nothing when False
    defined_idx = [i for i, v in enumerate(init) if not isinstance(v, _Undef)]
    raw = tuple(init[i] for i in defined_idx)

    def run(branch_fn, c):
        full = list(init)
        for j, i in enumerate(defined_idx):
            full[i] = c[j]
        return tuple(branch_fn(tuple(full)))

    try:
        return jax.lax.cond(
            _to_pred(pred),
            lambda c: run(true_fn, c),
            lambda c: run(false_fn, c),
            raw,
        )
    except (TypeError, NameError):
        # branch outputs differ structurally (a name defined in only
        # one branch — the early-return transform produces this; a
        # fresh _Undef carry surfaces as NameError from its use traps
        # during cond tracing): evaluate both, select with zeros
        # substitution
        t_vals, f_vals = _reconcile(run(true_fn, raw), run(false_fn, raw),
                                    allow_substitute)
        return jax.lax.cond(
            _to_pred(pred), lambda: t_vals, lambda: f_vals)


def convert_while(cond_fn, body_fn, init):
    """cond_fn(carry_tuple) -> scalar; body_fn(carry_tuple) -> carry
    tuple. Dispatches on whether the condition of the INITIAL carry is
    traced."""
    first = cond_fn(init)
    if not _is_traced(first) and not any(_is_traced(v) for v in init):
        carry = init
        while bool(np.asarray(_unwrap(cond_fn(carry))).reshape(())):
            carry = body_fn(carry)
        return carry
    if any(isinstance(v, _Undef) for v in init):
        raise NotImplementedError(
            "to_static: every variable a traced while assigns must be "
            "defined before the loop (it is part of the loop carry)"
        )
    template = init
    raw = tuple(_unwrap(v) for v in init)

    def cond(c):
        return _to_pred(cond_fn(_wrap_like(c, template)))

    def body(c):
        return tuple(_unwrap(v) for v in body_fn(_wrap_like(c, template)))

    out = jax.lax.while_loop(cond, body, raw)
    return _wrap_like(out, template)


def convert_logical_and(a, b_fn):
    if _is_traced(a):
        return jnp.logical_and(_to_pred(a), _to_pred(b_fn()))
    return bool(np.asarray(_unwrap(a)).reshape(())) and b_fn()


def convert_logical_or(a, b_fn):
    if _is_traced(a):
        return jnp.logical_or(_to_pred(a), _to_pred(b_fn()))
    return bool(np.asarray(_unwrap(a)).reshape(())) or b_fn()


def convert_logical_not(a):
    if _is_traced(a):
        return jnp.logical_not(_to_pred(a))
    return not bool(np.asarray(_unwrap(a)).reshape(()))


import numpy as np  # noqa: E402  (used by the helpers above)


# -- the AST transformer -----------------------------------------------------


def _assigned_names(stmts) -> list:
    names = []

    class V(ast.NodeVisitor):
        def visit_Assign(self, node):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if t.id not in names:
                        names.append(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        if isinstance(e, ast.Name) and e.id not in names:
                            names.append(e.id)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            if isinstance(node.target, ast.Name) and node.target.id not in names:
                names.append(node.target.id)
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            pass  # nested defs keep their own scope

    for s in stmts:
        V().visit(s)
    return names


def _contains_return(stmts) -> bool:
    class V(ast.NodeVisitor):
        found = False

        def visit_Return(self, node):
            V.found = True

        def visit_FunctionDef(self, node):
            pass

    for s in stmts:
        V().visit(s)
    return V.found


def _name_tuple(names, ctx):
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names], ctx=ctx())


class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites If/While whose condition may be traced. Mirrors the
    reference's IfElseTransformer/LoopTransformer at the scope the
    framework supports (no return/break/continue inside converted
    blocks — same early-scope limits the reference documents)."""

    def __init__(self):
        self._count = 0

    def _uid(self):
        self._count += 1
        return self._count

    def visit_If(self, node):
        self.generic_visit(node)
        if _contains_return(node.body) or _contains_return(node.orelse):
            raise NotImplementedError(
                "to_static: `return` inside a converted if/else is not "
                "supported — assign to a variable and return after"
            )
        names = sorted(
            set(_assigned_names(node.body)) | set(_assigned_names(node.orelse))
        )
        if not names:
            return node  # pure-side-effect if over concrete values only
        k = self._uid()
        carry = f"_jst_ifc_{k}"
        tname, fname = f"_jst_true_{k}", f"_jst_false_{k}"
        unpack = ast.Assign(
            targets=[_name_tuple(names, ast.Store)],
            value=ast.Name(id=carry, ctx=ast.Load()),
        )
        ret = ast.Return(value=_name_tuple(names, ast.Load))
        import copy

        tfn = ast.FunctionDef(
            name=tname, args=_one_arg(carry),
            body=[unpack] + node.body + [ret], decorator_list=[],
        )
        ffn = ast.FunctionDef(
            name=fname, args=_one_arg(carry),
            body=[copy.deepcopy(unpack)] + list(node.orelse)
            + [copy.deepcopy(ret)],
            decorator_list=[],
        )
        call = ast.Assign(
            targets=[_name_tuple(names, ast.Store)],
            value=ast.Call(
                func=_jst_attr("convert_ifelse"),
                args=[_transform_test(node.test),
                      ast.Name(id=tname, ctx=ast.Load()),
                      ast.Name(id=fname, ctx=ast.Load()),
                      _grab_expr(names)],
                keywords=[ast.keyword(
                    arg="names",
                    value=ast.Tuple(
                        elts=[ast.Constant(value=n) for n in names],
                        ctx=ast.Load()))],
            ),
        )
        return [tfn, ffn, call]

    def visit_While(self, node):
        self.generic_visit(node)
        # break is unsupported inside converted loops, so a while/else's
        # else suite ALWAYS runs — it simply follows the loop
        orelse = list(node.orelse)
        node.orelse = []
        if _contains_return(node.body):
            raise NotImplementedError(
                "to_static: `return` inside a converted while is not supported"
            )
        names = _assigned_names(node.body)
        if not names:
            raise NotImplementedError(
                "to_static: converted while must assign at least one variable"
            )
        k = self._uid()
        carry = f"_jst_carry_{k}"
        cname, bname = f"_jst_cond_{k}", f"_jst_body_{k}"
        unpack = ast.Assign(
            targets=[_name_tuple(names, ast.Store)],
            value=ast.Name(id=carry, ctx=ast.Load()),
        )
        import copy

        cfn = ast.FunctionDef(
            name=cname, args=_one_arg(carry),
            body=[unpack, ast.Return(value=_transform_test(node.test))],
            decorator_list=[],
        )
        bfn = ast.FunctionDef(
            name=bname, args=_one_arg(carry),
            body=[copy.deepcopy(unpack)] + node.body + [
                ast.Return(value=_name_tuple(names, ast.Load))],
            decorator_list=[],
        )
        call = ast.Assign(
            targets=[_name_tuple(names, ast.Store)],
            value=ast.Call(
                func=_jst_attr("convert_while"),
                args=[ast.Name(id=cname, ctx=ast.Load()),
                      ast.Name(id=bname, ctx=ast.Load()),
                      _grab_expr(names)],
                keywords=[],
            ),
        )
        return [cfn, bfn, call] + orelse

    # NOTE: and/or/not are rewritten ONLY inside if/while TESTS
    # (_transform_test below). A value-position boolop like
    # `cfg = opts or {}` keeps python's value-returning semantics.


class _TestExprTransformer(ast.NodeTransformer):
    """Rewrites and/or/not within a condition expression, preserving
    short-circuit for concrete values via lambdas."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        out = node.values[0]
        for v in node.values[1:]:
            out = ast.Call(
                func=_jst_attr(fn),
                args=[out, ast.Lambda(args=_empty_args(), body=v)],
                keywords=[],
            )
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(func=_jst_attr("convert_logical_not"),
                            args=[node.operand], keywords=[])
        return node

    def visit_Lambda(self, node):
        return node  # don't descend into nested value expressions


def _transform_test(test):
    return ast.fix_missing_locations(_TestExprTransformer().visit(test))


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                         kw_defaults=[], kwarg=None, defaults=[])


def _one_arg(name):
    return ast.arguments(posonlyargs=[], args=[ast.arg(arg=name)], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])


def _jst_attr(name):
    return ast.Attribute(value=ast.Name(id="_jst", ctx=ast.Load()),
                         attr=name, ctx=ast.Load())


def _grab_expr(names):
    """`_jst.grab(locals(), [names])` — tolerates names not yet bound
    (assigned for the first time inside the converted block)."""
    return ast.Call(
        func=_jst_attr("grab"),
        args=[
            ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                     args=[], keywords=[]),
            ast.List(elts=[ast.Constant(value=n) for n in names],
                     ctx=ast.Load()),
        ],
        keywords=[],
    )


_CACHE = {}

_RV, _DONE = "_jst_ret_val", "_jst_done"


def finalize_ret(rv, done):
    """Final-return hook for functions whose body can FALL OFF THE END
    while other paths return a value: python semantics say the
    fall-through path returns None. Eagerly the done flag is concrete
    and we honor that; under trace a None-or-value return cannot exist,
    so fail loudly instead of silently returning the zeros
    substitute."""
    if _is_traced(done):
        raise NotImplementedError(
            "to_static: this function returns a value on some paths and "
            "falls through (implicit None) on others — that mix is not "
            "jittable; add an explicit return at the end"
        )
    import numpy as np

    return rv if bool(np.asarray(_unwrap(done)).reshape(())) else None


def _guarantees_return(stmts):
    """True when every path through the suite ends in return/raise."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, (ast.Return, ast.Raise)):
        return True
    if isinstance(last, ast.If):
        return (_guarantees_return(last.body)
                and _guarantees_return(last.orelse))
    return False


def _lower_returns(stmts):
    """Rewrite `return` inside if/else into done-flag + value carries
    (the reference's return_transformer.py): after this pass the only
    `return` left in the suite is a trailing top-level one. Returns
    (new_stmts, had_early_return)."""
    out, early = [], False
    for idx, st in enumerate(stmts):
        rest = stmts[idx + 1:]
        if isinstance(st, ast.Return):
            val = st.value if st.value is not None else ast.Constant(value=None)
            out.append(ast.Assign(
                targets=[ast.Name(id=_RV, ctx=ast.Store())], value=val))
            out.append(ast.Assign(
                targets=[ast.Name(id=_DONE, ctx=ast.Store())],
                value=ast.Constant(value=True)))
            return out, True  # anything after is dead code
        if isinstance(st, ast.If):
            tb, te = _lower_returns(st.body)
            fb, fe = _lower_returns(st.orelse)
            st.body = tb or [ast.Pass()]
            st.orelse = fb
            out.append(st)
            if te or fe:
                new_rest, _ = _lower_returns(rest)
                if new_rest:
                    out.append(ast.If(
                        test=ast.UnaryOp(
                            op=ast.Not(),
                            operand=ast.Name(id=_DONE, ctx=ast.Load())),
                        body=new_rest, orelse=[]))
                return out, True
            continue
        out.append(st)
    return out, early


def _apply_return_transform(fdef):
    guaranteed = _guarantees_return(fdef.body)
    body, had = _lower_returns(fdef.body)
    if not had:
        return
    inits = [
        ast.Assign(targets=[ast.Name(id=_DONE, ctx=ast.Store())],
                   value=ast.Constant(value=False)),
        ast.Assign(targets=[ast.Name(id=_RV, ctx=ast.Store())],
                   value=ast.Constant(value=None)),
    ]
    if guaranteed:
        final = ast.Return(value=ast.Name(id=_RV, ctx=ast.Load()))
    else:
        # fall-off-the-end is reachable: route through finalize_ret so
        # eager returns None on that path and jit fails loudly
        final = ast.Return(value=ast.Call(
            func=_jst_attr("finalize_ret"),
            args=[ast.Name(id=_RV, ctx=ast.Load()),
                  ast.Name(id=_DONE, ctx=ast.Load())],
            keywords=[]))
    fdef.body = inits + body + [final]


def convert_to_static(fn):
    """Source-to-source conversion (reference cache_program.py caches
    by function; same here)."""
    if fn in _CACHE:
        return _CACHE[fn]
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    fdef = tree.body[0]
    fdef.decorator_list = []  # drop @declarative/@to_static
    _apply_return_transform(fdef)
    new_tree = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    import sys

    # exec into the LIVE module globals (not a copy) so forward
    # references and monkeypatched globals keep working; only _jst is
    # injected (collision-checked). Closures exec into a COPY with the
    # free variables re-read from the cells at every call (they may be
    # rebound between calls).
    ns = dict(fn.__globals__) if fn.__closure__ else fn.__globals__
    me = sys.modules[__name__]
    if "_jst" in ns and ns["_jst"] is not me:
        raise RuntimeError(
            "to_static: the module already binds the name '_jst'"
        )
    ns["_jst"] = me
    converted_name = fdef.name
    fdef.name = f"_jst_converted_{fn.__name__}"
    ast.fix_missing_locations(new_tree)
    code = compile(new_tree, filename=f"<to_static:{fn.__name__}>", mode="exec")
    exec(code, ns)
    inner = ns.pop(fdef.name)
    inner.__name__ = converted_name
    if fn.__closure__:
        free, cells = fn.__code__.co_freevars, fn.__closure__

        @functools.wraps(fn)
        def converted(*args, **kwargs):
            for n, c in zip(free, cells):
                ns[n] = c.cell_contents
            return inner(*args, **kwargs)
    else:
        converted = inner
    _CACHE[fn] = converted
    return converted


def declarative(fn=None):
    """@declarative — the reference dygraph_to_static entry point."""
    def deco(f):
        converted = convert_to_static(f)

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            return converted(*args, **kwargs)

        wrapper._converted = converted
        wrapper._original = f
        return wrapper

    return deco(fn) if fn is not None else deco

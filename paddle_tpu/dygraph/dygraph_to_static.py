"""dygraph_to_static: AST transform of python control flow.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/
(ast_transformer.py rewrites a dygraph function's source — IfElse/loop
transformers — so data-dependent python `if`/`while` over Variables
become cond/while ops in a Program; cache_program.py caches the
converted function).

TPU-native redesign: the same source-to-source rewrite, but the
converted control flow targets lax.cond / lax.while_loop directly, so
the converted function is fully jax.jit-able (python `if tracer:` would
throw a TracerBoolConversionError). Dispatch is at runtime: with
concrete (eager) values the original python branch executes, so one
converted function serves both dygraph eagerness and the compiled
static path — the dual-mode contract of the reference's
@declarative."""

from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any

import jax
import jax.numpy as jnp


# -- runtime helpers (the `_jst` namespace the rewritten code calls) --------


def _unwrap(v):
    from .base import VarBase

    return v.value if isinstance(v, VarBase) else v


def _is_traced(v) -> bool:
    return isinstance(_unwrap(v), jax.core.Tracer)


def _to_pred(v):
    return jnp.reshape(jnp.asarray(_unwrap(v)), ()).astype(bool)


class _Undef:
    """Placeholder for names not yet bound before a converted block.
    Use-site traps make it behave like an unbound name: mere presence
    in a carry is fine (an if-without-else that assigns a new name is
    legal python when the branch is untaken), USING it raises."""

    def __repr__(self):
        return "<to_static undefined>"

    def _raise(self, *a, **k):
        raise NameError(
            "to_static: variable was only assigned in an untaken branch"
        )

    __bool__ = __call__ = __getattr__ = __add__ = __radd__ = _raise
    __sub__ = __mul__ = __truediv__ = __iter__ = __array__ = _raise
    __eq__ = __ne__ = __lt__ = __le__ = __gt__ = __ge__ = _raise
    __getitem__ = __len__ = __str__ = __format__ = __hash__ = _raise


UNDEF = _Undef()


def grab(lcls, names):
    return tuple(lcls.get(n, UNDEF) for n in names)


def _wrap_like(new_vals, templates):
    from .base import VarBase

    out = []
    for nv, t in zip(new_vals, templates):
        if isinstance(t, VarBase):
            out.append(VarBase(nv, stop_gradient=True))
        else:
            out.append(nv)
    return tuple(out)


def _is_missing(v):
    return v is None or isinstance(v, _Undef)


def _tree_zeros_like(v):
    return jax.tree_util.tree_map(
        lambda a: jnp.zeros_like(jnp.asarray(_unwrap(a))), v)


def _reconcile(t_vals, f_vals, allow_substitute):
    """Align branch outputs for select. With allow_substitute (this if
    participates in the early-return transform — its assigned names
    include the done flag), a position defined in only one branch gets
    zeros_like of the defined side: sound because the done-flag gating
    guarantees the undefined side is never the FINAL value along any
    consistent path. For ORDINARY user ifs a mismatch raises — a
    silent zeros substitute would make `y = None; if c: y = ...`
    return 0.0 instead of None under jit. Both-missing positions stay
    None (the name remains undefined)."""
    t2, f2 = list(t_vals), list(f_vals)
    for i, (a, b) in enumerate(zip(t_vals, f_vals)):
        am, bm = _is_missing(a), _is_missing(b)
        if am and bm:
            t2[i] = f2[i] = None
        elif am or bm:
            if not allow_substitute:
                raise NotImplementedError(
                    "to_static: a variable assigned in only one branch "
                    "of a traced if/else must be defined before it"
                )
            if am:
                t2[i] = _tree_zeros_like(b)
            else:
                f2[i] = _tree_zeros_like(a)
    return tuple(t2), tuple(f2)


def convert_ifelse(pred, true_fn, false_fn, init, names=()):
    """Branch fns take the tuple of assigned names' CURRENT values (a
    branch that reads a name it also assigns would otherwise hit
    UnboundLocalError — python makes assigned names function-local) and
    return the updated tuple. `names` lets the traced paths tell the
    early-return transform's generated ifs (which assign the done
    flag) from ordinary user ifs."""
    from .base import VarBase

    allow_substitute = _DONE in names
    if not _is_traced(pred):
        p = _unwrap(pred)
        p = bool(np.asarray(p).reshape(())) if hasattr(p, "reshape") or hasattr(
            p, "__array__") else bool(p)
        # an untaken branch may leave a fresh name as UNDEF — legal
        # until used (the sentinel's use-site traps raise then)
        return true_fn(init) if p else false_fn(init)
    if any(isinstance(v, VarBase) for v in init):
        # VarBase-under-trace: evaluate both branches, select (the
        # rewrap bookkeeping through a lazy cond is not worth it for
        # the eager-API-under-jit corner)
        template = true_fn(init)
        f_template = false_fn(init)
        t_vals, f_vals = _reconcile(
            tuple(_unwrap(v) for v in template),
            tuple(_unwrap(v) for v in f_template), allow_substitute)
        out = jax.lax.cond(_to_pred(pred), lambda: t_vals, lambda: f_vals)
        # wrap positions by whichever branch defined them
        merged = tuple(
            t if not _is_missing(t) else f
            for t, f in zip(template, f_template))
        return _wrap_like(out, merged)
    # pure-array path: a REAL lazy cond — XLA executes only the taken
    # branch, so `if use_aux: big_network(x)` costs nothing when False
    defined_idx = [i for i, v in enumerate(init) if not isinstance(v, _Undef)]
    raw = tuple(init[i] for i in defined_idx)

    def run(branch_fn, c):
        full = list(init)
        for j, i in enumerate(defined_idx):
            full[i] = c[j]
        return tuple(branch_fn(tuple(full)))

    try:
        return jax.lax.cond(
            _to_pred(pred),
            lambda c: run(true_fn, c),
            lambda c: run(false_fn, c),
            raw,
        )
    except (TypeError, NameError):
        # branch outputs differ structurally (a name defined in only
        # one branch — the early-return transform produces this; a
        # fresh _Undef carry surfaces as NameError from its use traps
        # during cond tracing): evaluate both, select with zeros
        # substitution
        t_vals, f_vals = _reconcile(run(true_fn, raw), run(false_fn, raw),
                                    allow_substitute)
        return jax.lax.cond(
            _to_pred(pred), lambda: t_vals, lambda: f_vals)


def convert_while(cond_fn, body_fn, init, names=()):
    """cond_fn(carry_tuple) -> scalar; body_fn(carry_tuple) -> carry
    tuple. Hybrid dispatch, re-checked EVERY evaluation: while the
    condition comes back concrete, run python iterations (this also
    unrolls loops whose trip count is static but whose carry is traced
    — the static `for i in range(n)` / layer-list case, where the
    reference leaves the loop un-converted too); the moment the
    condition evaluates to a tracer, hand the current carry to
    lax.while_loop."""
    carry = tuple(init)
    while True:
        c = cond_fn(carry)
        if _is_traced(c):
            return _traced_while(cond_fn, body_fn, carry, names)
        if not bool(np.asarray(_unwrap(c)).reshape(())):
            return carry
        carry = body_fn(carry)


def _traced_while(cond_fn, body_fn, init, names):
    # zeros-substitution is sound ONLY for the done-flag machinery's
    # own slots (_RV/_DONE, gated by the done flag); a user variable
    # first assigned inside the loop must still fail loudly — zeros
    # would silently stand in where python raises NameError
    missing = {
        i for i, v in enumerate(init)
        if _is_missing(v) and i < len(names) and names[i] in (_RV, _DONE)
    }
    if any(_is_missing(v) and i not in missing
           for i, v in enumerate(init)):
        raise NotImplementedError(
            "to_static: every variable a traced while assigns must be "
            "defined before the loop (it is part of the loop carry)"
        )
    if missing:
        # done-flag machinery (early return lowered into the loop): a
        # missing carry slot (e.g. _jst_ret_val=None) takes zeros shaped
        # like the body's output for it — sound because the done flag
        # guarantees the substitute is never the final value. The probe
        # trace is discarded; XLA dead-code-eliminates it.
        probe = body_fn(init)
        init = tuple(
            _tree_zeros_like(t) if i in missing and not _is_missing(t) else v
            for i, (v, t) in enumerate(zip(init, probe))
        )
    template = init
    raw = tuple(_unwrap(v) for v in init)

    def cond(c):
        return _to_pred(cond_fn(_wrap_like(c, template)))

    def body(c):
        return tuple(_unwrap(v) for v in body_fn(_wrap_like(c, template)))

    out = jax.lax.while_loop(cond, body, raw)
    return _wrap_like(out, template)


# -- for-loop sequence protocol ---------------------------------------------


class _RangeSeq:
    """range(...) whose bounds may be tracers (python range() rejects
    those)."""

    def __init__(self, start, stop, step):
        self.start, self.stop, self.step = start, stop, step


def to_seq_range(*args):
    if len(args) == 1:
        return _RangeSeq(0, args[0], 1)
    if len(args) == 2:
        return _RangeSeq(args[0], args[1], 1)
    return _RangeSeq(args[0], args[1], args[2])


def to_seq(x):
    x = _unwrap(x)
    if isinstance(x, range):
        return _RangeSeq(x.start, x.stop, x.step)
    return x


def seq_len(seq):
    if isinstance(seq, _RangeSeq):
        s, e, st = (_unwrap(seq.start), _unwrap(seq.stop), _unwrap(seq.step))
        if not any(map(_is_traced, (s, e, st))):
            return len(range(int(s), int(e), int(st)))
        # floor-division identity, valid for either step sign
        return jnp.maximum(0, -((s - e) // st))
    if hasattr(seq, "shape"):
        if not seq.shape:
            raise TypeError("to_static: cannot iterate a 0-d tensor")
        return int(seq.shape[0])  # static shapes: python int
    return len(seq)


def seq_get(seq, i):
    if isinstance(seq, _RangeSeq):
        return seq.start + i * seq.step
    if isinstance(seq, (list, tuple)):
        if _is_traced(i):
            raise NotImplementedError(
                "to_static: cannot index a python list with a traced loop "
                "index — iterate a stacked tensor instead"
            )
        return seq[int(np.asarray(_unwrap(i)).reshape(()))]
    return seq[i]


def seq_template(seq, n):
    """Pre-loop binding for the loop target so a traced loop has a
    defined carry. For a provably-empty concrete sequence the target
    stays undefined (python semantics); otherwise the element at index
    0 serves as the template (after an empty TRACED loop the target
    keeps this value — python-undefined is not expressible in a traced
    carry)."""
    if not _is_traced(n) and int(np.asarray(_unwrap(n)).reshape(())) == 0:
        return UNDEF
    return seq_get(seq, 0)


def convert_logical_and(a, b_fn):
    if _is_traced(a):
        return jnp.logical_and(_to_pred(a), _to_pred(b_fn()))
    return bool(np.asarray(_unwrap(a)).reshape(())) and b_fn()


def convert_logical_or(a, b_fn):
    if _is_traced(a):
        return jnp.logical_or(_to_pred(a), _to_pred(b_fn()))
    return bool(np.asarray(_unwrap(a)).reshape(())) or b_fn()


def convert_logical_not(a):
    if _is_traced(a):
        return jnp.logical_not(_to_pred(a))
    return not bool(np.asarray(_unwrap(a)).reshape(()))


import numpy as np  # noqa: E402  (used by the helpers above)


# -- the AST transformer -----------------------------------------------------


def _assigned_names(stmts) -> list:
    names = []

    class V(ast.NodeVisitor):
        def visit_Assign(self, node):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if t.id not in names:
                        names.append(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        if isinstance(e, ast.Name) and e.id not in names:
                            names.append(e.id)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            if isinstance(node.target, ast.Name) and node.target.id not in names:
                names.append(node.target.id)
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            pass  # nested defs keep their own scope

    for s in stmts:
        V().visit(s)
    return names


def _contains_return(stmts) -> bool:
    class V(ast.NodeVisitor):
        found = False

        def visit_Return(self, node):
            V.found = True

        def visit_FunctionDef(self, node):
            pass

    for s in stmts:
        V().visit(s)
    return V.found


def _name_tuple(names, ctx):
    return ast.Tuple(elts=[ast.Name(id=n, ctx=ctx()) for n in names], ctx=ctx())


def _lower_break_continue(stmts, brk, cnt):
    """Rewrite break/continue into flag assignments + rest-gating (the
    reference's break_continue_transformer.py): `break` sets the brk
    flag (the loop condition gains `and not brk`), `continue` sets the
    cnt flag (reset at the top of each iteration); statements after
    either, at any If nesting depth, are gated on the flags being
    unset. Does not descend into nested FunctionDefs (converted inner
    loops are already function defs by the time this runs, so any
    remaining Break/Continue belongs to THIS loop).
    Returns (new_stmts, uses_brk, uses_cnt)."""
    out, uses_brk, uses_cnt = [], False, False
    for idx, st in enumerate(stmts):
        rest = stmts[idx + 1:]
        if isinstance(st, ast.Break):
            out.append(ast.Assign(
                targets=[ast.Name(id=brk, ctx=ast.Store())],
                value=ast.Constant(value=True)))
            return out, True, uses_cnt  # rest of suite is dead code
        if isinstance(st, ast.Continue):
            out.append(ast.Assign(
                targets=[ast.Name(id=cnt, ctx=ast.Store())],
                value=ast.Constant(value=True)))
            return out, uses_brk, True
        if isinstance(st, ast.If):
            tb, tbrk, tcnt = _lower_break_continue(st.body, brk, cnt)
            fb, fbrk, fcnt = _lower_break_continue(st.orelse, brk, cnt)
            st.body = tb or [ast.Pass()]
            st.orelse = fb
            out.append(st)
            if tbrk or fbrk or tcnt or fcnt:
                uses_brk = uses_brk or tbrk or fbrk
                uses_cnt = uses_cnt or tcnt or fcnt
                new_rest, rbrk, rcnt = _lower_break_continue(rest, brk, cnt)
                uses_brk, uses_cnt = uses_brk or rbrk, uses_cnt or rcnt
                if new_rest:
                    flags = []
                    if tbrk or fbrk:
                        flags.append(ast.Name(id=brk, ctx=ast.Load()))
                    if tcnt or fcnt:
                        flags.append(ast.Name(id=cnt, ctx=ast.Load()))
                    test = flags[0] if len(flags) == 1 else ast.BoolOp(
                        op=ast.Or(), values=flags)
                    out.append(ast.If(
                        test=ast.UnaryOp(op=ast.Not(), operand=test),
                        body=new_rest, orelse=[]))
                return out, uses_brk, uses_cnt
            continue
        out.append(st)
    return out, uses_brk, uses_cnt


class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites If/While/For whose condition may be traced. Mirrors the
    reference's IfElseTransformer/LoopTransformer
    (dygraph_to_static/loop_transformer.py:115 visit_For, :121
    visit_While) with break/continue and early-return support."""

    def __init__(self):
        self._count = 0

    def _uid(self):
        self._count += 1
        return self._count

    def visit_If(self, node):
        self.generic_visit(node)
        if _contains_return(node.body) or _contains_return(node.orelse):
            raise NotImplementedError(
                "to_static: `return` inside a converted if/else is not "
                "supported — assign to a variable and return after"
            )
        names = sorted(
            set(_assigned_names(node.body)) | set(_assigned_names(node.orelse))
        )
        if not names:
            return node  # pure-side-effect if over concrete values only
        k = self._uid()
        carry = f"_jst_ifc_{k}"
        tname, fname = f"_jst_true_{k}", f"_jst_false_{k}"
        unpack = ast.Assign(
            targets=[_name_tuple(names, ast.Store)],
            value=ast.Name(id=carry, ctx=ast.Load()),
        )
        ret = ast.Return(value=_name_tuple(names, ast.Load))
        import copy

        tfn = ast.FunctionDef(
            name=tname, args=_one_arg(carry),
            body=[unpack] + node.body + [ret], decorator_list=[],
        )
        ffn = ast.FunctionDef(
            name=fname, args=_one_arg(carry),
            body=[copy.deepcopy(unpack)] + list(node.orelse)
            + [copy.deepcopy(ret)],
            decorator_list=[],
        )
        call = ast.Assign(
            targets=[_name_tuple(names, ast.Store)],
            value=ast.Call(
                func=_jst_attr("convert_ifelse"),
                args=[_transform_test(node.test),
                      ast.Name(id=tname, ctx=ast.Load()),
                      ast.Name(id=fname, ctx=ast.Load()),
                      _grab_expr(names)],
                keywords=[ast.keyword(
                    arg="names",
                    value=ast.Tuple(
                        elts=[ast.Constant(value=n) for n in names],
                        ctx=ast.Load()))],
            ),
        )
        return [tfn, ffn, call]

    def visit_While(self, node):
        # ORDER MATTERS: lower break/continue on the RAW body first —
        # once generic_visit converts inner ifs into function defs, a
        # Break inside them would be 'break outside loop'. Nested
        # While/For still own their breaks (_lower_break_continue does
        # not descend into them); they convert during generic_visit
        # below.
        orelse = list(node.orelse)
        node.orelse = []
        if _contains_return(node.body):
            # _apply_return_transform lowers return-in-loop before this
            # runs; anything left (e.g. conversion invoked on a raw
            # fragment) still fails loudly
            raise NotImplementedError(
                "to_static: `return` inside a converted while is not supported"
            )
        k = self._uid()
        brk, cnt = f"_jst_brk_{k}", f"_jst_cnt_{k}"
        body, uses_brk, uses_cnt = _lower_break_continue(node.body, brk, cnt)
        pre = []
        test = node.test
        if uses_cnt:
            # reset at the top of each iteration
            body = [ast.Assign(targets=[ast.Name(id=cnt, ctx=ast.Store())],
                               value=ast.Constant(value=False))] + body
            pre.append(ast.Assign(
                targets=[ast.Name(id=cnt, ctx=ast.Store())],
                value=ast.Constant(value=False)))
        if uses_brk:
            test = ast.BoolOp(op=ast.And(), values=[
                test,
                ast.UnaryOp(op=ast.Not(),
                            operand=ast.Name(id=brk, ctx=ast.Load()))])
            pre.append(ast.Assign(
                targets=[ast.Name(id=brk, ctx=ast.Store())],
                value=ast.Constant(value=False)))
        node.body = body
        node.test = test
        ast.fix_missing_locations(node)
        self.generic_visit(node)
        test = node.test
        names = _assigned_names(node.body)
        if not names:
            raise NotImplementedError(
                "to_static: converted while must assign at least one variable"
            )
        carry = f"_jst_carry_{k}"
        cname, bname = f"_jst_cond_{k}", f"_jst_body_{k}"
        unpack = ast.Assign(
            targets=[_name_tuple(names, ast.Store)],
            value=ast.Name(id=carry, ctx=ast.Load()),
        )
        import copy

        cfn = ast.FunctionDef(
            name=cname, args=_one_arg(carry),
            body=[unpack, ast.Return(value=_transform_test(test))],
            decorator_list=[],
        )
        bfn = ast.FunctionDef(
            name=bname, args=_one_arg(carry),
            body=[copy.deepcopy(unpack)] + node.body + [
                ast.Return(value=_name_tuple(names, ast.Load))],
            decorator_list=[],
        )
        call = ast.Assign(
            targets=[_name_tuple(names, ast.Store)],
            value=ast.Call(
                func=_jst_attr("convert_while"),
                args=[ast.Name(id=cname, ctx=ast.Load()),
                      ast.Name(id=bname, ctx=ast.Load()),
                      _grab_expr(names)],
                keywords=[ast.keyword(
                    arg="names",
                    value=ast.Tuple(
                        elts=[ast.Constant(value=n) for n in names],
                        ctx=ast.Load()))],
            ),
        )
        # a while/else's else suite runs unless the loop broke; the
        # else suite was detached before generic_visit, so convert it
        # here (visit may return a list per statement)
        def _flat_visit(stmts):
            out = []
            for s in stmts:
                r = self.visit(s)
                out.extend(r if isinstance(r, list) else [r])
            return out

        if orelse and uses_brk:
            gate = ast.If(
                test=ast.UnaryOp(op=ast.Not(),
                                 operand=ast.Name(id=brk, ctx=ast.Load())),
                body=orelse, orelse=[])
            ast.fix_missing_locations(gate)
            lowered_gate = self.visit_If(gate)
            orelse = (lowered_gate if isinstance(lowered_gate, list)
                      else [lowered_gate])
        elif orelse:
            orelse = _flat_visit(orelse)
        return [cfn, bfn] + pre + [call] + orelse

    def visit_For(self, node):
        """Lower `for target in ITER:` to the while machinery through a
        sequence protocol (reference loop_transformer.py:115 visit_For):

            seq = _jst.to_seq(ITER)        # range() -> _jst.to_seq_range
            n = _jst.seq_len(seq)
            i = 0
            target = _jst.seq_template(seq, n)
            while i < n:
                target = _jst.seq_get(seq, i)
                i = i + 1        # BEFORE the body: continue must not skip it
                <body>

        Supports range(...) with traced bounds, tensor iteration (dim
        0), python lists (unrolled), and enumerate(...) over any of
        those. python-vs-lax.while_loop dispatch happens at runtime in
        convert_while."""
        import copy

        k = self._uid()
        seq_n, n_n, i_n = f"_jst_seq_{k}", f"_jst_n_{k}", f"_jst_it_{k}"
        iter_expr, target = node.iter, node.target

        enum_start = None
        if (isinstance(iter_expr, ast.Call)
                and isinstance(iter_expr.func, ast.Name)
                and iter_expr.func.id == "enumerate"):
            if not (isinstance(target, ast.Tuple) and len(target.elts) == 2):
                raise NotImplementedError(
                    "to_static: `for ... in enumerate(...)` needs a "
                    "2-name target (idx, item)")
            enum_start = ast.Constant(value=0)
            if len(iter_expr.args) > 1:
                enum_start = iter_expr.args[1]
            for kw in iter_expr.keywords:
                if kw.arg == "start":
                    enum_start = kw.value
            inner_iter = iter_expr.args[0]
            idx_target, item_target = target.elts[0], target.elts[1]
        else:
            inner_iter = iter_expr

        if (isinstance(inner_iter, ast.Call)
                and isinstance(inner_iter.func, ast.Name)
                and inner_iter.func.id == "range"):
            seq_value = ast.Call(func=_jst_attr("to_seq_range"),
                                 args=list(inner_iter.args), keywords=[])
        else:
            seq_value = ast.Call(func=_jst_attr("to_seq"),
                                 args=[inner_iter], keywords=[])

        def assign(tgt, value):
            return ast.Assign(targets=[tgt], value=value)

        def name(n, ctx=ast.Load):
            return ast.Name(id=n, ctx=ctx())

        get_call = ast.Call(func=_jst_attr("seq_get"),
                            args=[name(seq_n), name(i_n)], keywords=[])
        if enum_start is not None:
            head = [
                assign(copy.deepcopy(idx_target),
                       ast.BinOp(left=name(i_n), op=ast.Add(),
                                 right=enum_start)),
                assign(copy.deepcopy(item_target), get_call),
            ]
            template_tgts = [
                assign(copy.deepcopy(idx_target), ast.Constant(value=0)),
                assign(copy.deepcopy(item_target),
                       ast.Call(func=_jst_attr("seq_template"),
                                args=[name(seq_n), name(n_n)], keywords=[])),
            ]
        else:
            head = [assign(copy.deepcopy(target), get_call)]
            template_tgts = [
                assign(copy.deepcopy(target),
                       ast.Call(func=_jst_attr("seq_template"),
                                args=[name(seq_n), name(n_n)], keywords=[])),
            ]
        head.append(assign(name(i_n, ast.Store),
                           ast.BinOp(left=name(i_n), op=ast.Add(),
                                     right=ast.Constant(value=1))))
        pre = [
            assign(name(seq_n, ast.Store), seq_value),
            assign(name(n_n, ast.Store),
                   ast.Call(func=_jst_attr("seq_len"), args=[name(seq_n)],
                            keywords=[])),
            assign(name(i_n, ast.Store), ast.Constant(value=0)),
        ] + template_tgts
        new_while = ast.While(
            test=ast.Compare(left=name(i_n), ops=[ast.Lt()],
                             comparators=[name(n_n)]),
            body=head + list(node.body),
            orelse=list(node.orelse),
        )
        lowered = self.visit_While(new_while)
        return pre + (lowered if isinstance(lowered, list) else [lowered])

    # NOTE: and/or/not are rewritten ONLY inside if/while TESTS
    # (_transform_test below). A value-position boolop like
    # `cfg = opts or {}` keeps python's value-returning semantics.


class _TestExprTransformer(ast.NodeTransformer):
    """Rewrites and/or/not within a condition expression, preserving
    short-circuit for concrete values via lambdas."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        out = node.values[0]
        for v in node.values[1:]:
            out = ast.Call(
                func=_jst_attr(fn),
                args=[out, ast.Lambda(args=_empty_args(), body=v)],
                keywords=[],
            )
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(func=_jst_attr("convert_logical_not"),
                            args=[node.operand], keywords=[])
        return node

    def visit_Lambda(self, node):
        return node  # don't descend into nested value expressions


def _transform_test(test):
    return ast.fix_missing_locations(_TestExprTransformer().visit(test))


def _empty_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                         kw_defaults=[], kwarg=None, defaults=[])


def _one_arg(name):
    return ast.arguments(posonlyargs=[], args=[ast.arg(arg=name)], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None, defaults=[])


def _jst_attr(name):
    return ast.Attribute(value=ast.Name(id="_jst", ctx=ast.Load()),
                         attr=name, ctx=ast.Load())


def _grab_expr(names):
    """`_jst.grab(locals(), [names])` — tolerates names not yet bound
    (assigned for the first time inside the converted block)."""
    return ast.Call(
        func=_jst_attr("grab"),
        args=[
            ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                     args=[], keywords=[]),
            ast.List(elts=[ast.Constant(value=n) for n in names],
                     ctx=ast.Load()),
        ],
        keywords=[],
    )


_CACHE = {}

_RV, _DONE = "_jst_ret_val", "_jst_done"


def finalize_ret(rv, done):
    """Final-return hook for functions whose body can FALL OFF THE END
    while other paths return a value: python semantics say the
    fall-through path returns None. Eagerly the done flag is concrete
    and we honor that; under trace a None-or-value return cannot exist,
    so fail loudly instead of silently returning the zeros
    substitute."""
    if _is_traced(done):
        raise NotImplementedError(
            "to_static: this function returns a value on some paths and "
            "falls through (implicit None) on others — that mix is not "
            "jittable; add an explicit return at the end"
        )
    import numpy as np

    return rv if bool(np.asarray(_unwrap(done)).reshape(())) else None


def _guarantees_return(stmts):
    """True when every path through the suite ends in return/raise."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, (ast.Return, ast.Raise)):
        return True
    if isinstance(last, ast.If):
        return (_guarantees_return(last.body)
                and _guarantees_return(last.orelse))
    return False


def _lower_returns(stmts):
    """Rewrite `return` inside if/else AND inside while/for into
    done-flag + value carries (the reference's return_transformer.py):
    a return in a loop becomes RV/done assignment + `break` (the
    break/continue lowering then turns that into loop exit), and
    statements after the loop are gated on the done flag. After this
    pass the only `return` left in the suite is a trailing top-level
    one. Returns (new_stmts, had_early_return)."""
    out, early = [], False
    for idx, st in enumerate(stmts):
        rest = stmts[idx + 1:]
        if isinstance(st, ast.Return):
            val = st.value if st.value is not None else ast.Constant(value=None)
            out.append(ast.Assign(
                targets=[ast.Name(id=_RV, ctx=ast.Store())], value=val))
            out.append(ast.Assign(
                targets=[ast.Name(id=_DONE, ctx=ast.Store())],
                value=ast.Constant(value=True)))
            return out, True  # anything after is dead code
        if isinstance(st, (ast.While, ast.For)):
            nb, ne = _lower_returns_in_loop(st.body)
            if ne:
                st.body = nb
                out.append(st)
                # while/else: a return exits immediately — the break
                # that implements it also (correctly) skips the else
                new_rest, _ = _lower_returns(rest)
                if new_rest:
                    out.append(ast.If(
                        test=ast.UnaryOp(
                            op=ast.Not(),
                            operand=ast.Name(id=_DONE, ctx=ast.Load())),
                        body=new_rest, orelse=[]))
                return out, True
            out.append(st)
            continue
        if isinstance(st, ast.If):
            tb, te = _lower_returns(st.body)
            fb, fe = _lower_returns(st.orelse)
            st.body = tb or [ast.Pass()]
            st.orelse = fb
            out.append(st)
            if te or fe:
                new_rest, _ = _lower_returns(rest)
                if new_rest:
                    out.append(ast.If(
                        test=ast.UnaryOp(
                            op=ast.Not(),
                            operand=ast.Name(id=_DONE, ctx=ast.Load())),
                        body=new_rest, orelse=[]))
                return out, True
            continue
        out.append(st)
    return out, early


def _lower_returns_in_loop(stmts):
    """Lower `return` within a loop body: RV/done assignment followed
    by `break`. After an If that may have returned, `if done: break`
    exits this loop level; a nested loop that returned gets the same
    gate right after it so the break propagates outward level by
    level. Returns (new_stmts, had_return)."""
    out, had = [], False
    done_break = lambda: ast.If(
        test=ast.Name(id=_DONE, ctx=ast.Load()),
        body=[ast.Break()], orelse=[])
    for st in stmts:
        if isinstance(st, ast.Return):
            val = st.value if st.value is not None else ast.Constant(value=None)
            out.append(ast.Assign(
                targets=[ast.Name(id=_RV, ctx=ast.Store())], value=val))
            out.append(ast.Assign(
                targets=[ast.Name(id=_DONE, ctx=ast.Store())],
                value=ast.Constant(value=True)))
            out.append(ast.Break())
            return out, True  # rest of the suite is dead code
        if isinstance(st, (ast.While, ast.For)):
            nb, ne = _lower_returns_in_loop(st.body)
            if ne:
                st.body = nb
                out.append(st)
                out.append(done_break())
                had = True
                continue
            out.append(st)
            continue
        if isinstance(st, ast.If):
            tb, te = _lower_returns_in_loop(st.body)
            fb, fe = _lower_returns_in_loop(st.orelse)
            st.body = tb or [ast.Pass()]
            st.orelse = fb
            out.append(st)
            if te or fe:
                out.append(done_break())
                had = True
            continue
        out.append(st)
    return out, had


def _apply_return_transform(fdef):
    guaranteed = _guarantees_return(fdef.body)
    body, had = _lower_returns(fdef.body)
    if not had:
        return
    inits = [
        ast.Assign(targets=[ast.Name(id=_DONE, ctx=ast.Store())],
                   value=ast.Constant(value=False)),
        ast.Assign(targets=[ast.Name(id=_RV, ctx=ast.Store())],
                   value=ast.Constant(value=None)),
    ]
    if guaranteed:
        final = ast.Return(value=ast.Name(id=_RV, ctx=ast.Load()))
    else:
        # fall-off-the-end is reachable: route through finalize_ret so
        # eager returns None on that path and jit fails loudly
        final = ast.Return(value=ast.Call(
            func=_jst_attr("finalize_ret"),
            args=[ast.Name(id=_RV, ctx=ast.Load()),
                  ast.Name(id=_DONE, ctx=ast.Load())],
            keywords=[]))
    fdef.body = inits + body + [final]


def convert_to_static(fn):
    """Source-to-source conversion (reference cache_program.py caches
    by function; same here)."""
    if fn in _CACHE:
        return _CACHE[fn]
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    fdef = tree.body[0]
    fdef.decorator_list = []  # drop @declarative/@to_static
    _apply_return_transform(fdef)
    new_tree = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)
    import sys

    # exec into the LIVE module globals (not a copy) so forward
    # references and monkeypatched globals keep working; only _jst is
    # injected (collision-checked). Closures exec into a COPY with the
    # free variables re-read from the cells at every call (they may be
    # rebound between calls).
    ns = dict(fn.__globals__) if fn.__closure__ else fn.__globals__
    me = sys.modules[__name__]
    if "_jst" in ns and ns["_jst"] is not me:
        raise RuntimeError(
            "to_static: the module already binds the name '_jst'"
        )
    ns["_jst"] = me
    converted_name = fdef.name
    fdef.name = f"_jst_converted_{fn.__name__}"
    ast.fix_missing_locations(new_tree)
    code = compile(new_tree, filename=f"<to_static:{fn.__name__}>", mode="exec")
    exec(code, ns)
    inner = ns.pop(fdef.name)
    inner.__name__ = converted_name
    if fn.__closure__:
        free, cells = fn.__code__.co_freevars, fn.__closure__

        @functools.wraps(fn)
        def converted(*args, **kwargs):
            for n, c in zip(free, cells):
                ns[n] = c.cell_contents
            return inner(*args, **kwargs)
    else:
        converted = inner
    _CACHE[fn] = converted
    return converted


def declarative(fn=None):
    """@declarative — the reference dygraph_to_static entry point."""
    def deco(f):
        converted = convert_to_static(f)

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            return converted(*args, **kwargs)

        wrapper._converted = converted
        wrapper._original = f
        return wrapper

    return deco(fn) if fn is not None else deco

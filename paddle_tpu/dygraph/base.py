"""Dygraph core: VarBase + eager tracer + taped autograd.

Reference: imperative/tracer.cc:87 (TraceOp — create op, run kernel,
record grad node), imperative/layer.h:61 (VarBase),
imperative/engine.cc (BasicEngine reverse walk),
imperative/gradient_accumulator.cc (multi-consumer grad sum).
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core import dygraph as _mode
from ..core.registry import LoweringContext, get_op_def

guard = _mode.dygraph_guard
in_dygraph_mode = _mode.in_dygraph_mode


def enabled():
    return _mode.in_dygraph_mode()


def enable_dygraph(place=None):
    _mode._in_dygraph = True


def disable_dygraph():
    _mode._in_dygraph = False


_no_grad = False


@contextlib.contextmanager
def no_grad():
    global _no_grad
    prev = _no_grad
    _no_grad = True
    try:
        yield
    finally:
        _no_grad = prev


class _TapeEntry:
    __slots__ = ("op", "opdef", "in_vars", "out_vars", "key")

    def __init__(self, op, opdef, in_vars, out_vars, key=None):
        self.op = op
        self.opdef = opdef
        self.in_vars = in_vars  # slot -> [VarBase]
        self.out_vars = out_vars  # slot -> [VarBase]
        self.key = key  # PRNG key used by the eager forward (replayed in vjp)


class _PseudoOp:
    __slots__ = ("type", "attrs", "inputs", "outputs")

    def __init__(self, type, attrs):
        self.type = type
        self.attrs = attrs
        self.inputs = {}
        self.outputs = {}


class VarBase:
    """Eager tensor: a jax array + autograd metadata."""

    _name_counter = 0

    def __init__(self, value, name=None, stop_gradient=False, persistable=False):
        self.value = jnp.asarray(value)
        VarBase._name_counter += 1
        self.name = name or f"eager_tmp_{VarBase._name_counter}"
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.grad: Optional[jax.Array] = None
        self._producer: Optional[_TapeEntry] = None

    # -- numpy / info ---------------------------------------------------------
    def numpy(self):
        return np.asarray(self.value)

    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return str(self.value.dtype)

    def detach(self):
        return VarBase(self.value, stop_gradient=True)

    def clear_gradient(self):
        self.grad = None

    @property
    def gradient(self):
        return None if self.grad is None else np.asarray(self.grad)

    def set_value(self, v):
        self.value = jnp.asarray(v if not isinstance(v, VarBase) else v.value)

    def astype(self, dtype):
        return _trace("cast", {"X": [self]}, ["Out"], {"out_dtype": str(dtype)})[0]

    def __repr__(self):
        return f"VarBase(name={self.name}, shape={self.shape}, dtype={self.dtype})"

    # -- autograd -------------------------------------------------------------
    def backward(self, retain_graph=False):
        run_backward(self)

    # -- operator sugar -------------------------------------------------------
    def _ew(self, other, op, reverse=False):
        if not isinstance(other, VarBase):
            other = VarBase(jnp.asarray(other, self.value.dtype), stop_gradient=True)
        a, b = (other, self) if reverse else (self, other)
        return _trace(op, {"X": [a], "Y": [b]}, ["Out"], {"axis": -1})[0]

    def __add__(self, o):
        return self._ew(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._ew(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._ew(o, "elementwise_sub", reverse=True)

    def __mul__(self, o):
        return self._ew(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._ew(o, "elementwise_div")

    def __neg__(self):
        return _trace("scale", {"X": [self]}, ["Out"], {"scale": -1.0})[0]

    def __getitem__(self, idx):
        # route simple indexing through the slice/squeeze ops so the
        # tape records it and gradients flow (a detached copy here
        # would silently cut autograd)
        import builtins

        items = idx if isinstance(idx, tuple) else (idx,)
        axes, starts, ends, squeeze_axes = [], [], [], []
        simple = True
        for i, it in enumerate(items):
            if isinstance(it, int):
                axes.append(i)
                starts.append(it)
                ends.append(it + 1)
                squeeze_axes.append(i)
            elif isinstance(it, builtins.slice) and it.step in (None, 1):
                if it.start is None and it.stop is None:
                    continue
                axes.append(i)
                starts.append(it.start or 0)
                ends.append(it.stop if it.stop is not None else 10**9)
            else:
                simple = False
                break
        if not simple:
            return VarBase(self.value[idx], stop_gradient=True)
        out = self
        if axes:
            (out,) = _trace(
                "slice", {"Input": [out]}, ["Out"],
                {"axes": axes, "starts": starts, "ends": ends},
            )
        if squeeze_axes:
            out, _ = _trace(
                "squeeze2", {"X": [out]}, ["Out", "XShape"], {"axes": squeeze_axes}
            )
        return out


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, VarBase):
        return value
    arr = np.asarray(value)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    if arr.dtype == np.int64 and not jax.config.jax_enable_x64:
        arr = arr.astype(np.int32)
    return VarBase(arr, name=name)


_eager_rng_counter = 0


def _eager_ctx():
    global _eager_rng_counter
    _eager_rng_counter += 1
    return LoweringContext(step_key=jax.random.PRNGKey(_eager_rng_counter))


def _trace(op_type: str, ins: Dict[str, List[VarBase]], out_slots: List[str],
           attrs: Dict[str, Any], n_outs: Optional[Dict[str, int]] = None):
    """Eager TraceOp: run lowering now, record tape entry (reference
    imperative/tracer.cc:87-110)."""
    opdef = get_op_def(op_type)
    pseudo = _PseudoOp(op_type, dict(attrs))
    raw_ins = {slot: [v.value for v in vs] for slot, vs in ins.items()}
    pseudo.inputs = {slot: [v.name for v in vs] for slot, vs in ins.items()}
    ctx = _eager_ctx()
    outs = opdef.lower(ctx, pseudo, raw_ins)
    out_vars: Dict[str, List[VarBase]] = {}
    flat: List[VarBase] = []
    stop = _no_grad or all(
        v.stop_gradient for vs in ins.values() for v in vs
    ) or opdef.stop_gradient
    for slot in out_slots:
        vals = outs.get(slot, [])
        vbs = [VarBase(v, stop_gradient=stop) for v in vals]
        out_vars[slot] = vbs
        flat.extend(vbs)
    if not stop:
        entry = _TapeEntry(pseudo, opdef, dict(ins), out_vars, key=ctx.step_key)
        for vb in flat:
            vb._producer = entry
    return flat


def run_backward(root: VarBase):
    """BasicEngine: reverse-topological walk over producer entries,
    applying per-op vjp and accumulating grads
    (imperative/engine.cc + gradient_accumulator.cc)."""
    if root._producer is None and root.stop_gradient:
        raise RuntimeError("backward() on a leaf with stop_gradient=True")
    root.grad = jnp.ones_like(root.value)

    # topo-order entries reachable from root (iterative DFS — deep
    # eager graphs would blow Python's recursion limit)
    order: List[_TapeEntry] = []
    seen = set()
    if root._producer is not None:
        stack = [(root._producer, False)]
        while stack:
            entry, expanded = stack.pop()
            if entry is None:
                continue
            if expanded:
                order.append(entry)
                continue
            if id(entry) in seen:
                continue
            seen.add(id(entry))
            stack.append((entry, True))
            for vs in entry.in_vars.values():
                for v in vs:
                    if v._producer is not None and id(v._producer) not in seen:
                        stack.append((v._producer, False))

    for entry in reversed(order):
        op, opdef = entry.op, entry.opdef
        # cotangents for outputs
        out_grads = {}
        any_g = False
        for slot, vbs in entry.out_vars.items():
            gs = []
            for vb in vbs:
                if vb.grad is not None:
                    gs.append(vb.grad)
                    any_g = True
                else:
                    gs.append(None)
            out_grads[slot] = gs
        if not any_g:
            continue

        diff_ins = {}
        aux_ins = {}
        for slot, vbs in entry.in_vars.items():
            vals = [v.value for v in vbs]
            if slot in opdef.no_grad_slots or all(v.stop_gradient for v in vbs):
                aux_ins[slot] = vals
            else:
                diff_ins[slot] = vals

        if not diff_ins:
            continue

        ctx = LoweringContext(step_key=entry.key)

        def fwd(d_ins, _op=op, _opdef=opdef, _aux=aux_ins):
            all_ins = {**_aux, **d_ins}
            outs = _opdef.lower(ctx, _op, all_ins)
            return {s: list(outs.get(s, [])) for s in _opdef.output_slots}

        primals, vjp_fn = jax.vjp(fwd, diff_ins)
        cots = {}
        for s in opdef.output_slots:
            prim_list = primals.get(s, [])
            gs = out_grads.get(s, [])
            cots[s] = [
                (gs[i].astype(p.dtype) if i < len(gs) and gs[i] is not None else jnp.zeros_like(p))
                for i, p in enumerate(prim_list)
            ]
        (grads,) = vjp_fn(cots)
        for slot, gvals in grads.items():
            for vb, g in zip(entry.in_vars[slot], gvals):
                if vb.stop_gradient:
                    continue
                vb.grad = g if vb.grad is None else vb.grad + g
